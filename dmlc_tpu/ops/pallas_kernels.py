"""Pallas TPU kernels for the inference hot path.

Two memory-bound steps surround the model's matmuls: input normalization
(uint8 -> scaled float, the replacement for the reference's CPU-side
``imagenet::load_image_and_resize`` normalize, services.rs:492) and the
softmax/top-1 readout (services.rs:493-494). XLA fuses both well; these
kernels exist to (a) pin the fusion — one HBM read, one write, no
intermediate f32 image buffer — and (b) serve the standalone preprocessing
path where there is no adjacent op to fuse into.

Layout notes (per /opt/skills/guides/pallas_guide.md): images are viewed as
[rows, W*C] 2-D blocks so the lane dimension is dense; normalization is
expressed as one fused multiply-add ``u8 * scale + bias`` with per-column
vectors precomputed on the host (scale = 1/(255*std), bias = -mean/std).
Off-TPU the kernels run in interpreter mode so tests stay hermetic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# uint8 -> normalized float (NHWC)
# ---------------------------------------------------------------------------


def _normalize_kernel(u8_ref, scale_ref, bias_ref, out_ref):
    # Mosaic has no direct u8->f32 cast; widen through i32 (free on the VPU).
    x = u8_ref[:].astype(jnp.int32).astype(jnp.float32)
    out_ref[:] = (x * scale_ref[:] + bias_ref[:]).astype(out_ref.dtype)


@partial(jax.jit, static_argnames=("out_dtype",))
def _normalize_call(u8_2d, scale_row, bias_row, out_dtype):
    rows, cols = u8_2d.shape
    block_rows = min(rows, 512)
    grid = (pl.cdiv(rows, block_rows),)
    return pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, cols), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, cols), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(u8_2d, scale_row, bias_row)


def normalize_u8(batch_u8, mean, std, out_dtype=jnp.float32):
    """uint8 [N, H, W, C] -> ((x/255) - mean) / std as ``out_dtype``.

    One fused pass: each byte is read once, multiplied and shifted by
    per-channel constants, and written once — no intermediate f32 image.
    """
    n, h, w, c = batch_u8.shape
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    scale = np.tile(1.0 / (255.0 * std), w)[None, :]   # [1, W*C]
    bias = np.tile(-mean / std, w)[None, :]            # [1, W*C]
    u8_2d = batch_u8.reshape(n * h, w * c)
    out = _normalize_call(u8_2d, jnp.asarray(scale), jnp.asarray(bias), out_dtype)
    return out.reshape(n, h, w, c)


# ---------------------------------------------------------------------------
# fused softmax + top-1 readout
# ---------------------------------------------------------------------------


def _softmax_top1_kernel(logits_ref, idx_ref, prob_ref):
    x = logits_ref[:].astype(jnp.float32)              # [B, C]
    m = jnp.max(x, axis=1, keepdims=True)              # [B, 1]
    z = jnp.sum(jnp.exp(x - m), axis=1, keepdims=True)
    # softmax peak = exp(m - m) / z = 1/z; argmax is dtype-stable.
    idx_ref[:] = jnp.argmax(x, axis=1, keepdims=True).astype(jnp.int32)
    prob_ref[:] = 1.0 / z


@jax.jit
def softmax_top1(logits):
    """[B, C] logits -> (top-1 index int32 [B], top-1 prob float32 [B]) in a
    single pass — the full softmax matrix is never materialized in HBM."""
    b, c = logits.shape
    block_b = min(b, 256)
    idx, prob = pl.pallas_call(
        _softmax_top1_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ),
        grid=(pl.cdiv(b, block_b),),
        in_specs=[
            pl.BlockSpec((block_b, c), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=(
            pl.BlockSpec((block_b, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(logits)
    return idx[:, 0], prob[:, 0]
