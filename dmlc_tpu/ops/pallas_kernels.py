"""Pallas TPU kernels for the inference hot path.

Two memory-bound steps surround the model's matmuls: input normalization
(uint8 -> scaled float, the replacement for the reference's CPU-side
``imagenet::load_image_and_resize`` normalize, services.rs:492) and the
softmax/top-1 readout (services.rs:493-494). XLA fuses both well; these
kernels exist to (a) pin the fusion — one HBM read, one write, no
intermediate f32 image buffer — and (b) serve the standalone preprocessing
path where there is no adjacent op to fuse into.

Layout notes (per /opt/skills/guides/pallas_guide.md): images are viewed as
[rows, W*C] 2-D blocks so the lane dimension is dense; normalization is
expressed as one fused multiply-add ``u8 * scale + bias`` with per-column
vectors precomputed on the host (scale = 1/(255*std), bias = -mean/std).
Off-TPU the kernels run in interpreter mode so tests stay hermetic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _sds(shape, dtype, like):
    """ShapeDtypeStruct inheriting ``like``'s varying-manual-axes set on
    jax versions that track one (jax.typeof, >= 0.7); the plain struct on
    older jax, whose ShapeDtypeStruct has no vma parameter."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(
        shape, dtype, vma=getattr(typeof(like), "vma", frozenset())
    )


# ---------------------------------------------------------------------------
# uint8 -> normalized float (NHWC)
# ---------------------------------------------------------------------------


def _normalize_kernel(u8_ref, scale_ref, bias_ref, out_ref):
    # Mosaic has no direct u8->f32 cast; widen through i32 (free on the VPU).
    x = u8_ref[:].astype(jnp.int32).astype(jnp.float32)
    out_ref[:] = (x * scale_ref[:] + bias_ref[:]).astype(out_ref.dtype)


@partial(jax.jit, static_argnames=("out_dtype",))
def _normalize_call(u8_2d, scale_row, bias_row, out_dtype):
    rows, cols = u8_2d.shape
    block_rows = min(rows, 512)
    grid = (pl.cdiv(rows, block_rows),)
    return pl.pallas_call(
        _normalize_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, cols), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, cols), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cols), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, cols), lambda i: (i, 0), memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(u8_2d, scale_row, bias_row)


def normalize_u8(batch_u8, mean, std, out_dtype=jnp.float32):
    """uint8 [N, H, W, C] -> ((x/255) - mean) / std as ``out_dtype``.

    One fused pass: each byte is read once, multiplied and shifted by
    per-channel constants, and written once — no intermediate f32 image.
    """
    n, h, w, c = batch_u8.shape
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    scale = np.tile(1.0 / (255.0 * std), w)[None, :]   # [1, W*C]
    bias = np.tile(-mean / std, w)[None, :]            # [1, W*C]
    u8_2d = batch_u8.reshape(n * h, w * c)
    # dmlc-lint: disable=A6 -- out_dtype static is bounded by the dtypes the pipeline feeds it (f32, bf16), not by data
    out = _normalize_call(u8_2d, jnp.asarray(scale), jnp.asarray(bias), out_dtype)
    return out.reshape(n, h, w, c)


# ---------------------------------------------------------------------------
# fused softmax + top-1 readout
# ---------------------------------------------------------------------------


def _softmax_top1_kernel(logits_ref, idx_ref, prob_ref):
    x = logits_ref[:].astype(jnp.float32)              # [B, C]
    m = jnp.max(x, axis=1, keepdims=True)              # [B, 1]
    z = jnp.sum(jnp.exp(x - m), axis=1, keepdims=True)
    # softmax peak = exp(m - m) / z = 1/z; argmax is dtype-stable.
    idx_ref[:] = jnp.argmax(x, axis=1, keepdims=True).astype(jnp.int32)
    prob_ref[:] = 1.0 / z


# ---------------------------------------------------------------------------
# flash attention (the hot op of the transformer families) — training-grade:
# O(S)-memory forward AND backward, with the [S, S] score matrix never
# materialized in either direction.
# ---------------------------------------------------------------------------

# K/V bytes per (batch, head) above which the forward streams K/V blocks
# from HBM instead of holding them VMEM-resident. Resident is faster (K/V
# read once per batch-head instead of once per q block) and is used
# whenever it fits; 4 MiB leaves room for q/o blocks, the f32 score block,
# and Mosaic's double buffering in ~16 MiB of VMEM (bf16 Dh=128: S=8192
# resident — matching the measured compile ceiling — S=16384+ streamed).
_RESIDENT_KV_BYTES = 4 * 1024 * 1024


# Longest sequence allowed to run as ONE full-S block (the fallback for
# odd/prime S with no Mosaic-legal sub-block, and for explicit blk >= S):
# the kernel materializes a [blk_q, blk_k] f32 score tile in VMEM, so a
# full-S block costs S^2 * 4 bytes — 4 MiB at 1024, which together with
# the resident operands still fits a ~16 MiB VMEM core. Past this, pad the
# sequence to a multiple of 8 instead.
_FULL_BLOCK_CAP = 1024


def _auto_block(s: int, requested: int | None, default: int) -> int:
    """Largest Mosaic-LEGAL block for a sequence of length ``s``: a divisor
    of s that is also a multiple of 8 (the TPU lowering requires block dims
    divisible by 8 unless equal to the array dim), not exceeding the
    requested size — S=192 with 128-blocks runs at blk=96 (the largest
    divisor of 192 that is a multiple of 8 and <= 128). Sequences with
    no such divisor (odd S, primes) fall back to ONE full-S block — always
    layout-legal, but its [S, S] score tile must fit VMEM, hence capped at
    _FULL_BLOCK_CAP. An explicit request >= s for a sequence past that cap
    searches for a smaller divisor instead of demanding padding the
    sequence does not need."""
    blk = min(requested if requested is not None else default, s)
    if blk >= s and s > _FULL_BLOCK_CAP:
        blk = min(default, s - 8)
    if blk < s:
        for d in range(blk - blk % 8, 7, -8):
            if s % d == 0:
                return d
    if s <= _FULL_BLOCK_CAP:
        return s
    raise ValueError(
        f"sequence {s} has no block divisor that is a multiple of 8 and is "
        f"too long for a single full-sequence block (> {_FULL_BLOCK_CAP}): "
        "pad the sequence"
    )


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, blk_k: int, causal: bool, scale: float
):
    """Resident-K/V forward: one (batch*head, q-block) cell, online-softmax
    over k blocks sliced from VMEM.

    q_ref: [1, blk_q, Dh]; k_ref/v_ref: [1, S, Dh] (VMEM-resident K/V);
    o_ref like q; lse_ref: [1, blk_q] log-sum-exp, the backward's residual.
    The [blk_q, S] score matrix is never materialized: each k block's scores
    live only for one loop step, folded into the running (m, l, acc).
    """
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                    # [blk_q, Dh]
    blk_q = q.shape[0]
    s_total = k_ref.shape[1]
    n_k = s_total // blk_k
    q_pos = iq * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)

    def body(j, carry):
        m, l, acc = carry
        # Slice the REF (Mosaic lowers ref dynamic slices; array-level
        # dynamic_slice inside the kernel does not lower).
        k_blk = k_ref[0, pl.ds(j * blk_k, blk_k), :]
        v_blk = v_ref[0, pl.ds(j * blk_k, blk_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                        # [blk_q, blk_k]
        if causal:
            k_pos = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        # Fully-masked-so-far rows keep m == -inf; their correction is 1.
        corr = jnp.where(jnp.isneginf(m_new), 1.0, jnp.exp(m - m_new))
        p = jnp.exp(s - m_new)
        p = jnp.where(jnp.isneginf(s), 0.0, p)
        l_new = l * corr + p.sum(axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((blk_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    acc0 = jnp.zeros_like(q)
    if causal:
        # Blocks entirely past the causal frontier are all-masked: skip
        # them instead of computing-then-discarding (~2x for long S).
        n_loop = jnp.minimum(n_k, ((iq + 1) * blk_q + blk_k - 1) // blk_k)
    else:
        n_loop = n_k
    m, l, acc = jax.lax.fori_loop(0, n_loop, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)  # [blk_q, 1] — lse is carried [bh, S, 1]


def _flash_fwd_stream_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, causal: bool, scale: float,
):
    """Streamed-K/V forward: grid (bh, q-block, k-block), K/V blocks fetched
    from HBM per cell, online-softmax state carried across the (sequential)
    k dimension in VMEM scratch. Lifts the resident path's S cap: working
    set is O(blk_q * blk_k) regardless of S."""
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)
    blk_q, blk_k = q_ref.shape[1], k_ref.shape[1]

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    def compute():
        q = q_ref[0].astype(jnp.float32) * scale
        s = jax.lax.dot_general(
            q, k_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = iq * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = ik * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
        m = m_scr[:]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        corr = jnp.where(jnp.isneginf(m_new), 1.0, jnp.exp(m - m_new))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(jnp.isneginf(s), 0.0, p)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new

    if causal:
        # Blocks wholly past the causal frontier contribute nothing: their
        # compute is predicated off (the block fetch still happens — the
        # grid is static — but the MXU work, the 2x term, is skipped).
        pl.when(ik * blk_k < (iq + 1) * blk_q)(compute)
    else:
        compute()

    @pl.when(ik == n_k - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l_safe)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, causal: bool, scale: float,
):
    """dQ: grid (bh, q-block, k-block); for each q block, accumulate
    dq = scale * sum_k ds @ K over streamed k blocks (FlashAttention-2
    form: p recomputed from the forward's lse, no [S, S] buffer)."""
    iq, ik = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)
    blk_q, blk_k = q_ref.shape[1], k_ref.shape[1]

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    def compute():
        qs = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qs, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                       # [blk_q, blk_k]
        if causal:
            q_pos = iq * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = ik * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
        p = jnp.exp(s - lse_ref[0])                             # lse: [blk_q, 1]
        if causal:
            # A fully-masked row has lse == -inf; exp(-inf - -inf) is nan.
            p = jnp.where(jnp.isneginf(s), 0.0, p)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # [blk_q, blk_k]
        ds = p * (dp - delta_ref[0])
        dq_scr[:] += jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        pl.when(ik * blk_k < (iq + 1) * blk_q)(compute)
    else:
        compute()

    @pl.when(ik == n_k - 1)
    def _finalize():
        dq_ref[0] = (dq_scr[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, causal: bool, scale: float,
):
    """dK/dV: grid (bh, k-block, q-block); for each k block, accumulate
    dv = sum_q P^T @ dO and dk = sum_q dS^T @ (scale * Q) over streamed
    q blocks."""
    ik, iq = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)
    blk_q, blk_k = q_ref.shape[1], k_ref.shape[1]

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    def compute():
        qs = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            qs, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                       # [blk_q, blk_k]
        if causal:
            q_pos = iq * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = ik * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
        p = jnp.exp(s - lse_ref[0])
        if causal:
            p = jnp.where(jnp.isneginf(s), 0.0, p)
        do = do_ref[0].astype(jnp.float32)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                                       # [blk_k, Dh]
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0])
        dk_scr[:] += jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                                       # [blk_k, Dh]

    if causal:
        # A k block only receives gradient from q blocks at or past it.
        pl.when((iq + 1) * blk_q > ik * blk_k)(compute)
    else:
        compute()

    @pl.when(iq == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _flash(causal, scale, blk_q, blk_k, q, k, v):
    return _flash_forward(causal, scale, blk_q, blk_k, q, k, v)[0]


def _flash_vjp_fwd(causal, scale, blk_q, blk_k, q, k, v):
    out, lse = _flash_forward(causal, scale, blk_q, blk_k, q, k, v)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, blk_q, blk_k, res, g):
    q, k, v, out, lse = res
    return _flash_backward(causal, scale, q, k, v, out, lse, g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False, scale: float | None = None,
                    blk_q: int | None = None, blk_k: int | None = None):
    """Blockwise (flash) attention: [B, H, S, Dh] q/k/v -> [B, H, S, Dh].

    Never materializes the [S, S] score matrix — per q block the working set
    is O(blk_q * blk_k) scores plus the online-softmax carries, so peak
    memory scales with S, not S^2 (the enabler for long single-device
    sequences; combine with ring/Ulysses SP for sequences past one chip).
    Measured on v5e vs XLA's dense attention (bf16, Dh=128, causal):
    parity at S=2048, 1.1-1.5x faster at S=8192 (artifact:
    bench_detail.json["flash"], re-measured every bench run).

    Design your models with Dh = 128 — the MXU lane width. The kernel
    accepts any Dh, but Dh=64 measured 2.6x slower than Dh=128 on
    identical flops (B=8, S=2048; half of every 128-lane tile idle), and
    an 8-layer LM's whole train step went from MFU 0.29 to 0.43 by
    switching 12 heads of 64 to 6 heads of 128
    (bench_detail.json["roofline_notes"]["lm_flash_train"]).

    Two forward schedules, chosen by K/V footprint (_RESIDENT_KV_BYTES):
    VMEM-resident K/V while it fits (K/V read from HBM once per batch-head),
    HBM-streamed K/V blocks past that (unbounded S — the old hard S=8192
    compile ceiling is gone; bigger default q blocks keep the streamed
    matmuls MXU-bound).

    Block sizes default per schedule and are shrunk to the largest
    Mosaic-legal divisor of S (a multiple of 8); lengths with no such
    divisor (odd S, primes) run as one full-S block up to
    _FULL_BLOCK_CAP and are rejected past it — pad the sequence instead.

    Differentiable with O(S) memory end-to-end: the forward saves only the
    per-row log-sum-exp, and the backward recomputes p blockwise in two
    kernels (dQ over streamed K, dK/dV over streamed Q — the
    FlashAttention-2 schedule), so schedule="flash" is training-grade at
    sequence lengths where the dense [S, S] recompute could never fit.
    Interpreter mode off-TPU keeps tests hermetic.
    """
    s, dh = q.shape[2], q.shape[3]
    resident = 2 * s * dh * q.dtype.itemsize <= _RESIDENT_KV_BYTES
    # Streamed cells refetch K/V per q block: blk_q sets the flops fetched
    # per byte, and 256 keeps the MXU (not HBM) the bottleneck.
    bq = _auto_block(s, blk_q, 128 if resident else 256)
    bk = _auto_block(s, blk_k, 128 if resident else 256)
    if scale is None:
        scale = dh**-0.5
    return _flash(causal, float(scale), bq, bk, q, k, v)


def flash_attention_with_lse(
    q, k, v, *, causal: bool = False, scale: float | None = None,
    blk_q: int | None = None, blk_k: int | None = None,
):
    """Forward-only blockwise attention returning ``(out, lse)`` with lse
    reshaped to ``[B, H, S, 1]`` — the composition primitive for ring /
    sequence-parallel schedules: partial results from different K/V blocks
    merge exactly via log-sum-exp weights, so the ring accumulator never
    materializes an [S_local, S_local] score matrix (VERDICT r3 weak #6).

    NOT differentiable on its own — the composed schedule supplies a custom
    VJP built on ``flash_attention_block_bwd`` (the per-block gradients are
    only meaningful against the GLOBAL lse/out, which the composition owns).
    """
    b, h, s, dh = q.shape
    resident = 2 * s * dh * q.dtype.itemsize <= _RESIDENT_KV_BYTES
    bq = _auto_block(s, blk_q, 128 if resident else 256)
    bk = _auto_block(s, blk_k, 128 if resident else 256)
    if scale is None:
        scale = dh**-0.5
    out, lse = _flash_forward(causal, float(scale), bq, bk, q, k, v)
    return out, lse.reshape(b, h, s, 1)


def flash_attention_block_bwd(
    q, k, v, out, lse, do, *, causal: bool = False, scale: float | None = None,
    delta=None,
):
    """Blockwise gradients of one (q, k-block) pair against the GLOBAL
    (out, lse): because p = exp(s - lse_global) and delta = rowsum(do*out)
    use the fully-merged forward results, the returned (dq, dk, dv) are
    exactly this block pair's contributions to the global gradients — the
    ring backward sums dq locally and rotates dk/dv home with their blocks.
    lse: [B, H, S, 1] as returned by flash_attention_with_lse. ``delta``
    ([B, H, S, 1]) is step-invariant across ring steps — pass it
    precomputed so the per-step call skips the full-tensor reduction."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, h, s, _ = q.shape
    return _flash_backward(
        causal, float(scale), q, k, v, out, lse.reshape(b * h, s, 1), do,
        delta=delta.reshape(b * h, s, 1) if delta is not None else None,
    )


def _flash_forward(causal, scale, blk_q, blk_k, q, k, v):
    b, h, s, dh = q.shape
    q3, k3, v3 = (x.reshape(b * h, s, dh) for x in (q, k, v))
    # Under shard_map (e.g. as Ulysses' per-device attention) the output
    # must declare which mesh axes it varies over — inherit q's.
    # lse rides as [bh, S, 1]: the trailing singleton keeps the Mosaic
    # block-shape rule happy ((1, blk_q, 1) has its last dim equal to the
    # array's) AND gives kernels the [blk_q, 1] column layout directly.
    out_shapes = (
        _sds((b * h, s, dh), q.dtype, q3),
        _sds((b * h, s, 1), jnp.float32, q3),  # lse
    )
    resident = 2 * s * dh * q.dtype.itemsize <= _RESIDENT_KV_BYTES
    if resident:
        out, lse = pl.pallas_call(
            partial(_flash_kernel, blk_k=blk_k, causal=causal, scale=scale),
            out_shape=out_shapes,
            grid=(b * h, s // blk_q),
            in_specs=[
                pl.BlockSpec((1, blk_q, dh), lambda bh, iq: (bh, iq, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, s, dh), lambda bh, iq: (bh, 0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, s, dh), lambda bh, iq: (bh, 0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec((1, blk_q, dh), lambda bh, iq: (bh, iq, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk_q, 1), lambda bh, iq: (bh, iq, 0), memory_space=pltpu.VMEM),
            ),
            interpret=_interpret(),
        )(q3, k3, v3)
    else:
        out, lse = pl.pallas_call(
            partial(_flash_fwd_stream_kernel, causal=causal, scale=scale),
            out_shape=out_shapes,
            grid=(b * h, s // blk_q, s // blk_k),
            in_specs=[
                pl.BlockSpec((1, blk_q, dh), lambda bh, iq, ik: (bh, iq, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk_k, dh), lambda bh, iq, ik: (bh, ik, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk_k, dh), lambda bh, iq, ik: (bh, ik, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec((1, blk_q, dh), lambda bh, iq, ik: (bh, iq, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, blk_q, 1), lambda bh, iq, ik: (bh, iq, 0), memory_space=pltpu.VMEM),
            ),
            scratch_shapes=[
                pltpu.VMEM((blk_q, 1), jnp.float32),
                pltpu.VMEM((blk_q, 1), jnp.float32),
                pltpu.VMEM((blk_q, dh), jnp.float32),
            ],
            interpret=_interpret(),
        )(q3, k3, v3)
    return out.reshape(b, h, s, dh), lse


def _flash_backward(causal, scale, q, k, v, out, lse, do, delta=None):
    """Blockwise gradients (FlashAttention-2): one pass for dQ, one for
    dK/dV, both streaming the non-resident operand — peak memory O(S)."""
    b, h, s, dh = q.shape
    bh = b * h
    q3, k3, v3, do3 = (x.reshape(bh, s, dh) for x in (q, k, v, do))
    o3 = out.reshape(bh, s, dh)
    if delta is None:
        # delta_i = dO_i . O_i, the softmax-jacobian row term; O(S) and fused
        # into the surrounding jit by XLA. [bh, S, 1] like lse.
        delta = jnp.sum(
            o3.astype(jnp.float32) * do3.astype(jnp.float32), axis=-1, keepdims=True
        )
    # Backward cells do ~3 matmuls per fetched block (vs the forward's 2),
    # so 256 blocks keep both kernels MXU-bound; shrink for short S.
    blk_q = _auto_block(s, None, 256)
    blk_k = _auto_block(s, None, 256)

    qspec = pl.BlockSpec((1, blk_q, dh), lambda bh, iq, ik: (bh, iq, 0), memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, blk_k, dh), lambda bh, iq, ik: (bh, ik, 0), memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, blk_q, 1), lambda bh, iq, ik: (bh, iq, 0), memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        partial(_flash_bwd_dq_kernel, causal=causal, scale=scale),
        out_shape=_sds((bh, s, dh), q.dtype, q3),
        grid=(bh, s // blk_q, s // blk_k),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((blk_q, dh), jnp.float32)],
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)

    # dK/dV grid: (bh, k-block, q-block) — q innermost so the scratch
    # accumulators belong to one k block at a time.
    qspec2 = pl.BlockSpec((1, blk_q, dh), lambda bh, ik, iq: (bh, iq, 0), memory_space=pltpu.VMEM)
    kspec2 = pl.BlockSpec((1, blk_k, dh), lambda bh, ik, iq: (bh, ik, 0), memory_space=pltpu.VMEM)
    rowspec2 = pl.BlockSpec((1, blk_q, 1), lambda bh, ik, iq: (bh, iq, 0), memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        partial(_flash_bwd_dkv_kernel, causal=causal, scale=scale),
        out_shape=(
            _sds((bh, s, dh), k.dtype, q3),
            _sds((bh, s, dh), v.dtype, q3),
        ),
        grid=(bh, s // blk_k, s // blk_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=(kspec2, kspec2),
        scratch_shapes=[
            pltpu.VMEM((blk_k, dh), jnp.float32),
            pltpu.VMEM((blk_k, dh), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)
    shape = (b, h, s, dh)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


@jax.jit
def softmax_top1(logits):
    """[B, C] logits -> (top-1 index int32 [B], top-1 prob float32 [B]) in a
    single pass — the full softmax matrix is never materialized in HBM."""
    b, c = logits.shape
    block_b = min(b, 256)
    idx, prob = pl.pallas_call(
        _softmax_top1_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ),
        grid=(pl.cdiv(b, block_b),),
        in_specs=[
            pl.BlockSpec((block_b, c), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=(
            pl.BlockSpec((block_b, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(logits)
    return idx[:, 0], prob[:, 0]


# ---------------------------------------------------------------------------
# Crossover-dispatched attention
# ---------------------------------------------------------------------------

# Calibration, measured on this repo's v5e (bf16, causal, Dh=128; the
# artifact re-measures every bench run — bench_detail.json["flash"], the
# "dispatch" entry records these constants next to the timings):
# - Small problems: XLA dense wins (best-of-history 4.67 ms vs flash 5.21
#   at S=2048, bh=8) — the score matrix fits comfortably and XLA's fused
#   softmax beats the kernel's block bookkeeping.
# - Long sequences: flash wins (6.43 vs 7.18 ms at S=8192) and is the only
#   path that scales past HBM (O(S) memory).
# - Large batch*heads at moderate S: flash wins even at S=2048 — an
#   8-layer LM at bh=48 measured flash step 126 ms vs dense 159, because
#   dense's f32 score matrix (bh * S^2 * 4 bytes = 805 MB there) turns the
#   whole layer HBM-bound. Hence the second bound below.
AUTO_FLASH_MIN_S = 4096
AUTO_DENSE_SCORES_CAP_BYTES = 256 * 1024 * 1024


def auto_picks_dense(b: int, h: int, s: int) -> bool:
    """The dispatch predicate, exposed so artifacts/telemetry that record
    which leg ``attention`` ran share ONE definition with the dispatch."""
    return s < AUTO_FLASH_MIN_S and 4 * b * h * s * s <= AUTO_DENSE_SCORES_CAP_BYTES


def attention(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Attention with measured crossover dispatch: XLA dense when the
    problem is small enough for dense to win (S below AUTO_FLASH_MIN_S AND
    the f32 score matrix under AUTO_DENSE_SCORES_CAP_BYTES), the blockwise
    flash kernel otherwise. Shapes [B, H, S, Dh]; prefer Dh=128 (see
    flash_attention). The dispatch is static per compiled shape — no
    data-dependent control flow under jit."""
    b, h, s, _ = q.shape
    if auto_picks_dense(b, h, s):
        from dmlc_tpu.parallel.ring_attention import dense_attention

        return dense_attention(q, k, v, causal=causal, scale=scale)
    return flash_attention(q, k, v, causal=causal, scale=scale)
