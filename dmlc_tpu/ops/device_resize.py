"""On-device image resize as MXU matmuls (SURVEY §7 "custom preprocessing
on device", VERDICT r1 weak #7).

Host-side resize is ~35% of the decode pipeline's CPU cost (measured: PIL
decode-only 1372 img/s vs decode+resize 893 img/s on this host). Moving it
onto the chip raises host decode capacity ~1.5x and ships only the
DCT-scaled raw pixels.

Design: a separable triangle-filter resample is LINEAR in the image, so
``out = Wy @ img @ Wx^T`` per channel, with banded weight matrices
precomputed on the host per (in_size, out_size) pair — identical tap
weights to the native C++ path (native/image_pipeline.cpp make_taps) and
PIL BILINEAR semantics. On TPU the two einsums tile straight onto the MXU
and XLA fuses them with the normalize + first conv of the consumer model.
This is deliberately NOT a Pallas kernel: a gather-style resize would fight
the hardware, while the matmul formulation IS the hardware's native op (the
same reasoning ops/pallas_kernels.py documents for normalize/top-1).
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=64)
def triangle_weights(in_size: int, out_size: int) -> np.ndarray:
    """[out_size, in_size] float32 row-stochastic triangle-filter weights
    (PIL BILINEAR: filter support widens by the downscale ratio)."""
    w = np.zeros((out_size, in_size), np.float32)
    scale = in_size / out_size
    support = max(1.0, scale)
    for i in range(out_size):
        center = (i + 0.5) * scale
        lo = max(0, int(np.floor(center - support)))
        hi = min(in_size, int(np.ceil(center + support)))
        js = np.arange(lo, hi)
        d = np.abs((js + 0.5 - center) / (scale if support > 1.0 else 1.0))
        ws = np.where(d < 1.0, 1.0 - d, 0.0)
        total = ws.sum()
        if total <= 0.0:  # degenerate: nearest
            ws[:] = 0.0
            ws[np.clip(int(center), lo, hi - 1) - lo] = total = 1.0
        w[i, lo:hi] = ws / total
    return w


# Shape combinations already seen by resize_batch: each NEW (N, H, W, out)
# forces a fresh trace/compile of the einsums (standalone, or of the caller's
# jit program when traced inline), so first-sight is exactly the
# compile-census event (cluster/devicemon.py; the runtime face of rule A6's
# "unstable shapes reaching jit" hazard).
_SEEN_SHAPES: set = set()


def resize_batch(images, out_size: int, dtype=jnp.float32):
    """[N, H, W, C] (any numeric dtype) -> [N, out, out, C] ``dtype``.

    Two einsums over precomputed weight matrices; under jit they are MXU
    matmuls fused with whatever consumes the result. Static shapes only —
    one compile per (H, W, out) combination."""
    n, h, w, c = images.shape
    combo = (int(n), int(h), int(w), int(out_size))
    if combo not in _SEEN_SHAPES:
        _SEEN_SHAPES.add(combo)
        from dmlc_tpu.cluster.devicemon import CENSUS

        CENSUS.record(f"device_resize/{h}x{w}->{out_size}")
    wy = jnp.asarray(triangle_weights(h, out_size), dtype)
    wx = jnp.asarray(triangle_weights(w, out_size), dtype)
    x = images.astype(dtype)
    x = jnp.einsum("oh,nhwc->nowc", wy, x)
    return jnp.einsum("pw,nowc->nopc", wx, x)


def reference_resize(images_u8: np.ndarray, out_size: int) -> np.ndarray:
    """Pure-numpy reference (same weights) for parity tests."""
    n, h, w, c = images_u8.shape
    wy = triangle_weights(h, out_size)
    wx = triangle_weights(w, out_size)
    x = images_u8.astype(np.float32)
    x = np.einsum("oh,nhwc->nowc", wy, x)
    return np.einsum("pw,nowc->nopc", wx, x)
