"""Ragged paged-KV decode attention: the per-step op of the generation engine.

Autoregressive decode attends ONE new query token per slot against that
slot's cached K/V, whose length differs per slot ("ragged" — per "Ragged
Paged Attention", PAPERS.md). The cache itself is PAGED (generate/kvcache.py):
fixed-size pages drawn from a shared pool, stitched into a per-slot sequence
by an int32 page table — so slots join/leave the running batch without
copying or fragmenting HBM.

Two paths behind the repo's kernel-fallback pattern (ops/pallas_kernels.py):

- ``gather_kv_pages`` XLA path — ``jnp.take`` over the page axis; what the
  engine runs off-TPU and the parity reference everywhere.
- ``gather_kv_pages`` Pallas path — a page-gather kernel using scalar
  prefetch (``PrefetchScalarGridSpec``): the page table is prefetched to
  SMEM and drives the BlockSpec index map, so each grid cell DMAs exactly
  one page from the pool into its contiguous output slot — the gather is
  pure data movement with no gather-scatter HLO. Interpreter mode off-TPU
  keeps tests hermetic (same seam as the flash kernels).

``ragged_decode_attention`` is the mask-based attention itself: scores are
computed against the full padded [B, S_max] cache view and positions at or
past each slot's kv length are masked to -inf, exactly mirroring
``parallel/ring_attention.dense_attention``'s f32 score/softmax discipline
so paged decode logits match the full-sequence forward bit-for-tolerance
(tests/test_generate.py pins this).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dmlc_tpu.ops.pallas_kernels import _interpret


def _gather_pages_pallas(pages, flat_table):
    """[N, P, D] pages gathered by a flat page-id vector -> [len, P, D].

    One grid cell per output page: the prefetched table entry picks which
    pool page the cell's input block maps to, the output block is the
    cell's own slot — the kernel body is a straight block copy.
    """
    n_out = flat_table.shape[0]
    _, page_size, width = pages.shape

    def copy_kernel(table_ref, page_ref, out_ref):
        del table_ref  # consumed by the index maps, not the body
        out_ref[...] = page_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_out,),
        in_specs=[
            pl.BlockSpec((1, page_size, width), lambda j, table: (table[j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, page_size, width), lambda j, table: (j, 0, 0)),
    )
    return pl.pallas_call(
        copy_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_out, page_size, width), pages.dtype),
        interpret=_interpret(),
    )(flat_table, pages)


def gather_kv_pages(pages, page_table, *, use_pallas: bool = False):
    """Assemble the per-slot contiguous cache view from the shared pool.

    ``pages``: [num_pages, page_size, H, Dh] (one layer's K or V pool);
    ``page_table``: int32 [B, max_pages] — row b's sequence is the
    concatenation of its pages in table order (unused entries point at the
    reserved scratch page 0 and are masked out by the attention lengths).
    Returns [B, max_pages * page_size, H, Dh].
    """
    b, max_pages = page_table.shape
    _, page_size, heads, head_dim = pages.shape
    if use_pallas:
        flat = page_table.reshape(b * max_pages).astype(jnp.int32)
        wide = pages.reshape(pages.shape[0], page_size, heads * head_dim)
        out = _gather_pages_pallas(wide, flat)
        return out.reshape(b, max_pages * page_size, heads, head_dim)
    out = jnp.take(pages, page_table.reshape(-1), axis=0)
    return out.reshape(b, max_pages * page_size, heads, head_dim)


def ragged_decode_attention(q, k, v, kv_lengths, *, scale: float | None = None):
    """One decode step of attention over ragged per-slot lengths.

    ``q``: [B, H, Dh] (the single new position per slot); ``k``/``v``:
    [B, S_max, H, Dh] padded cache views; ``kv_lengths``: int32 [B] — slot
    b attends positions [0, kv_lengths[b]). Scores and softmax run in f32
    (dense_attention's discipline); output is cast back to q's dtype.
    Callers guarantee kv_lengths >= 1 for every row (inactive slots carry a
    scratch-page row of length 1), so no row is fully masked.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s_max = k.shape[1]
    scores = jnp.einsum(
        "bhd,bshd->bhs",
        q.astype(jnp.float32) * scale,
        k.astype(jnp.float32),
    )
    mask = jnp.arange(s_max)[None, None, :] < kv_lengths[:, None, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhs,bshd->bhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_decode_attention(
    q, k_pages, v_pages, page_table, kv_lengths,
    *, scale: float | None = None, use_pallas: bool = False,
):
    """Gather + ragged attention in one call: the engine's per-layer step."""
    k = gather_kv_pages(k_pages, page_table, use_pallas=use_pallas)
    v = gather_kv_pages(v_pages, page_table, use_pallas=use_pallas)
    return ragged_decode_attention(q, k, v, kv_lengths, scale=scale)
