"""Image preprocessing: decode, resize, ImageNet-normalize, batch.

Capability parity with the reference's
``tch::vision::imagenet::load_image_and_resize(path, 224, 224)`` +
normalization (reference: src/services.rs:492): decode a JPEG, resize to the
model's input size, scale to [0,1], normalize with the ImageNet mean/std, and
also the label utilities around ``synset_words.txt`` (src/services.rs:170-184)
and per-class fixture lookup (src/services.rs:485-490).

Design split, TPU-first:
- **Host side** (numpy/PIL): decode + resize, returns uint8 HWC. JPEG decode
  cannot run on the TPU; at >10k img/s it must be overlapped with device
  compute, which the batch loader does with a thread pool.
- **Device side** (jax, fused into the model's first conv by XLA, or the
  Pallas kernel in ops/pallas_kernels.py): uint8 -> float, /255, (x-mean)/std.
  Shipping uint8 to the device cuts host->HBM transfer bytes 4x vs fp32.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
from pathlib import Path
from typing import Iterable, Sequence

import jax.numpy as jnp
import numpy as np

from dmlc_tpu.utils.hotpath import hot_path

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)

# ---- cached host decode pool ----------------------------------------------
# One module-level pool shared by every load_batch call. The original design
# built (and tore down) a fresh ThreadPoolExecutor per batch — at serving
# steady state that is thread spawn/join churn on every shard, the exact
# pattern lint rule H1 now forbids on hot paths. Grow-only: a bigger
# ``workers`` request replaces the pool; the abandoned smaller pool's idle
# threads are reclaimed at interpreter exit (same rationale as
# JobScheduler._ensure_gang_pool).
_HOST_POOL: concurrent.futures.ThreadPoolExecutor | None = None
_HOST_POOL_WORKERS = 0
_HOST_POOL_LOCK = threading.Lock()


def _host_pool(workers: int) -> concurrent.futures.ThreadPoolExecutor:
    global _HOST_POOL, _HOST_POOL_WORKERS
    with _HOST_POOL_LOCK:
        if _HOST_POOL is None or _HOST_POOL_WORKERS < workers:
            _HOST_POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="pp-decode"
            )
            _HOST_POOL_WORKERS = workers
        return _HOST_POOL


def load_synset_words(path: str | Path) -> list[tuple[str, str]]:
    """Parse synset_words.txt lines 'n01440764 tench, Tinca tinca' ->
    [(synset_id, label), ...] in file order. The file order defines the class
    index order (reference: src/services.rs:170-184), and the list doubles as
    the query workload for the scheduler."""
    out: list[tuple[str, str]] = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        synset, _, label = line.partition(" ")
        out.append((synset, label))
    return out


def class_image_path(data_dir: str | Path, synset: str) -> Path:
    """First image in the per-class fixture directory
    (reference: src/services.rs:485-490 picks the first dir entry)."""
    d = Path(data_dir) / synset
    files = sorted(p for p in d.iterdir() if p.is_file())
    if not files:
        raise FileNotFoundError(f"no images under {d}")
    return files[0]


def decode_resize(path: str | Path, size: int = 224) -> np.ndarray:
    """JPEG/PNG -> uint8 [size, size, 3] RGB, bilinear resize.

    Matches tch's load_image_and_resize semantics: direct resize to the target
    square (not resize-shortest-side + center-crop)."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        if im.size != (size, size):  # already-staged sizes skip the resample
            im = im.resize((size, size), Image.BILINEAR)
        return np.asarray(im, dtype=np.uint8)


@hot_path
def load_batch(
    paths: Sequence[str | Path],
    size: int = 224,
    workers: int | None = None,
    backend: str = "auto",
) -> np.ndarray:
    """Decode+resize a batch -> uint8 [N, size, size, 3] (fresh array).

    Thin wrapper over :func:`load_batch_into`; callers that run batches in a
    loop should preallocate the output once and use ``load_batch_into``
    directly so steady-state decode allocates nothing per batch.
    """
    out = np.empty((len(paths), size, size, 3), np.uint8)
    return load_batch_into(out, paths, size=size, workers=workers, backend=backend)


@hot_path
def load_batch_into(
    out: np.ndarray,
    paths: Sequence[str | Path],
    size: int = 224,
    workers: int | None = None,
    backend: str = "auto",
) -> np.ndarray:
    """Decode+resize a batch into the caller-owned arena ``out`` (returned).

    This is the stage that must keep up with the TPU (SURVEY.md §7 hard part
    b). ``out`` must be C-contiguous uint8 [len(paths), size, size, 3]; both
    the native and the PIL path fill it in place, so a caller that reuses one
    buffer per pipeline slot pays zero allocations per batch. ``workers`` is
    a concurrency hint — the cached pools (module-level here, persistent
    in-library for native) grow to the largest ever requested and are never
    rebuilt per call. ``backend``:

    - "native" — the C++ pipeline (dmlc_tpu.native): libjpeg with DCT-domain
      downscaling + a persistent thread-pooled triangle resample, GIL-free.
    - "pil" — PIL decode on the cached thread pool (decode releases the GIL).
    - "auto" — native when the library is built, else PIL. The two resize
      paths agree to within JPEG-noise tolerance (mean |diff| < 0.5/255 on
      the fixture corpus); a native decode failure falls back per-batch.
    """
    n = len(paths)
    shape = (n, size, size, 3)
    if (
        not isinstance(out, np.ndarray)
        or out.shape != shape
        or out.dtype != np.uint8
        or not out.flags["C_CONTIGUOUS"]
    ):
        raise ValueError(f"out must be a C-contiguous uint8 array of shape {shape}")
    if not n:
        return out
    if backend not in ("auto", "native", "pil"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend in ("auto", "native"):
        from dmlc_tpu import native

        if native.available():
            _, status = native.decode_resize_batch(
                paths, size, workers=workers or 0, out=out
            )
            if not status.any():
                return out
            if backend == "native":
                bad = [str(paths[i]) for i in np.nonzero(status)[0][:3]]
                raise ValueError(f"native decode failed for {bad}")
            # auto: a non-JPEG (e.g. PNG) snuck in — redo the batch via PIL.
        elif backend == "native":
            raise RuntimeError("native image pipeline not built")
    workers = workers or min(32, (os.cpu_count() or 8))
    if n == 1 or workers == 1:
        for i, p in enumerate(paths):
            out[i] = decode_resize(p, size)
        return out
    pool = _host_pool(workers)

    def fill(i: int) -> None:
        out[i] = decode_resize(paths[i], size)

    list(pool.map(fill, range(n)))  # list() re-raises worker exceptions
    return out


def decode_blob(data: bytes, size: int = 224) -> np.ndarray:
    """One encoded image's raw BYTES -> uint8 [size, size, 3] RGB. Same
    resize semantics as :func:`decode_resize`, but sourced from memory — the
    decode tier ships blobs over RPC, never paths (docs/INGEST.md §Decode
    tier). Raises on undecodable bytes; batch callers map that to a status
    slot instead of failing the batch."""
    from io import BytesIO

    from PIL import Image

    with Image.open(BytesIO(data)) as im:
        im = im.convert("RGB")
        if im.size != (size, size):
            im = im.resize((size, size), Image.BILINEAR)
        return np.asarray(im, dtype=np.uint8)


@hot_path
def decode_blobs(
    blobs: Sequence[bytes],
    size: int = 224,
    workers: int | None = None,
    backend: str = "auto",
) -> tuple[np.ndarray, np.ndarray]:
    """Decode a batch of raw encoded-image bytes (the decode tier's wire
    unit) -> ``(uint8 [N, size, size, 3], status uint8 [N])``.

    Per-blob failure is DATA, not an exception: a nonzero status slot marks
    an undecodable blob (its tensor rows are zeros) so the member's
    ``job.decode`` handler can answer with a typed ``DecodeError`` naming
    the poison indices while the caller keeps every good tensor it can
    still get locally. Backend selection mirrors :func:`load_batch_into`:
    the native path lands blobs in a throwaway tmpdir so the PERSISTENT
    C++ DecodePool (path-based ABI) does the GIL-free work; the PIL path
    decodes from memory on the cached host pool.
    """
    n = len(blobs)
    out = np.zeros((n, size, size, 3), np.uint8)
    status = np.zeros(n, np.uint8)
    if not n:
        return out, status
    if backend not in ("auto", "native", "pil"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend in ("auto", "native"):
        from dmlc_tpu import native

        if native.available():
            import tempfile

            with tempfile.TemporaryDirectory(prefix="dmlc-blobs-") as td:
                paths = []
                for i, b in enumerate(blobs):
                    p = Path(td) / f"{i}.img"
                    p.write_bytes(b)
                    paths.append(p)
                _, st = native.decode_resize_batch(
                    paths, size, workers=workers or 0, out=out
                )
            bad = np.nonzero(st)[0]
            if not bad.size:
                return out, status
            # Redo only the refused slots via PIL (a PNG snuck in, or the
            # blob really is poison — PIL gets the final word in "auto").
            for i in bad:
                try:
                    out[i] = decode_blob(blobs[i], size)
                except Exception:
                    out[i] = 0
                    status[i] = 1
            return out, status
        if backend == "native":
            raise RuntimeError("native image pipeline not built")

    def fill(i: int) -> None:
        try:
            out[i] = decode_blob(blobs[i], size)
        except Exception:
            out[i] = 0
            status[i] = 1

    workers = workers or min(32, (os.cpu_count() or 8))
    if n == 1 or workers == 1:
        for i in range(n):
            fill(i)
        return out, status
    pool = _host_pool(workers)
    list(pool.map(fill, range(n)))
    return out, status


# Device-resident normalization constants, keyed by value: jnp.asarray on a
# host constant is an upload (and a tracer-cache miss) — the standalone
# normalize path was re-staging mean/std on EVERY call. The cache holds a
# handful of 3-float arrays, so unbounded-by-key is bounded in practice.
_DEVICE_CONSTS: dict[tuple, "jnp.ndarray"] = {}


def _device_const(arr: np.ndarray):
    arr = np.asarray(arr, np.float32)
    key = (arr.tobytes(), arr.shape)
    cached = _DEVICE_CONSTS.get(key)
    if cached is None:
        cached = _DEVICE_CONSTS[key] = jnp.asarray(arr)
    return cached


def normalize(batch_u8, mean: np.ndarray = IMAGENET_MEAN, std: np.ndarray = IMAGENET_STD):
    """Device-side: uint8 NHWC -> normalized float32 NHWC. Under jit, XLA fuses
    this into the consumer; the Pallas variant exists for the standalone path.
    mean/std ride the device-constant cache, so repeated standalone calls
    re-upload nothing."""
    x = jnp.asarray(batch_u8).astype(jnp.float32) / 255.0
    return (x - _device_const(mean)) / _device_const(std)


def stats_for_model(model_name: str) -> tuple[np.ndarray, np.ndarray]:
    """Host-side (numpy) normalization stats — always the same module-level
    constant objects, never rebuilt, so callers may key caches on identity."""
    if model_name.startswith("clip"):
        return CLIP_MEAN, CLIP_STD
    return IMAGENET_MEAN, IMAGENET_STD


def device_stats_for_model(model_name: str):
    """Device-resident (jnp) normalization stats, cached across calls."""
    mean, std = stats_for_model(model_name)
    return _device_const(mean), _device_const(std)
