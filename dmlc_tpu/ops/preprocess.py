"""Image preprocessing: decode, resize, ImageNet-normalize, batch.

Capability parity with the reference's
``tch::vision::imagenet::load_image_and_resize(path, 224, 224)`` +
normalization (reference: src/services.rs:492): decode a JPEG, resize to the
model's input size, scale to [0,1], normalize with the ImageNet mean/std, and
also the label utilities around ``synset_words.txt`` (src/services.rs:170-184)
and per-class fixture lookup (src/services.rs:485-490).

Design split, TPU-first:
- **Host side** (numpy/PIL): decode + resize, returns uint8 HWC. JPEG decode
  cannot run on the TPU; at >10k img/s it must be overlapped with device
  compute, which the batch loader does with a thread pool.
- **Device side** (jax, fused into the model's first conv by XLA, or the
  Pallas kernel in ops/pallas_kernels.py): uint8 -> float, /255, (x-mean)/std.
  Shipping uint8 to the device cuts host->HBM transfer bytes 4x vs fp32.
"""

from __future__ import annotations

import concurrent.futures
import os
from pathlib import Path
from typing import Iterable, Sequence

import jax.numpy as jnp
import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
CLIP_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


def load_synset_words(path: str | Path) -> list[tuple[str, str]]:
    """Parse synset_words.txt lines 'n01440764 tench, Tinca tinca' ->
    [(synset_id, label), ...] in file order. The file order defines the class
    index order (reference: src/services.rs:170-184), and the list doubles as
    the query workload for the scheduler."""
    out: list[tuple[str, str]] = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        synset, _, label = line.partition(" ")
        out.append((synset, label))
    return out


def class_image_path(data_dir: str | Path, synset: str) -> Path:
    """First image in the per-class fixture directory
    (reference: src/services.rs:485-490 picks the first dir entry)."""
    d = Path(data_dir) / synset
    files = sorted(p for p in d.iterdir() if p.is_file())
    if not files:
        raise FileNotFoundError(f"no images under {d}")
    return files[0]


def decode_resize(path: str | Path, size: int = 224) -> np.ndarray:
    """JPEG/PNG -> uint8 [size, size, 3] RGB, bilinear resize.

    Matches tch's load_image_and_resize semantics: direct resize to the target
    square (not resize-shortest-side + center-crop)."""
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB")
        if im.size != (size, size):  # already-staged sizes skip the resample
            im = im.resize((size, size), Image.BILINEAR)
        return np.asarray(im, dtype=np.uint8)


def load_batch(
    paths: Sequence[str | Path],
    size: int = 224,
    workers: int | None = None,
    backend: str = "auto",
) -> np.ndarray:
    """Decode+resize a batch -> uint8 [N, size, size, 3].

    This is the stage that must keep up with the TPU (SURVEY.md §7 hard part
    b). ``backend``:

    - "native" — the C++ pipeline (dmlc_tpu.native): libjpeg with DCT-domain
      downscaling + thread-pooled triangle resample, GIL-free.
    - "pil" — PIL decode on a thread pool (decode releases the GIL).
    - "auto" — native when the library is built, else PIL. The two resize
      paths agree to within JPEG-noise tolerance (mean |diff| < 0.5/255 on
      the fixture corpus); a native decode failure falls back per-batch.
    """
    if not paths:
        return np.zeros((0, size, size, 3), np.uint8)
    if backend not in ("auto", "native", "pil"):
        raise ValueError(f"unknown backend {backend!r}")
    if backend in ("auto", "native"):
        from dmlc_tpu import native

        if native.available():
            out, status = native.decode_resize_batch(paths, size, workers=workers or 0)
            if not status.any():
                return out
            if backend == "native":
                bad = [str(paths[i]) for i in np.nonzero(status)[0][:3]]
                raise ValueError(f"native decode failed for {bad}")
            # auto: a non-JPEG (e.g. PNG) snuck in — redo the batch via PIL.
        elif backend == "native":
            raise RuntimeError("native image pipeline not built")
    workers = workers or min(32, (os.cpu_count() or 8))
    if len(paths) == 1 or workers == 1:
        return np.stack([decode_resize(p, size) for p in paths])
    with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as pool:
        return np.stack(list(pool.map(lambda p: decode_resize(p, size), paths)))


def normalize(batch_u8, mean: np.ndarray = IMAGENET_MEAN, std: np.ndarray = IMAGENET_STD):
    """Device-side: uint8 NHWC -> normalized float32 NHWC. Under jit, XLA fuses
    this into the consumer; the Pallas variant exists for the standalone path."""
    x = jnp.asarray(batch_u8).astype(jnp.float32) / 255.0
    return (x - jnp.asarray(mean)) / jnp.asarray(std)


def stats_for_model(model_name: str) -> tuple[np.ndarray, np.ndarray]:
    if model_name.startswith("clip"):
        return CLIP_MEAN, CLIP_STD
    return IMAGENET_MEAN, IMAGENET_STD
