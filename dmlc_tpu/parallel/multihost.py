"""Multi-host mesh formation: the cluster substrate bootstraps jax.distributed.

The reference runs on 10 hosts but each host's model runs alone — there is no
cross-host device mesh anywhere (src/services.rs:26-30, 199-211). TPU-native
scaling needs one: a v5e-8 host is multi-chip, but anything bigger (pods,
multi-host DP/TP) requires every process to join one jax.distributed runtime
so ``jax.devices()`` becomes the GLOBAL device list and pjit/shard_map
programs span hosts, with XLA routing collectives over ICI/DCN.

The missing piece is agreeing on (coordinator_address, num_processes,
process_id) — exactly the kind of agreement the cluster layer already
provides. The elected leader (cluster/failover.py) serves ``mesh.register``:
each member registers its address and is assigned the next process id;
everyone polls until the expected process count has registered, then calls
``jax.distributed.initialize`` with the leader-published coordinator address.
Deterministic, restart-safe (same address re-registers to the same rank), and
with no second consensus system.

Hermetic coverage: tests/test_multihost.py forms a real 2-process CPU
jax.distributed runtime and runs the dp train step over the global mesh.
"""

from __future__ import annotations

import logging
import threading
import time

from dmlc_tpu.cluster.rpc import Rpc, RpcError
from dmlc_tpu.utils.tracing import traced_methods

log = logging.getLogger(__name__)


class MeshBootstrap:
    """Leader-side rank assignment for the global device mesh.

    Ranks are handed out in registration order; re-registration of a known
    address is idempotent (a restarted process keeps its rank — required, as
    jax.distributed binds rank to the coordinator's barrier state). The
    published coordinator address is ``<rank-0's host>:<coordinator_port>``:
    jax.distributed runs the coordination service IN process 0, so the
    coordinator host must be wherever rank 0 lives, which is only known once
    the first process registers.

    Like SdfsLeader, writes are refused unless actively leading (set by
    StandbyLeader on promotion) so two candidates can never hand out
    conflicting rank maps. The mesh forms once per fleet lifetime — a
    post-failover leader cannot re-rank already-initialized processes.
    """

    def __init__(self, coordinator_port: int, num_processes: int, is_leading: bool = True):
        self.coordinator_port = int(coordinator_port)
        self.num_processes = int(num_processes)
        self.is_leading = is_leading
        self.ranks: dict[str, int] = {}
        self._lock = threading.Lock()

    def methods(self) -> dict:
        return traced_methods({
            "mesh.register": self._register,
            "mesh.info": self._info,
            "mesh.state": self._state_wire,
        })

    def _state_wire(self, p: dict) -> dict:
        """Rank-map replication payload for standby leaders: without it a
        failover would re-rank already-initialized processes."""
        with self._lock:
            return {"ranks": dict(self.ranks)}

    def adopt_state(self, wire: dict) -> None:
        with self._lock:
            self.ranks = {str(a): int(r) for a, r in wire["ranks"].items()}

    def group(self) -> dict | None:
        """{addr: rank} once every expected process has registered, else
        None — the scheduler's gang-dispatch readiness check (keeps the
        ready invariant here instead of in callers)."""
        with self._lock:
            if len(self.ranks) < self.num_processes:
                return None
            return dict(self.ranks)

    def _register(self, p: dict) -> dict:
        addr = p["addr"]
        with self._lock:
            if not self.is_leading:
                raise RpcError("not the active leader")
            if addr not in self.ranks:
                if len(self.ranks) >= self.num_processes:
                    raise RpcError(
                        f"mesh is full: {self.num_processes} processes already registered"
                    )
                self.ranks[addr] = len(self.ranks)
            return self._info_locked(self.ranks[addr])

    def _info(self, p: dict) -> dict:
        with self._lock:
            return self._info_locked(None)

    def _coordinator_locked(self) -> str | None:
        rank0 = next((a for a, r in self.ranks.items() if r == 0), None)
        if rank0 is None:
            return None
        host, _, _ = rank0.rpartition(":")
        return f"{host}:{self.coordinator_port}"

    def _info_locked(self, process_id) -> dict:
        return {
            "process_id": process_id,
            "num_processes": self.num_processes,
            "coordinator": self._coordinator_locked(),
            "registered": len(self.ranks),
            "ready": len(self.ranks) >= self.num_processes,
        }


# RpcError fragments that polling can never fix — fail fast instead of
# burning the whole join window.
_PERMANENT_ERRORS = ("unknown method", "mesh is full")


def register_until_ready(
    rpc: Rpc,
    leader_addr,
    self_addr: str,
    timeout_s: float = 120.0,
    poll_s: float = 0.5,
) -> dict:
    """Register with the leader and poll until every expected process has —
    returns the final {process_id, num_processes, coordinator, ...} info.

    ``leader_addr`` may be a callable re-resolved every poll (the node's
    LeaderTracker) so a leader failover mid-join redirects to the promoted
    standby instead of stranding the fleet. Transient failures (connection
    drops, a candidate still deferring mid-election) keep polling until the
    deadline; permanent refusals (mesh not configured, mesh full) raise
    immediately."""
    addr_fn = leader_addr if callable(leader_addr) else (lambda: leader_addr)
    deadline = time.monotonic() + timeout_s
    info = None
    last_err: Exception | None = None
    while time.monotonic() < deadline:
        addr = addr_fn()
        try:
            # Each attempt is bounded (dmlc-analyze A3): a wedged candidate
            # must cost one short re-poll, never the implicit 60 s default —
            # and never more than the join window that remains.
            attempt_s = max(0.1, min(10.0, deadline - time.monotonic()))
            info = rpc.call(
                addr, "mesh.register", {"addr": self_addr}, timeout=attempt_s
            )
            if info["ready"]:
                return info
        except RpcError as e:
            if any(frag in str(e) for frag in _PERMANENT_ERRORS):
                raise
            last_err = e
            log.warning("mesh.register at %s failed (will retry): %s", addr, e)
        time.sleep(poll_s)
    raise TimeoutError(
        f"global mesh never became ready: {info and info['registered']}"
        f"/{info and info['num_processes']} processes registered"
        + (f" (last error: {last_err})" if last_err else "")
    )


def initialize_global_runtime(info: dict) -> None:
    """Join the jax.distributed runtime described by a register reply. After
    this, jax.devices() is the GLOBAL device list and meshes span hosts."""
    import jax

    jax.distributed.initialize(
        coordinator_address=info["coordinator"],
        num_processes=int(info["num_processes"]),
        process_id=int(info["process_id"]),
    )
    log.info(
        "joined global mesh: process %d/%d, %d global devices",
        info["process_id"],
        info["num_processes"],
        jax.device_count(),
    )


def join_global_mesh(
    rpc: Rpc, leader_addr, self_addr: str, timeout_s: float = 120.0
) -> dict:
    """The member-side one-call path: register, wait for the fleet, join.
    ``leader_addr`` may be a callable (see register_until_ready)."""
    info = register_until_ready(rpc, leader_addr, self_addr, timeout_s=timeout_s)
    initialize_global_runtime(info)
    return info
