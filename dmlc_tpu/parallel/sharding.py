"""Partition-rule engine: regex rules -> PartitionSpec pytrees -> sharded programs.

Every registry model declares its sharding ONCE as an ordered table of
``(regex, PartitionSpec)`` rules (``ModelSpec.partition_rules``). The engine
matches each rule with ``re.search`` against the '/'-joined path of every
parameter leaf — first match wins, scalars and size-1 leaves are always
replicated — and compiles the resulting spec pytree into jit programs at ANY
mesh shape: axes a mesh does not carry (or that do not divide a leaf's dim)
are clamped to replication, so the same table serves a 1-chip replica, a
2-chip tensor-parallel gang, and an 8-chip dp x tp grid without edits.

This generalizes the hardcoded Megatron walk in ``mesh.param_spec`` (kept as
the engine-internal fallback for models that declare no table) and is what
``models/export.py`` uses to export sharded executables and what the serving
gang path (``scheduler/worker.LmBackend``) runs at predict time.

Rule-table hygiene is checked twice: statically by analyzer rule A8
(tools/analyze/rules/devsem.py — bad regexes, rules shadowed by an earlier
catch-all, tables with no terminal catch-all) and dynamically by
``validate_rules`` against the real abstract parameter tree (dead rules that
match no param, params no rule matches). See docs/SHARDING.md.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PyTree = Any
PartitionRule = tuple[str, PartitionSpec]

# Megatron-style table for every transformer in the zoo (SPTransformerLM,
# ViT, the CLIP vision trunk — they share Dense naming): attention q/k/v and
# MLP-in split the OUTPUT feature dim over tp, attention-out and MLP-out
# split the INPUT dim, so each block pays exactly one psum; the vocab/class
# head splits its output and is gathered once at the end. Everything else
# (embeddings, norms, convs, the out-projection biases added after the psum)
# replicates via the terminal catch-all.
TRANSFORMER_PARTITION_RULES: tuple[PartitionRule, ...] = (
    (r"(query|key|value|mlp_in)/kernel$", PartitionSpec(None, "tp")),
    (r"(query|key|value|mlp_in)/bias$", PartitionSpec("tp")),
    (r"(out|mlp_out)/kernel$", PartitionSpec("tp", None)),
    (r"(head|projection)/kernel$", PartitionSpec(None, "tp")),
    (r".*", PartitionSpec()),
)

# CNN families: the win is dp over the batch; XLA gains nothing from
# splitting 3x3 convs at these sizes (see mesh.param_spec's rationale).
REPLICATED_PARTITION_RULES: tuple[PartitionRule, ...] = ((r".*", PartitionSpec()),)


def _key_str(entry: Any) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_paths(tree: PyTree) -> list[tuple[str, Any]]:
    """Flatten a pytree to ``[('/joined/param/path', leaf), ...]``."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [("/".join(_key_str(k) for k in path), leaf) for path, leaf in flat]


def match_partition_rules(
    rules: Sequence[PartitionRule], tree: PyTree, *, strict: bool = True
) -> PyTree:
    """Map every leaf to the spec of the FIRST rule whose regex ``search``es
    its '/'-joined path. Scalars and size-1 leaves always get ``P()``. With
    ``strict`` (the default), a leaf no rule matches raises ``ValueError`` —
    an unsharded multi-GB param silently replicated onto every chip is the
    bug this engine exists to prevent."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs: list[PartitionSpec] = []
    for path, leaf in flat:
        name = "/".join(_key_str(k) for k in path)
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape or math.prod(shape) == 1:
            specs.append(PartitionSpec())
            continue
        for pat, spec in compiled:
            if pat.search(name):
                specs.append(spec)
                break
        else:
            if strict:
                raise ValueError(f"no partition rule matches param {name!r}")
            specs.append(PartitionSpec())
    return jax.tree_util.tree_unflatten(treedef, specs)


@dataclass(frozen=True)
class RuleReport:
    """Dynamic rule-table audit: the runtime half of analyzer rule A8."""

    dead_rules: tuple[str, ...]  # patterns matching NO param path in the tree
    unmatched: tuple[str, ...]   # param paths no rule matches (spec-less at mesh>1)

    @property
    def ok(self) -> bool:
        return not self.dead_rules and not self.unmatched


def validate_rules(rules: Sequence[PartitionRule], tree: PyTree) -> RuleReport:
    """Audit a rule table against a real (or abstract) parameter tree."""
    paths = [p for p, leaf in tree_paths(tree)]
    compiled = [(pat, re.compile(pat)) for pat, _ in rules]
    dead = tuple(pat for pat, rx in compiled if not any(rx.search(p) for p in paths))
    unmatched = tuple(
        p for p in paths if not any(rx.search(p) for _, rx in compiled)
    )
    return RuleReport(dead_rules=dead, unmatched=unmatched)


def clamp_spec(spec: PartitionSpec, mesh: Mesh, shape: Sequence[int]) -> PartitionSpec:
    """Make a spec valid on THIS mesh and leaf shape: drop axes the mesh does
    not carry (or carries at size 1), and fall back to replication on any dim
    the surviving axes do not divide evenly. This is what lets one rule table
    compile at every mesh shape."""
    sizes: dict[str, int] = dict(zip(mesh.axis_names, mesh.devices.shape))
    out: list[Any] = []
    for dim, entry in enumerate(tuple(spec)[: len(shape)]):
        axes = entry if isinstance(entry, tuple) else (entry,)
        keep = [a for a in axes if a is not None and sizes.get(str(a), 1) > 1]
        factor = math.prod(sizes[str(a)] for a in keep) if keep else 1
        if factor > 1 and shape[dim] % factor:
            keep = []
        out.append(tuple(keep) if len(keep) > 1 else (keep[0] if keep else None))
    return PartitionSpec(*out)


def shardings_for_tree(
    mesh: Mesh,
    tree: PyTree,
    rules: Sequence[PartitionRule],
    *,
    strict: bool = True,
) -> PyTree:
    """Rule table + abstract/real param tree -> pytree of NamedShardings,
    clamped to this mesh."""
    specs = match_partition_rules(rules, tree, strict=strict)
    return jax.tree_util.tree_map(
        lambda leaf, spec: NamedSharding(
            mesh, clamp_spec(spec, mesh, tuple(getattr(leaf, "shape", ())))
        ),
        tree,
        specs,
    )


def make_shard_and_gather_fns(
    mesh: Mesh, shardings: PyTree
) -> tuple[Callable[[PyTree], PyTree], Callable[[PyTree], PyTree]]:
    """``(shard_fn, gather_fn)``: shard_fn places a host tree onto the mesh
    per the shardings; gather_fn pulls a device tree back to host numpy."""

    def shard_fn(tree: PyTree) -> PyTree:
        return jax.tree_util.tree_map(
            lambda leaf, shd: jax.device_put(leaf, shd), tree, shardings
        )

    def gather_fn(tree: PyTree) -> PyTree:
        return jax.tree_util.tree_map(lambda leaf: np.asarray(jax.device_get(leaf)), tree)

    return shard_fn, gather_fn


def plan_axes(
    n_devices: int, *, num_heads: int | None = None, max_tp: int | None = None
) -> dict[str, int]:
    """Mesh-shape selection for a gang of ``n_devices`` chips: tp is the
    largest divisor of n that also divides the head count (attention heads
    cannot split fractionally), capped by ``max_tp``; the rest is dp. A prime
    gang (n=3) with 4 heads therefore runs pure dp; n=8 with 4 heads runs
    dp=2 x tp=4."""
    if n_devices < 1:
        raise ValueError(f"gang needs at least one device, got {n_devices}")
    cap = n_devices if max_tp is None else max(1, min(max_tp, n_devices))
    tp = 1
    for cand in range(1, n_devices + 1):
        if n_devices % cand or cand > cap:
            continue
        if num_heads is not None and num_heads % cand:
            continue
        tp = cand
    return {"dp": n_devices // tp, "tp": tp}


def min_gang_width(
    model_bytes: int, per_chip_budget: int, *, max_width: int
) -> int | None:
    """Smallest gang width whose even ceil-share of the model's resident
    bytes fits the per-chip budget — the replica-count-vs-shard-width trade
    the PlacementAdvisor makes. None when even the widest gang cannot fit."""
    if per_chip_budget <= 0:
        return None
    for width in range(1, max(1, max_width) + 1):
        if -(-model_bytes // width) <= per_chip_budget:
            return width
    return None


def rules_for_model(model_name: str) -> tuple[PartitionRule, ...]:
    """The registry model's declared table, or full replication."""
    from dmlc_tpu.models.registry import get_model

    rules = get_model(model_name).partition_rules
    return tuple(rules) if rules else REPLICATED_PARTITION_RULES


def abstract_params(model_name: str, dtype: Any = jnp.float32) -> PyTree:
    """Shape/dtype-only variables pytree (no device allocation)."""
    from dmlc_tpu.models.registry import get_model

    spec = get_model(model_name)

    def init() -> Any:
        return spec.init_params(jax.random.PRNGKey(0), dtype=dtype)[1]

    return jax.eval_shape(init)


def validate_model_rules(model_name: str, dtype: Any = jnp.float32) -> RuleReport:
    """Audit a registry model's declared table against its abstract tree."""
    return validate_rules(rules_for_model(model_name), abstract_params(model_name, dtype))


def sharded_bytes_per_chip(
    model_name: str, mesh: Mesh, dtype: Any = jnp.float32
) -> int:
    """Per-chip resident weight bytes under this mesh: each leaf contributes
    its bytes divided by the product of mesh-axis sizes its clamped spec
    actually shards over. The gauge the node publishes per gang member."""
    tree = abstract_params(model_name, dtype)
    specs = match_partition_rules(rules_for_model(model_name), tree, strict=False)
    sizes: dict[str, int] = dict(zip(mesh.axis_names, mesh.devices.shape))
    total = 0
    for (path, leaf), (_, spec) in zip(tree_paths(tree), tree_paths(specs)):
        shape = tuple(leaf.shape)
        clamped = clamp_spec(spec, mesh, shape)
        factor = 1
        for entry in tuple(clamped):
            for ax in entry if isinstance(entry, tuple) else (entry,):
                if ax is not None:
                    factor *= sizes.get(str(ax), 1)
        width = jnp.dtype(dtype).itemsize if dtype is not None else jnp.dtype(leaf.dtype).itemsize
        total += -(-math.prod(shape) * width // factor)
    return total


# ---------------------------------------------------------------------------
# Sharded program construction


class ShardedProgram:
    """A registry model compiled at a specific mesh shape: rule-sharded
    params resident on the mesh, a jit forward with batch over dp, plus the
    matching next-token / embedding entry points. One instance == one gang's
    executable; ``mesh`` of 1 device == the unsharded reference."""

    def __init__(
        self,
        model_name: str,
        mesh: Mesh,
        *,
        dtype: Any = jnp.float32,
        seed: int = 0,
    ) -> None:
        from dmlc_tpu.models.registry import get_model

        self.model_name = model_name
        self.mesh = mesh
        self.dtype = dtype
        self.spec = get_model(model_name)
        rules = rules_for_model(model_name)
        model, variables = self.spec.init_params(
            jax.random.PRNGKey(seed), dtype=dtype
        )
        self.model = model
        shardings = shardings_for_tree(mesh, variables, rules)
        shard_fn, self._gather_fn = make_shard_and_gather_fns(mesh, shardings)
        self.variables = shard_fn(variables)
        self._param_shardings = shardings
        self._data_sharding = NamedSharding(mesh, clamp_spec(PartitionSpec("dp"), mesh, (0,)))
        self._forward: Any = None

    @property
    def dp(self) -> int:
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        return int(sizes.get("dp", 1))

    def load_variables(self, variables: PyTree) -> None:
        """Hot-swap weights (SDFS blob path), re-sharded under the same rules."""
        shardings = shardings_for_tree(
            self.mesh, variables, rules_for_model(self.model_name)
        )
        shard_fn, _ = make_shard_and_gather_fns(self.mesh, shardings)
        self.variables = shard_fn(variables)
        self._param_shardings = shardings

    def _build_forward(self) -> Any:
        if self._forward is not None:
            return self._forward
        repl = NamedSharding(self.mesh, PartitionSpec())

        if self.spec.kind == "lm":

            def forward(variables: PyTree, tokens: Any) -> Any:
                logits = self.model.apply(variables, tokens)  # [B, S, V]
                return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

        else:
            mean = jnp.asarray([0.485, 0.456, 0.406], self.dtype) * 255.0
            std = jnp.asarray([0.229, 0.224, 0.225], self.dtype) * 255.0

            def forward(variables: PyTree, images: Any) -> Any:
                x = (images.astype(self.dtype) - mean) / std
                out = self.model.apply(variables, x, train=False)
                if self.spec.classifier:
                    return jnp.argmax(out, axis=-1).astype(jnp.int32)
                return out

        self._forward = jax.jit(
            forward,
            in_shardings=(self._param_shardings, self._data_sharding),
            out_shardings=repl,
        )
        return self._forward

    def _pad_to_dp(self, batch: np.ndarray) -> tuple[np.ndarray, int]:
        n = batch.shape[0]
        dp = self.dp
        pad = (-n) % dp
        if pad:
            batch = np.concatenate([batch, np.repeat(batch[-1:], pad, axis=0)], axis=0)
        return batch, n

    def run(self, batch: np.ndarray) -> np.ndarray:
        """Forward a host batch (tokens [B,S] int32 for LMs, uint8 NHWC for
        image models); returns host numpy, padding stripped."""
        fwd = self._build_forward()
        padded, n = self._pad_to_dp(np.asarray(batch))
        dev = jax.device_put(jnp.asarray(padded), self._data_sharding)
        out = np.asarray(jax.device_get(fwd(self.variables, dev)))
        return out[:n]


def tokens_for_prompt(prompt: str, length: int, vocab: int) -> np.ndarray:
    """Deterministic prompt encoding shared by every serving path (cluster
    members, the reference process, the bench): pure arithmetic on a crc32
    seed, so it is stable across processes, PYTHONHASHSEED, and platforms."""
    import zlib

    seed = zlib.crc32(prompt.encode("utf-8"))
    return np.asarray(
        [(seed + i * 2654435761) % vocab for i in range(length)], dtype=np.int32
    )


def encode_prompts(prompts: Iterable[str], length: int, vocab: int) -> np.ndarray:
    return np.stack([tokens_for_prompt(p, length, vocab) for p in prompts])
