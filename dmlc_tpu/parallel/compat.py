"""JAX version compatibility shims.

``jax.shard_map`` graduated out of ``jax.experimental`` only after the
jax this image ships (0.4.37); every sp/pp schedule routes through this
one alias so the code runs on both sides of the move.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # jax < 0.5: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):  # type: ignore[no-redef]
        # Callers use the current ``check_vma`` spelling; the experimental
        # API called the same knob ``check_rep``.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(f, **kwargs)


def axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis inside a shard_map body.

    ``lax.axis_size`` postdates this image's jax; ``psum(1, axis)`` is the
    classic spelling and constant-folds to a concrete int during the
    shard_map trace, so ring perms / scan lengths built from it stay static.
    """
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)
