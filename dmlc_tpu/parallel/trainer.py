"""Checkpointed training driver: the dp x tp train step in a restartable loop.

The reference's `train` verb never trains (it broadcasts pretrained files,
src/services.rs:139-144); its only resume machinery is the replicated job
cursor. This driver completes the training story the TPU-native way: the
SPMD step from parallel/train.py runs under one jit, and every
``checkpoint_every`` steps the FULL TrainState (params, optimizer moments,
batch stats, step counter) is saved as a new replicated SDFS version via
utils/checkpoint.py — so a crashed driver, or a new driver started after
leader failover on a different node, restores from the replicated store and
continues exactly where training stopped (tests/test_train_driver.py kills
the SDFS leader mid-run and restores via the promoted standby).

``data_fn(step) -> (images, labels)`` abstracts the input pipeline: tests
use synthetic batches; a real run feeds decoded corpus batches (the
ops/preprocess stream) the same way.
"""

from __future__ import annotations

import logging
from typing import Callable

import jax

from dmlc_tpu.parallel import train as train_lib

log = logging.getLogger(__name__)


class TrainingDriver:
    """Drive ``steps`` train steps with periodic replicated checkpoints.

    ``checkpointer`` is anything with save(state, step) / restore(template)
    — an SdfsCheckpointer for replicated storage, or None to disable."""

    def __init__(
        self,
        mesh,
        state: train_lib.TrainState,
        data_fn: Callable[[int], tuple],
        checkpointer=None,
        checkpoint_every: int = 100,
        remat: bool = False,
        grad_accum: int = 1,
    ):
        self.mesh = mesh
        self.data_fn = data_fn
        self.checkpointer = checkpointer
        self.checkpoint_every = int(checkpoint_every)
        self.history: list[dict] = []
        # Restore BEFORE sharding: the template must be host-side with the
        # same tree structure the checkpoint was saved from.
        self.start_step = 0
        if checkpointer is not None:
            try:
                state, self.start_step = checkpointer.restore(state)
                log.info("restored checkpoint at step %d", self.start_step)
            except Exception as e:  # no checkpoint yet — fresh run
                log.info("no checkpoint to restore (%s); starting fresh", e)
        self.state, self.step_fn = train_lib.make_train_step(
            mesh, state, remat=remat, grad_accum=grad_accum
        )

    def run(self, steps: int) -> dict:
        """Train until the global step counter reaches ``start + steps``.
        Returns the last metrics. Checkpoints every checkpoint_every steps
        and once more at the end."""
        step = self.start_step
        last = {}
        for _ in range(steps):
            images, labels = self.data_fn(step)
            self.state, metrics = self.step_fn(self.state, images, labels)
            step += 1
            last = {k: float(v) for k, v in metrics.items()}
            self.history.append({"step": step, **last})
            if self.checkpointer is not None and step % self.checkpoint_every == 0:
                self._save(step)
        if self.checkpointer is not None and step % self.checkpoint_every != 0:
            self._save(step)
        self.start_step = step
        return last

    def _save(self, step: int) -> None:
        host_state = jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if hasattr(x, "shape") else x, self.state
        )
        self.checkpointer.save(host_state, step)
