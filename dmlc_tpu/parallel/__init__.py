from dmlc_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    param_shardings,
    param_spec,
    replicated,
    shard_params,
)
from dmlc_tpu.parallel.inference import BatchResult, InferenceEngine
from dmlc_tpu.parallel.ring_attention import dense_attention, ring_attention
from dmlc_tpu.parallel.sp_transformer import (
    SPSelfAttention,
    SPTransformerBlock,
    SPTransformerLM,
)
from dmlc_tpu.parallel.ulysses import ulysses_attention
from dmlc_tpu.parallel.train import (
    TrainState,
    create_train_state,
    default_optimizer,
    make_train_step,
    state_shardings,
)
