from dmlc_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    param_shardings,
    param_spec,
    replicated,
    shard_params,
)
from dmlc_tpu.parallel.ring_attention import dense_attention, ring_attention
from dmlc_tpu.parallel.sp_transformer import (
    SPSelfAttention,
    SPTransformerBlock,
    SPTransformerLM,
)
from dmlc_tpu.parallel.ulysses import ulysses_attention
from dmlc_tpu.parallel.train import (
    TrainState,
    create_train_state,
    default_optimizer,
    make_train_step,
    state_shardings,
)


def __getattr__(name: str):
    # Lazy (PEP 562): inference imports dmlc_tpu.models, and models.registry
    # imports parallel.sharding's rule tables — an eager import here would
    # close that loop into a circular-import crash whichever side loads
    # first. Deferring the ONE models-dependent module breaks the cycle
    # without pushing lazy imports into every registry call site.
    if name in ("BatchResult", "InferenceEngine"):
        from dmlc_tpu.parallel import inference

        return getattr(inference, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
