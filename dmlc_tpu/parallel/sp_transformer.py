"""Sequence-parallel transformer blocks: long-context as a MODEL property.

ring_attention / ulysses_attention are tensor-level schedules; this module
makes them drop-in model components, so "long context" is not a kernel demo
but a trainable architecture: activations stay sequence-sharded over ``sp``
through the whole block (every other op — projections, MLP, layernorm,
residuals — is position-wise, so XLA keeps them local to each device's
sequence slice; only attention communicates, via the chosen schedule).

With a {dp, sp} mesh the per-device activation footprint is
O(B/dp * S/sp * D): sequences that cannot exist on one chip train across
the ICI ring. Combine with remat/grad-accum (parallel/train.py) for the
full long-context memory stack. The reference has no sequence axis anywhere
(SURVEY.md §5); this is the TPU-first capability the north star asks for.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import Mesh

from dmlc_tpu.parallel.ring_attention import (
    dense_attention,
    ring_attention,
    ring_flash_attention,
)
from dmlc_tpu.parallel.ulysses import ulysses_attention

_SCHEDULES = ("ring", "ring_flash", "ulysses", "dense", "flash", "auto")


class SPSelfAttention(nn.Module):
    """Multi-head self-attention over a sequence sharded on ``mesh``'s sp
    axis. ``schedule`` picks the communication pattern: "ring_flash"
    (ppermute K/V rotation with the pallas flash kernel as the per-step
    accumulator — O(S_local * blk) memory, no [S_local, S_local] scores in
    forward or backward), "ring" (ppermute
    K/V rotation, O(S/n) memory, no head constraint), "ulysses" (all-to-all
    head/sequence reshard, needs heads % sp == 0), "dense" (no sp —
    single-device reference semantics, used for parity tests), or "flash"
    (no sp — the blockwise Pallas kernel, ops/pallas_kernels.py: O(S)
    memory and faster than dense on TPU for the single-device regime), or
    "auto" (no sp — measured crossover dispatch between dense and flash by
    sequence length and score-matrix footprint, ops/pallas_kernels.py:
    attention; the right default when not sequence-sharding)."""

    num_heads: int
    mesh: Mesh | None = None
    schedule: str = "ring"
    causal: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):  # [B, S, D] (S sharded over sp)
        if self.schedule not in _SCHEDULES:
            raise ValueError(f"schedule must be one of {_SCHEDULES}, got {self.schedule!r}")
        b, s, d = x.shape
        if d % self.num_heads:
            raise ValueError(f"model dim {d} not divisible by {self.num_heads} heads")
        dh = d // self.num_heads

        def heads(name):
            y = nn.Dense(d, dtype=self.dtype, name=name)(x)
            return y.reshape(b, s, self.num_heads, dh).transpose(0, 2, 1, 3)  # [B,H,S,Dh]

        q, k, v = heads("query"), heads("key"), heads("value")
        if self.schedule == "ring":
            o = ring_attention(q, k, v, self.mesh, causal=self.causal)
        elif self.schedule == "ring_flash":
            o = ring_flash_attention(q, k, v, self.mesh, causal=self.causal)
        elif self.schedule == "ulysses":
            o = ulysses_attention(q, k, v, self.mesh, causal=self.causal)
        elif self.schedule == "flash":
            from dmlc_tpu.ops.pallas_kernels import flash_attention

            o = flash_attention(q, k, v, causal=self.causal)
        elif self.schedule == "auto":
            from dmlc_tpu.ops.pallas_kernels import attention

            o = attention(q, k, v, causal=self.causal)
        else:
            o = dense_attention(q, k, v, causal=self.causal)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
        return nn.Dense(d, dtype=self.dtype, name="out")(o)


class SPTransformerBlock(nn.Module):
    """Pre-LN block: SP attention + position-wise MLP, both residual."""

    num_heads: int
    mlp_dim: int
    mesh: Mesh | None = None
    schedule: str = "ring"
    causal: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        a = SPSelfAttention(
            self.num_heads, self.mesh, self.schedule, self.causal, self.dtype, name="attn"
        )(nn.LayerNorm(dtype=self.dtype, name="ln1")(x))
        x = x + a
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = nn.gelu(nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_in")(h))
        return x + nn.Dense(x.shape[-1], dtype=self.dtype, name="mlp_out")(h)


class SPTransformerLM(nn.Module):
    """A small causal LM over sequence-parallel blocks: token embed ->
    N blocks -> tied-free head. Everything between attentions is
    position-wise, so the sequence axis stays sp-sharded end to end."""

    vocab: int
    num_layers: int
    num_heads: int
    hidden: int
    mlp_dim: int
    max_len: int = 2048
    mesh: Mesh | None = None
    schedule: str = "ring"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):  # [B, S] int32
        b, s = tokens.shape
        if s > self.max_len:
            # XLA gather would silently clamp out-of-range position indices
            # to the last embedding — wrong positional signal, no error.
            raise ValueError(f"sequence length {s} exceeds max_len {self.max_len}")
        x = nn.Embed(self.vocab, self.hidden, dtype=self.dtype, name="embed")(tokens)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype, name="pos_embed")(
            jnp.arange(s)[None, :]
        )
        x = x + pos  # position-wise: stays sp-sharded
        for i in range(self.num_layers):
            x = SPTransformerBlock(
                self.num_heads,
                self.mlp_dim,
                self.mesh,
                self.schedule,
                causal=True,
                dtype=self.dtype,
                name=f"block{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(self.vocab, dtype=self.dtype, name="head")(x)  # [B, S, V]
