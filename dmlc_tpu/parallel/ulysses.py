"""Ulysses attention: all-to-all sequence/context parallelism over ``sp``.

The second of the two standard sequence-parallel schedules (the first,
ring attention, is parallel/ring_attention.py — the reference has neither,
SURVEY.md §5 "long-context: entirely absent"). Where the ring rotates K/V
blocks device-to-device and keeps the sequence sharded throughout, Ulysses
(DeepSpeed-Ulysses style) *re-shards* around the attention op: inputs arrive
sequence-sharded ``[B, H, S/n, Dh]``, one ``all_to_all`` per tensor swaps the
sharded axis from sequence to heads ``[B, H/n, S, Dh]``, each device runs
ordinary dense attention for its head slice over the FULL sequence, and one
``all_to_all`` on the output swaps back.

Trade-offs vs the ring (why both exist):

- communication: Ulysses moves each of q/k/v/o exactly once through an
  all-to-all (O(S·Dh·H/n) per device, bandwidth-optimal, latency-batched);
  the ring issues n-1 dependent ppermute steps — Ulysses wins when the
  all-to-all fits ICI comfortably and n is large, the ring wins when
  compute per block is big enough to hide every hop.
- constraint: Ulysses needs ``H % n == 0`` (heads are the resharded axis);
  ring attention has no head constraint.
- memory: each device materializes its head slice's full [S, S] scores
  unless the local attention is itself blockwise; the ring never holds more
  than an [S/n, S/n] tile. For the extreme sequence lengths the ring is the
  memory-safe choice; Ulysses is the throughput choice for moderate S.

Both are `shard_map` programs over the same mesh axis, so callers can pick
per-call. The collectives ride ICI when ``sp`` is laid out within a pod.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dmlc_tpu.parallel.compat import shard_map

from dmlc_tpu.parallel.ring_attention import dense_attention


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, scale: float, local_attn=None):
    """Per-device body. q/k/v: [B, H, S/n, Dh] -> same shape/sharding.

    all_to_all(split_axis=1, concat_axis=2) turns the local sequence shard
    into the full sequence for H/n heads; attention is then embarrassingly
    parallel over heads, and the inverse all_to_all restores sequence
    sharding. Differentiable end-to-end (all_to_all transposes to itself
    with the axes swapped). ``local_attn`` swaps the per-device attention
    (default dense; the Pallas flash kernel composes here for O(S) memory
    on the reassembled sequence).
    """
    attn = local_attn or dense_attention
    a2a = partial(lax.all_to_all, axis_name=axis_name, tiled=True)
    # [B, H, S/n, Dh] -> [B, H/n, S, Dh]: heads scatter, sequence gathers.
    qh, kh, vh = (a2a(t, split_axis=1, concat_axis=2) for t in (q, k, v))
    out = attn(qh, kh, vh, causal=causal, scale=scale)
    # [B, H/n, S, Dh] -> [B, H, S/n, Dh].
    return a2a(out, split_axis=2, concat_axis=1)


def ulysses_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    scale: float | None = None,
    use_flash: bool = False,
):
    """Sequence-parallel attention via head/sequence all-to-all resharding.

    q/k/v: [B, H, S, Dh] with S sharded over ``axis_name`` in ``mesh``;
    returns [B, H, S, Dh] with the same sharding. Requires the head count to
    be divisible by the ``sp`` extent (checked eagerly — the failure inside
    all_to_all is far less readable). ``use_flash`` runs the per-device
    attention with the blockwise Pallas kernel (ops/pallas_kernels.py)
    instead of dense — sp handles sequences past one chip, flash keeps the
    reassembled full-sequence attention O(S) in memory."""
    n = mesh.shape[axis_name]
    if q.shape[1] % n:
        raise ValueError(
            f"ulysses needs heads % sp == 0: {q.shape[1]} heads over sp={n}"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    local_attn = None
    if use_flash:
        from dmlc_tpu.ops.pallas_kernels import flash_attention

        local_attn = flash_attention
    spec = P(None, None, axis_name, None)
    fn = partial(
        _ulysses_local, axis_name=axis_name, causal=causal, scale=scale, local_attn=local_attn
    )
    # check_vma off ONLY for the flash variant in INTERPRET mode (off-TPU):
    # interpret-mode pallas_call's discharge mixes varying and unvarying
    # operands inside dynamic_slice, which the vma checker rejects (jax
    # suggests exactly this workaround). Compiled TPU runs and the dense
    # variant keep full checking.
    check = not (use_flash and jax.default_backend() != "tpu")
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=check
    )(q, k, v)
