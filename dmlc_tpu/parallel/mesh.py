"""Device mesh construction and sharding rules.

The reference scales by running N copies of one binary on N VMs and assigning
whole models to whole hosts (src/services.rs:199-211). The TPU-native design
scales *inside* the model too: a `jax.sharding.Mesh` over the pod's chips with
named axes

- ``dp`` — data parallel (batch dimension; inference sharding)
- ``tp`` — tensor parallel (attention heads / MLP hidden)
- ``sp`` — sequence/context parallel (ring attention, long sequences)

XLA inserts the collectives (psum / all_gather / ppermute) implied by the
shardings, and they ride ICI when the mesh axes are laid out within a pod.
On multi-host deployments the mesh spans hosts (jax distributed runtime) and
DCN carries only the slow axis; the cluster substrate (dmlc_tpu.cluster) never
moves tensor bytes itself — that is the core divergence from the reference's
scp/tarpc data plane (src/services.rs:244-272).
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh with the given axis sizes, e.g. {'dp': 4, 'tp': 2}.

    Axis size -1 means "absorb all remaining devices". Default: all visible
    devices on a single ``dp`` axis (pure data-parallel inference, the
    reference's only strategy).
    """
    devs = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devs)}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if len(devs) % known:
            raise ValueError(f"{len(devs)} devices not divisible by {known}")
        sizes[sizes.index(-1)] = len(devs) // known
    if math.prod(sizes) != len(devs):
        raise ValueError(f"mesh {dict(zip(names, sizes))} wants {math.prod(sizes)} devices, have {len(devs)}")
    grid = np.asarray(devs, dtype=object).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dim over `axis`, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_spec(path: tuple[str, ...], leaf, tp_axis: str = "tp") -> P:
    """Tensor-parallel partition rules for the model zoo's parameter tree.

    Megatron-style: attention q/k/v and MLP-in shard the *output* feature dim
    (heads / hidden) over tp; attention-out and MLP-out shard the *input* dim,
    so the pair needs only one psum per block. Everything else (convs, norms,
    embeddings) is replicated — for the CNN families the win is dp+batch, and
    XLA would gain nothing from splitting 3x3 convs at these sizes.
    """
    names = [p for p in path]
    name = names[-2] if len(names) >= 2 else ""
    leaf_kind = names[-1] if names else ""
    if leaf_kind == "kernel" and leaf.ndim == 2:
        if name in ("query", "key", "value", "mlp_in"):
            return P(None, tp_axis)
        if name in ("out", "mlp_out"):
            return P(tp_axis, None)
        if name == "head":
            return P(None, tp_axis)  # vocab/class dim
    if leaf_kind == "bias" and name in ("query", "key", "value", "mlp_in"):
        return P(tp_axis)
    return P()


def param_shardings(mesh: Mesh, variables, tp_axis: str = "tp"):
    """Tree of NamedShardings for a flax variables pytree under `mesh`.

    If the mesh has no tp axis, everything is replicated (pure dp)."""
    has_tp = tp_axis in mesh.axis_names

    def one(path, leaf):
        spec = param_spec(tuple(str(getattr(p, "key", p)) for p in path), leaf, tp_axis) if has_tp else P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, variables)


def shard_params(mesh: Mesh, variables, tp_axis: str = "tp"):
    """Place a host-resident variables pytree onto the mesh per the rules."""
    shardings = param_shardings(mesh, variables, tp_axis)
    return jax.tree_util.tree_map(jax.device_put, variables, shardings)
