"""Batched, sharded inference engine — the TPU replacement for the
reference's per-image forward path.

Reference behavior being replaced (capability, not mechanism): a member
receives one synset id per RPC, decodes one JPEG, runs one 224x224 forward
under a model mutex on CPU, returns top-1 (src/services.rs:475-497). That
design caps at ~2 qps. Here the unit of work is a *shard*: a fixed-size uint8
image batch laid out over the mesh's ``dp`` axis, normalized on device and
driven through one jit-compiled XLA program — softmax + top-k included, so a
single fused program produces the answer and only tiny [B] arrays return to
the host.

Static shapes everywhere: partial shards are padded to ``batch_size`` (one
compile, ever) and the pad is masked out on the host side.
"""

from __future__ import annotations

import collections
import concurrent.futures
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from dmlc_tpu.cluster.devicemon import CensusedJit
from dmlc_tpu.models import get_model
from dmlc_tpu.ops import preprocess as pp
from dmlc_tpu.parallel import mesh as mesh_lib
from dmlc_tpu.utils.hotpath import hot_path
from dmlc_tpu.utils.metrics import LatencyStats
from dmlc_tpu.utils.tracing import tracer

# ---- persistent decode-stage pool -----------------------------------------
# Batch-granular decode tasks for run_paths_stream (each task itself fans
# out per image through ops.preprocess's cached pool / the native library's
# persistent pool). Module-level and lazily built ONCE — the old design
# created a ThreadPoolExecutor(max_workers=1) inside every run_paths_stream
# call, which both churned threads per shard and capped the decode stage at
# one batch in flight. Width is small on purpose: the per-image fan-out
# below it owns the cores; this pool only needs enough slots to keep
# ``prefetch`` batches decoding concurrently.
_STAGE_POOL: concurrent.futures.ThreadPoolExecutor | None = None
_STAGE_POOL_LOCK = threading.Lock()


def _stage_pool() -> concurrent.futures.ThreadPoolExecutor:
    global _STAGE_POOL
    with _STAGE_POOL_LOCK:
        if _STAGE_POOL is None:
            _STAGE_POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=max(2, min(4, os.cpu_count() or 2)),
                thread_name_prefix="ingest-decode",
            )
        return _STAGE_POOL


#: Stage names exported by InferenceEngine.ingest_summary(), in pipeline
#: order. "pipeline" records whole run_paths_stream walls, which is the
#: denominator for per-stage occupancy.
INGEST_STAGES = ("decode", "stage", "dispatch", "sync", "pipeline")


@dataclass
class BatchResult:
    top1_index: np.ndarray      # [N] int32 class indices (classifiers)
    top1_prob: np.ndarray       # [N] float32
    embeddings: np.ndarray | None  # [N, D] for embedding models
    # Wall seconds behind this result: the device execution for run_batch /
    # run_paths; the WHOLE pipeline (decode || transfer || compute) for
    # run_paths_stream.
    device_seconds: float


class InferenceEngine:
    """One model, one mesh, one compiled program."""

    def __init__(
        self,
        model_name: str,
        mesh: Mesh | None = None,
        variables: Any | None = None,
        dtype=jnp.bfloat16,
        batch_size: int = 256,
        seed: int = 0,
        use_pallas: bool | None = None,
        device_resize_from: int | None = None,
        device_work=None,
    ):
        self.spec = get_model(model_name)
        # Device-plane telemetry hook (cluster/devicemon.py): called with
        # (model, items, seconds) per device execution so the node's
        # DeviceMonitor can track achieved FLOP/s vs roofline. None = off.
        self.device_work = device_work
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh()
        self.batch_size = int(batch_size)
        # Optional device-side resize (ops/device_resize.py): the host ships
        # raw [B, R, R, 3] uint8 (R = device_resize_from, e.g. the corpus's
        # native/DCT-scaled size) and the chip resizes to the model's input
        # via MXU matmuls fused into the first conv — cutting the ~35% of
        # host CPU that resize costs (measured, ops/device_resize.py).
        self.device_resize_from = device_resize_from
        self.model = self.spec.module(dtype=dtype)
        if variables is None:
            _, variables = self.spec.init_params(jax.random.PRNGKey(seed), dtype=dtype)
        self.variables = mesh_lib.shard_params(self.mesh, variables)
        self._stats = LatencyStats()
        # Pallas kernels for normalize/top-1 are available but OPT-IN: XLA
        # already fuses both (measured parity, 14.3 vs 14.4 ms/batch for
        # ResNet-18 bs=256 on v5e), and the remote-tunnel backend's readiness
        # tracking for pallas outputs is unreliable, which breaks async
        # dispatch timing. The kernels earn their keep on the standalone
        # preprocessing path (ops/pallas_kernels.py) where there is no
        # adjacent op to fuse into.
        self.use_pallas = bool(use_pallas)

        mean_np, std_np = pp.stats_for_model(model_name)
        mean, std = jnp.asarray(mean_np), jnp.asarray(std_np)
        data_shd = mesh_lib.batch_sharding(self.mesh)
        classifier = self.spec.classifier

        resize_from = self.device_resize_from
        input_size = self.spec.input_size

        def forward(variables, u8):
            if resize_from is not None and resize_from != input_size:
                from dmlc_tpu.ops import device_resize

                x = device_resize.resize_batch(u8, input_size) / 255.0
                x = (x - mean) / std
            elif self.use_pallas:
                from dmlc_tpu.ops import pallas_kernels as pk

                x = pk.normalize_u8(u8, mean_np, std_np, jnp.float32)
            else:
                x = u8.astype(jnp.float32) / 255.0
                x = (x - mean) / std  # fused into the first conv's input by XLA
            out = self.model.apply(variables, x, train=False)
            if classifier:
                if self.use_pallas:
                    from dmlc_tpu.ops import pallas_kernels as pk

                    return pk.softmax_top1(out)
                probs = jax.nn.softmax(out, axis=-1)
                idx = jnp.argmax(probs, axis=-1).astype(jnp.int32)
                top = jnp.max(probs, axis=-1)
                return idx, top
            return out

        param_shd = mesh_lib.param_shardings(self.mesh, self.variables)
        # Outputs are pinned batch-sharded (not left to XLA): on a
        # multi-host mesh each process reads back exactly its own rows via
        # addressable shards (run_batch_global), which requires knowing the
        # output sharding; on a single host this changes nothing.
        out_shd = (data_shd, data_shd) if classifier else data_shd
        self._data_sharding = data_shd
        # Precomputed once (mesh and process layout are fixed for the
        # engine's lifetime): does the dp axis PARTITION batch rows by
        # process, as run_batch_global's row-ownership contract requires?
        # None = fine; else the error to raise there.
        self._global_batch_error: str | None = None
        procs = jax.process_count()
        if procs > 1 and "dp" in self.mesh.axis_names:
            axis = self.mesh.axis_names.index("dp")
            me = jax.process_index()
            dp_coords = {
                idx[axis]
                for idx, dev in np.ndenumerate(self.mesh.devices)
                if dev.process_index == me
            }
            dp_size = self.mesh.devices.shape[axis]
            rows_owned = len(dp_coords) * (self.batch_size // dp_size)
            coords = sorted(dp_coords)
            if rows_owned != self.batch_size // procs:
                self._global_batch_error = (
                    f"mesh layout puts {rows_owned} batch rows on process {me} "
                    f"but run_batch_global assumes {self.batch_size // procs} "
                    "(= batch/processes): the dp axis must partition rows by "
                    "process — lay dp over processes (slowest-varying mesh "
                    "axis), tp/sp within hosts"
                )
            elif coords != list(range(coords[0], coords[0] + len(coords))):
                # Non-contiguous dp coords would make local_rows' sort-by-
                # global-start disagree with the row order
                # make_array_from_process_local_data packed the local batch
                # in — results would come back silently permuted. Refuse.
                self._global_batch_error = (
                    f"process {me} owns non-contiguous dp coordinates {coords}: "
                    "run_batch_global requires each process's dp slice to be "
                    "one contiguous run so local row order matches global row "
                    "order — build the mesh with an unpermuted device list"
                )
        # Compile-census wrappers (cluster/devicemon.py): every jit site
        # carries a stable program label so steady-state recompiles are
        # attributable per program, not just per process.
        self._forward = CensusedJit(
            f"infer/{model_name}",
            jax.jit(forward, in_shardings=(param_shd, data_shd), out_shardings=out_shd),
        )
        # Stream-pipeline variant: donates the staged input buffer so XLA may
        # reuse its HBM while the pipeline stages the NEXT batch — the
        # double-buffered staging ring (run_paths_stream) owns each buffer
        # for exactly one dispatch. The shared _forward cannot donate: its
        # callers (run_batch, bench) re-dispatch the same device arrays.
        # CPU's PJRT backend doesn't implement donation (jax would warn on
        # every batch), so there the stream path reuses the plain program.
        if self.mesh.devices.flat[0].platform == "cpu":
            self._forward_stream = self._forward
        else:
            self._forward_stream = CensusedJit(
                f"infer/{model_name}/stream",
                jax.jit(
                    forward,
                    in_shardings=(param_shd, data_shd),
                    out_shardings=out_shd,
                    donate_argnums=(1,),
                ),
            )
        # Per-stage ingest pipeline counters (INGEST_STAGES): decode/stage/
        # dispatch record from pool threads too, hence the lock.
        self._ingest_lock = threading.Lock()
        self._ingest = {k: LatencyStats() for k in INGEST_STAGES}

    @property
    def input_size(self) -> int:
        """Host-side staging size: what decoded batches must be shaped as.
        With device resize active this is the RAW size; the model's input
        size is reached on the chip."""
        return self.device_resize_from or self.spec.input_size

    def load_variables(self, variables) -> None:
        """Hot-swap the model weights (the member side of the `train` verb,
        reference services.rs:139-144 + 513-524). The new tree must match the
        compiled program's structure; it is re-sharded onto the mesh with the
        same rules, so the jitted forward is reused without recompilation."""
        old = jax.tree_util.tree_flatten_with_path(self.variables)
        new = jax.tree_util.tree_flatten_with_path(variables)
        if old[1] != new[1]:
            raise ValueError(f"variables tree mismatch: {new[1]} != compiled {old[1]}")
        for (path, cur), (_, nxt) in zip(old[0], new[0]):
            if tuple(cur.shape) != tuple(np.shape(nxt)):
                raise ValueError(
                    f"shape mismatch at {jax.tree_util.keystr(path)}: "
                    f"got {tuple(np.shape(nxt))}, compiled {tuple(cur.shape)}"
                )
        self.variables = mesh_lib.shard_params(self.mesh, variables)

    def warmup(self) -> float:
        """Compile with a zero batch; returns compile+first-run seconds.
        The batch is a device-side constant (jnp, not np): a host zeros
        array would ship batch_size full images over the host->device link
        just to warm up — 150+ MB of nothing on a remote-tunnel TPU."""
        t0 = time.perf_counter()
        u8 = jnp.zeros((self.batch_size, self.input_size, self.input_size, 3), jnp.uint8)
        jax.block_until_ready(self._forward(self.variables, u8))
        return time.perf_counter() - t0

    def run_batch(self, batch_u8: np.ndarray) -> BatchResult:
        """Classify/embed up to ``batch_size`` images (uint8 NHWC)."""
        n = batch_u8.shape[0]
        if n == 0:
            raise ValueError("empty batch")
        if n > self.batch_size:
            raise ValueError(f"batch {n} exceeds engine batch_size {self.batch_size}")
        if n < self.batch_size:  # pad to the one compiled shape
            pad = np.zeros((self.batch_size - n, *batch_u8.shape[1:]), batch_u8.dtype)
            batch_u8 = np.concatenate([batch_u8, pad])
        t0 = time.perf_counter()
        out = self._forward(self.variables, batch_u8)
        out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self._stats.record(dt)
        tracer.record("device/forward", dt, model=self.spec.name, batch=int(n))
        if self.device_work is not None:
            self.device_work(self.spec.name, int(n), dt)
        if self.spec.classifier:
            idx, top = (np.asarray(o) for o in out)
            return BatchResult(idx[:n], top[:n], None, dt)
        emb = np.asarray(out)[:n]
        return BatchResult(np.zeros(n, np.int32), np.zeros(n, np.float32), emb, dt)

    def run_batch_global(self, local_u8: np.ndarray) -> BatchResult:
        """Multi-host SPMD inference: every process calls this with its OWN
        sub-batch; together they form one global batch over the mesh's dp
        axis, one XLA program runs across all hosts (collectives over
        ICI/DCN), and each process gets back results for the rows IT
        contributed. Single-host this degenerates to run_batch.

        The global batch shape stays static: each process pads its shard to
        ``batch_size / process_count`` (so ``batch_size`` must divide evenly
        by the process count). Row ownership follows
        ``jax.make_array_from_process_local_data``: the global array is this
        process's rows at its mesh positions, so the output's addressable
        shards are exactly the answers to this process's inputs.
        """
        procs = jax.process_count()
        local_cap = self.batch_size // procs
        if self.batch_size % procs:
            raise ValueError(
                f"batch_size {self.batch_size} not divisible by {procs} processes"
            )
        if self._global_batch_error is not None:  # precomputed in __init__
            raise ValueError(self._global_batch_error)
        n = local_u8.shape[0]
        if n > local_cap:
            raise ValueError(f"local batch {n} exceeds per-process share {local_cap}")
        if n < local_cap:
            # Pads even an EMPTY shard (dataset tail): every process must
            # enter the collective forward or the others deadlock in it.
            pad = np.zeros((local_cap - n, *local_u8.shape[1:]), local_u8.dtype)
            local_u8 = np.concatenate([local_u8, pad])
        t0 = time.perf_counter()
        global_u8 = jax.make_array_from_process_local_data(self._data_sharding, local_u8)
        out = jax.block_until_ready(self._forward(self.variables, global_u8))
        dt = time.perf_counter() - t0
        self._stats.record(dt)
        tracer.record("device/forward_global", dt, model=self.spec.name, batch=int(n))
        if self.device_work is not None:
            self.device_work(self.spec.name, int(n), dt)

        def local_rows(x) -> np.ndarray:
            # Dedupe on batch index: with a tp axis this process addresses
            # REPLICAS of its rows on several devices; concatenating them
            # all would silently double rows.
            seen: set = set()
            rows = []
            for s in sorted(x.addressable_shards, key=lambda s: (s.index[0].start or 0)):
                key = s.index[0].start or 0
                if key not in seen:
                    seen.add(key)
                    rows.append(np.asarray(s.data))
            return np.concatenate(rows)

        if self.spec.classifier:
            idx, top = (local_rows(o) for o in out)
            return BatchResult(idx[:n], top[:n], None, dt)
        emb = local_rows(out)[:n]
        return BatchResult(np.zeros(n, np.int32), np.zeros(n, np.float32), emb, dt)

    def run_paths(self, paths: Sequence[str], workers: int | None = None) -> BatchResult:
        """Decode + resize on host threads, then one device batch."""
        with tracer.span("host/decode", n=len(paths)):
            batch = pp.load_batch(paths, size=self.input_size, workers=workers)
        return self.run_batch(batch)

    @hot_path
    def run_paths_stream(
        self,
        paths: Sequence[str],
        workers: int | None = None,
        prefetch: int = 2,
        decode_source=None,
    ) -> BatchResult:
        """Decode overlapped with h2d transfer and device compute (SURVEY §7
        hard part b) — the three-stage ingest pipeline (docs/INGEST.md).

        1. **decode** — up to ``prefetch`` batches decode concurrently on the
           persistent stage pool (each batch itself fanning out per image
           via the native/PIL pool).
        2. **stage** — a double-buffered staging ring moves decoded batches
           onto the device (``jax.device_put`` with the batch sharding)
           ahead of dispatch, so the host->HBM transfer of batch i+1 rides
           under batch i's execution instead of inside its dispatch.
        3. **dispatch/compute** — staged buffers feed the jitted forward
           (input-donated off CPU, so the ring's HBM recycles), dispatched
           asynchronously and materialized two batches behind.

        Equivalent results to calling ``run_paths`` per batch, at up to
        min(decode_rate, device_rate) instead of their series combination.
        Every stage records into ingest_summary()/the tracer so bench.py's
        e2e leg can attribute wall time to decode vs stage vs compute vs
        sync.

        ``decode_source`` (optional) replaces the LOCAL per-batch decode
        with an external producer — ``decode_source(paths_chunk, size) ->
        uint8 [n, size, size, 3]`` — which is how the fleet decode tier
        (cluster/decodetier.py) plugs in: the prefetch stage still runs on
        the persistent stage pool and the staging ring/donation path below
        is untouched; only where the pixels come from changes.
        """
        if not paths:
            raise ValueError("empty path list")
        starts = list(range(0, len(paths), self.batch_size))
        prefetch = max(1, int(prefetch))
        pool = _stage_pool()

        def decode(s: int):
            chunk = paths[s : s + self.batch_size]
            t0 = time.perf_counter()
            with tracer.span("host/decode", n=len(chunk)):
                if decode_source is not None:
                    batch = decode_source(chunk, self.input_size)
                else:
                    batch = pp.load_batch(chunk, size=self.input_size, workers=workers)
            if len(chunk) < self.batch_size:
                pad = np.zeros(
                    (self.batch_size - len(chunk), *batch.shape[1:]), batch.dtype
                )
                batch = np.concatenate([batch, pad])
            self._record_stage("decode", time.perf_counter() - t0, batch=len(chunk))
            return len(chunk), batch

        t_all = time.perf_counter()
        outs: list[tuple[int, Any]] = []
        futs: collections.deque = collections.deque()
        next_i = 0
        while next_i < len(starts) and len(futs) < prefetch:
            futs.append(pool.submit(decode, starts[next_i]))
            next_i += 1
        staged: collections.deque = collections.deque()
        inflight: collections.deque = collections.deque()
        for _ in starts:
            # Fill the staging ring (depth 2): block on decode only when the
            # ring is empty; opportunistically stage a second batch when its
            # decode already finished, so the next dispatch finds its input
            # device-resident.
            while futs and len(staged) < 2 and (not staged or futs[0].done()):
                n, batch = futs.popleft().result()
                if next_i < len(starts):
                    futs.append(pool.submit(decode, starts[next_i]))
                    next_i += 1
                t0 = time.perf_counter()
                buf = jax.device_put(batch, self._data_sharding)
                self._record_stage("stage", time.perf_counter() - t0, batch=int(n))
                staged.append((n, buf))
            n, buf = staged.popleft()
            t0 = time.perf_counter()
            out = self._forward_stream(self.variables, buf)  # async dispatch
            self._record_stage("dispatch", time.perf_counter() - t0, batch=int(n))
            inflight.append((n, out))
            if len(inflight) > 2:  # sync two batches behind
                outs.append(self._materialize(*inflight.popleft()))
        while inflight:
            outs.append(self._materialize(*inflight.popleft()))
        total_dt = time.perf_counter() - t_all
        with self._ingest_lock:
            self._ingest["pipeline"].record(total_dt)
        if self.device_work is not None:
            # Pipeline wall, not isolated device time: on the stream path
            # the honest achieved-FLOP/s figure includes ingest stalls (a
            # decode-bound pipeline SHOULD read low MFU — that is the
            # signal that the host, not the chip, is the bottleneck).
            self.device_work(self.spec.name, len(paths), total_dt)

        if self.spec.classifier:
            idx = np.concatenate([np.asarray(o[0])[:n] for n, o in outs])
            top = np.concatenate([np.asarray(o[1])[:n] for n, o in outs])
            return BatchResult(idx, top, None, total_dt)
        emb = np.concatenate([np.asarray(o)[:n] for n, o in outs])
        return BatchResult(
            np.zeros(len(emb), np.int32), np.zeros(len(emb), np.float32), emb, total_dt
        )

    def _materialize(self, n: int, out):
        """Block on one in-flight device result. The recorded span is the
        SYNC WAIT — time the host stalls for the device — not the device's
        execution time: in a decode-bound pipeline the device finishes while
        the host decodes and this goes to ~0, which is exactly the signal
        that the host, not the device, is the bottleneck. (run_batch records
        true per-batch device latency into latency_summary.)"""
        t0 = time.perf_counter()
        # dmlc-lint: disable=A7 -- designed sync: _materialize IS the stream pipeline's two-behind backpressure barrier, and the wait is measured and exported as device/sync_wait rather than hidden
        out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        with self._ingest_lock:
            self._ingest["sync"].record(dt)
        tracer.record("device/sync_wait", dt, model=self.spec.name, batch=int(n))
        return n, out

    # ---- ingest pipeline observability ---------------------------------

    def _record_stage(self, stage: str, dt: float, **attrs) -> None:
        with self._ingest_lock:
            self._ingest[stage].record(dt)
        tracer.record(f"ingest/{stage}", dt, model=self.spec.name, **attrs)

    def ingest_summary(self) -> dict[str, dict[str, float]]:
        """Per-stage pipeline counters since construction (or the last
        reset): count, total busy seconds, mean, and occupancy — the stage's
        busy time over the summed run_paths_stream wall time, i.e. how much
        of the pipeline's life the stage spent working. The bottleneck stage
        reads near 1.0; in a well-overlapped pipeline the others still show
        substantial occupancy instead of summing to 1.0 (that sum-to-one
        shape is the serial-pipeline signature)."""
        with self._ingest_lock:
            wall = self._ingest["pipeline"]
            wall_total = wall.mean * wall.n if wall.n else 0.0
            out: dict[str, dict[str, float]] = {}
            for name, st in self._ingest.items():
                total = st.mean * st.n if st.n else 0.0
                entry = {
                    "count": float(st.n),
                    "total_s": total,
                    "mean_s": st.mean if st.n else 0.0,
                }
                if name != "pipeline":
                    entry["occupancy"] = total / wall_total if wall_total > 0 else 0.0
                out[name] = entry
            return out

    def reset_ingest_stats(self) -> None:
        with self._ingest_lock:
            self._ingest = {k: LatencyStats() for k in INGEST_STAGES}

    def latency_summary(self) -> dict[str, float]:
        return self._stats.summary()

    def resident_bytes(self) -> int:
        """Analytic device residency: the sharded weights pytree (this
        engine keeps no persistent activation state) — the per-model
        attribution behind the ``resident_bytes_<model>`` gauge."""
        from dmlc_tpu.cluster.devicemon import pytree_nbytes

        return pytree_nbytes(self.variables)
