"""Ring attention: sequence/context parallelism over the mesh's ``sp`` axis.

The reference has no sequence dimension anywhere (fixed 224x224 CNNs,
SURVEY.md §5 "long-context: entirely absent"), but long-context is first-class
here: sequences too long for one chip's HBM are sharded over ``sp``, each
device keeps its Q block resident, and K/V blocks rotate around the ring via
``ppermute`` (one ICI hop per step) while a numerically-stable online-softmax
(flash-attention style) accumulator absorbs each block. Peak memory per chip
is O(S/n) with n devices, compute overlaps the rotation, and no device ever
materializes the full [S, S] score matrix.

Implementation is `shard_map` over the mesh — the collective schedule is
explicit (ppermute), everything inside is plain jax the compiler can fuse.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-device body. q/k/v: [B, H, S_local, Dh] (this device's sequence block)."""
    n = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[2]
    q32 = q.astype(jnp.float32) * scale

    def one_block(carry, step):
        o, m, l, k_blk, v_blk = carry
        # Which global block the ring currently delivered to us: blocks move
        # to the next device each step, so at step i we hold (my_idx - i) % n.
        src = (my_idx - step) % n
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32))
        if causal:
            q_pos = my_idx * s_local + jnp.arange(s_local)
            k_pos = src * s_local + jnp.arange(k_blk.shape[2])
            mask = k_pos[None, :] <= q_pos[:, None]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # exp(-inf - -inf) guards: where a row is fully masked m_new stays -inf;
        # correction must then be 1, not nan.
        corr = jnp.where(jnp.isneginf(m_new), 1.0, jnp.exp(m - m_new))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        k_nxt, v_nxt = lax.ppermute(
            (k_blk, v_blk), axis_name, perm=[(j, (j + 1) % n) for j in range(n)]
        )
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    # Derive the zero carries from q32 so they inherit its varying-manual-axes
    # set (jax >= 0.9 vma tracking): the scan carry type must match the output,
    # which varies over every mesh axis q does (sp, and dp if batch-sharded).
    o0 = jnp.zeros_like(q32)
    m0 = jnp.full_like(q32[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q32[..., 0])
    (o, m, l, _, _), _ = lax.scan(one_block, (o0, m0, l0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q, k, v, mesh: Mesh, *, axis_name: str = "sp", causal: bool = False, scale: float | None = None
):
    """Sequence-parallel attention. q/k/v: [B, H, S, Dh] with S sharded over
    ``axis_name`` in ``mesh``; returns [B, H, S, Dh] with the same sharding."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, None, axis_name, None)
    fn = partial(_ring_attention_local, axis_name=axis_name, causal=causal, scale=scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)


def dense_attention(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Reference single-device attention for parity tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(s_k)[None, :] <= jnp.arange(s_q)[:, None]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)).astype(q.dtype)
