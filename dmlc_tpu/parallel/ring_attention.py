"""Ring attention: sequence/context parallelism over the mesh's ``sp`` axis.

The reference has no sequence dimension anywhere (fixed 224x224 CNNs,
SURVEY.md §5 "long-context: entirely absent"), but long-context is first-class
here: sequences too long for one chip's HBM are sharded over ``sp``, each
device keeps its Q block resident, and K/V blocks rotate around the ring via
``ppermute`` (one ICI hop per step) while a numerically-stable online-softmax
(flash-attention style) accumulator absorbs each block. Peak memory per chip
is O(S/n) with n devices, compute overlaps the rotation, and no device ever
materializes the full [S, S] score matrix.

Implementation is `shard_map` over the mesh — the collective schedule is
explicit (ppermute), everything inside is plain jax the compiler can fuse.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dmlc_tpu.parallel.compat import axis_size, shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-device body. q/k/v: [B, H, S_local, Dh] (this device's sequence block)."""
    n = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[2]
    q32 = q.astype(jnp.float32) * scale

    def one_block(carry, step):
        o, m, l, k_blk, v_blk = carry
        # Which global block the ring currently delivered to us: blocks move
        # to the next device each step, so at step i we hold (my_idx - i) % n.
        src = (my_idx - step) % n
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_blk.astype(jnp.float32))
        if causal:
            q_pos = my_idx * s_local + jnp.arange(s_local)
            k_pos = src * s_local + jnp.arange(k_blk.shape[2])
            mask = k_pos[None, :] <= q_pos[:, None]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # exp(-inf - -inf) guards: where a row is fully masked m_new stays -inf;
        # correction must then be 1, not nan.
        corr = jnp.where(jnp.isneginf(m_new), 1.0, jnp.exp(m - m_new))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        k_nxt, v_nxt = lax.ppermute(
            (k_blk, v_blk), axis_name, perm=[(j, (j + 1) % n) for j in range(n)]
        )
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    # Derive the zero carries from q32 so they inherit its varying-manual-axes
    # set (jax >= 0.9 vma tracking): the scan carry type must match the output,
    # which varies over every mesh axis q does (sp, and dp if batch-sharded).
    o0 = jnp.zeros_like(q32)
    m0 = jnp.full_like(q32[..., 0], -jnp.inf)
    l0 = jnp.zeros_like(q32[..., 0])
    (o, m, l, _, _), _ = lax.scan(one_block, (o0, m0, l0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q, k, v, mesh: Mesh, *, axis_name: str = "sp", causal: bool = False, scale: float | None = None
):
    """Sequence-parallel attention. q/k/v: [B, H, S, Dh] with S sharded over
    ``axis_name`` in ``mesh``; returns [B, H, S, Dh] with the same sharding."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, None, axis_name, None)
    fn = partial(_ring_attention_local, axis_name=axis_name, causal=causal, scale=scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)


# ---------------------------------------------------------------------------
# Ring attention COMPOSED with the pallas flash kernels: the per-step
# accumulator is the blockwise flash forward (out, lse) instead of an
# explicit [S_local, S_local] einsum, so per-chip memory is
# O(S_local * blk) per step (VERDICT r3 weak #6). Differentiable end to
# end: the custom VJP rings (k, v, dk, dv) together, each device adding its
# q rows' blockwise FlashAttention-2 gradients to whichever block it holds
# — after n rotations every block arrives home carrying its full gradient.
# ---------------------------------------------------------------------------


def _block_branches(my_idx, src, full_fn, diag_fn, masked_fn):
    """Three-way ring-step dispatch for CAUSAL attention: the block a device
    holds at a step is wholly before its rows (full attention), its own
    diagonal block (standard aligned causal masking — equal shards mean the
    local triangle IS the global one), or wholly after (no contribution).
    ``src``/``my_idx`` are traced per-device values, so this is a
    lax.switch, not Python control flow."""
    idx = (jnp.clip(my_idx - src, -1, 1) + 1).astype(jnp.int32)
    return lax.switch(idx, (masked_fn, diag_fn, full_fn), None)


def _merge_blocks(o32, lse, o_blk, lse_blk):
    """Exact log-sum-exp merge of two normalized partial attentions.
    All-masked contributions carry lse == -inf and weight 0."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(lse - lse_new))
    w_new = jnp.where(jnp.isneginf(lse_blk), 0.0, jnp.exp(lse_blk - lse_new))
    return o32 * w_old + o_blk.astype(jnp.float32) * w_new, lse_new


def _ring_flash_fwd_impl(axis_name, causal, scale, q, k, v):
    from dmlc_tpu.ops.pallas_kernels import flash_attention_with_lse

    n = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    q32 = q.astype(jnp.float32)

    def step_fn(carry, step):
        o, lse, k_blk, v_blk = carry
        src = (my_idx - step) % n

        def full(_):
            return flash_attention_with_lse(q, k_blk, v_blk, causal=False, scale=scale)

        def diag(_):
            return flash_attention_with_lse(q, k_blk, v_blk, causal=causal, scale=scale)

        def masked(_):
            return jnp.zeros_like(q), jnp.full_like(q32[..., :1], -jnp.inf)

        if causal:
            o_blk, lse_blk = _block_branches(my_idx, src, full, diag, masked)
        else:
            o_blk, lse_blk = full(None)
        o_new, lse_new = _merge_blocks(o, lse, o_blk, lse_blk)
        k_nxt, v_nxt = lax.ppermute(
            (k_blk, v_blk), axis_name, perm=[(j, (j + 1) % n) for j in range(n)]
        )
        return (o_new, lse_new, k_nxt, v_nxt), None

    o0 = jnp.zeros_like(q32)
    lse0 = jnp.full_like(q32[..., :1], -jnp.inf)
    (o, lse, _, _), _ = lax.scan(step_fn, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ring_flash(axis_name, causal, scale, q, k, v):
    return _ring_flash_fwd_impl(axis_name, causal, scale, q, k, v)[0]


def _ring_flash_vjp_fwd(axis_name, causal, scale, q, k, v):
    out, lse = _ring_flash_fwd_impl(axis_name, causal, scale, q, k, v)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, causal, scale, res, do):
    from dmlc_tpu.ops.pallas_kernels import flash_attention_block_bwd

    q, k, v, out, lse = res
    n = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    # Step-invariant softmax-jacobian row term, hoisted out of the ring:
    # each per-step block backward would otherwise recompute this full
    # reduction n times.
    delta = jnp.sum(
        out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1, keepdims=True
    )

    def step_fn(carry, step):
        dq_acc, k_blk, v_blk, dk_blk, dv_blk = carry
        src = (my_idx - step) % n

        def full(_):
            return flash_attention_block_bwd(
                q, k_blk, v_blk, out, lse, do, causal=False, scale=scale, delta=delta
            )

        def diag(_):
            return flash_attention_block_bwd(
                q, k_blk, v_blk, out, lse, do, causal=causal, scale=scale, delta=delta
            )

        def masked(_):
            return jnp.zeros_like(q), jnp.zeros_like(k_blk), jnp.zeros_like(v_blk)

        if causal:
            dq_c, dk_c, dv_c = _block_branches(my_idx, src, full, diag, masked)
        else:
            dq_c, dk_c, dv_c = full(None)
        # dq stays home; dk/dv travel WITH their block around the ring and
        # come home complete after n rotations. f32 carries: n bf16 adds
        # would drift, and gradients ride ICI only during the backward.
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_blk = dk_blk + dk_c.astype(jnp.float32)
        dv_blk = dv_blk + dv_c.astype(jnp.float32)
        k_nxt, v_nxt, dk_nxt, dv_nxt = lax.ppermute(
            (k_blk, v_blk, dk_blk, dv_blk),
            axis_name,
            perm=[(j, (j + 1) % n) for j in range(n)],
        )
        return (dq_acc, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    dq0 = jnp.zeros_like(q).astype(jnp.float32)
    dk0 = jnp.zeros_like(k).astype(jnp.float32)
    dv0 = jnp.zeros_like(v).astype(jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(step_fn, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention(
    q, k, v, mesh: Mesh, *, axis_name: str = "sp", causal: bool = False, scale: float | None = None
):
    """Ring attention whose per-step accumulator is the pallas flash kernel:
    same signature and sharding contract as ``ring_attention``, but no
    [S_local, S_local] score matrix exists at any point in forward OR
    backward — the enabler for S_local in the tens of thousands per chip."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, None, axis_name, None)
    fn = partial(_ring_flash, axis_name, causal, float(scale))
    # check_vma=False: the pallas interpreter (hermetic CPU tests) does not
    # yet propagate varying-manual-axes through its internal dynamic_slice
    # index operands; on TPU the kernels lower natively and the flag only
    # skips the static check (jax-ml/jax suggested workaround).
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def dense_attention(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Reference single-device attention for parity tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(s_k)[None, :] <= jnp.arange(s_q)[:, None]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)).astype(q.dtype)
