"""Sharded training step (dp x tp) over a device mesh.

The reference's "train" command only broadcasts pretrained weight files
(src/services.rs:139-144, README.md:21) — there is no gradient step anywhere.
This module supplies the real thing, TPU-first: a jit-compiled SPMD train step
where the batch is sharded over ``dp``, attention/MLP parameters over ``tp``
(Megatron-style, see parallel/mesh.py:param_spec), and XLA inserts the
gradient psum over dp and the activation collectives over tp automatically.

Works for both model families in the zoo: BatchNorm CNNs (ResNet — carries
``batch_stats``) and transformers (ViT/CLIP).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_tpu.parallel import mesh as mesh_lib


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any  # None for transformers
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads, new_batch_stats=None):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
            batch_stats=new_batch_stats if new_batch_stats is not None else self.batch_stats,
        )


def create_train_state(model, variables, tx) -> TrainState:
    params = variables["params"]
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        batch_stats=variables.get("batch_stats"),
        apply_fn=model.apply,
        tx=tx,
    )


def state_shardings(mesh: Mesh, state: TrainState, tp_axis: str = "tp"):
    """NamedShardings for the full train state.

    Optimizer moments mirror the param tree (their tree paths end with the
    same module/leaf names), so the single path-based rule in
    mesh_lib.param_spec covers params, mu, and nu alike; scalars and
    batch_stats fall through to replicated.
    """
    has_tp = tp_axis in mesh.axis_names

    def one(path, leaf):
        names = tuple(str(getattr(p, "key", getattr(p, "name", p))) for p in path)
        spec = mesh_lib.param_spec(names, leaf, tp_axis) if has_tp and hasattr(leaf, "ndim") else P()
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(one, state)


def cross_entropy(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def make_train_step(
    mesh: Mesh,
    state: TrainState,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    remat: bool = False,
    grad_accum: int = 1,
):
    """Returns (sharded_state, step_fn). step_fn(state, images_f32, labels) ->
    (state, metrics). One compiled SPMD program; state is donated.

    ``remat`` wraps the forward in ``jax.checkpoint``: activations are
    recomputed during the backward pass instead of saved, trading ~1/3 more
    FLOPs for O(sqrt)-ish activation memory — the standard TPU lever when a
    model's activations outgrow HBM (the MXU is rarely the binding
    constraint; HBM is).

    ``grad_accum`` > 1 splits the global batch into that many microbatches
    driven through a ``lax.scan`` (compiler-friendly: one compiled body, no
    Python unrolling), accumulating gradients and updating once — the lever
    for effective batch sizes whose activations don't fit even with remat.
    The batch must split evenly, and each microbatch stays dp-sharded, so
    ``batch % (grad_accum * dp) == 0``. BatchNorm stats chain through the
    scan in microbatch order.
    """
    shd = state_shardings(mesh, state, tp_axis)
    state = jax.tree_util.tree_map(jax.device_put, state, shd)
    data_shd = NamedSharding(mesh, P(dp_axis))
    label_shd = NamedSharding(mesh, P(dp_axis))
    has_bn = state.batch_stats is not None
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    def loss_fn(params, batch_stats, apply_fn, images, labels):
        variables = {"params": params}
        if has_bn:
            variables["batch_stats"] = batch_stats
            logits, mut = apply_fn(variables, images, train=True, mutable=["batch_stats"])
            return cross_entropy(logits, labels), (logits, mut["batch_stats"])
        logits = apply_fn(variables, images, train=True)
        return cross_entropy(logits, labels), (logits, None)

    if remat:
        # static_argnums: apply_fn is a function, not a traceable value.
        loss_fn = jax.checkpoint(loss_fn, static_argnums=(2,))

    def step_fn(state: TrainState, images, labels):
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        if grad_accum == 1:
            (loss, (logits, new_bn)), grads = grad_fn(
                state.params, state.batch_stats, state.apply_fn, images, labels
            )
            acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        else:
            dp = mesh.shape.get(dp_axis, 1)
            if images.shape[0] % (grad_accum * dp):
                raise ValueError(
                    f"batch {images.shape[0]} not divisible by "
                    f"grad_accum={grad_accum} x dp={dp} (each microbatch "
                    f"must still shard evenly over the dp axis)"
                )
            mb_images = images.reshape(grad_accum, -1, *images.shape[1:])
            mb_labels = labels.reshape(grad_accum, -1)

            def micro(carry, mb):
                bn, g_sum, loss_sum, acc_sum = carry
                imgs, lbls = mb
                (mb_loss, (logits, new_bn)), grads = grad_fn(
                    state.params, bn, state.apply_fn, imgs, lbls
                )
                mb_acc = jnp.mean((jnp.argmax(logits, -1) == lbls).astype(jnp.float32))
                g_sum = jax.tree_util.tree_map(jnp.add, g_sum, grads)
                return (new_bn, g_sum, loss_sum + mb_loss, acc_sum + mb_acc), None

            zeros = jax.tree_util.tree_map(jnp.zeros_like, state.params)
            zf = jnp.zeros((), jnp.float32)  # strong f32: scan carry types must match
            (new_bn, g_sum, loss_sum, acc_sum), _ = jax.lax.scan(
                micro, (state.batch_stats, zeros, zf, zf), (mb_images, mb_labels)
            )
            grads = jax.tree_util.tree_map(lambda g: g / grad_accum, g_sum)
            loss, acc = loss_sum / grad_accum, acc_sum / grad_accum
        new_state = state.apply_gradients(grads, new_batch_stats=new_bn)
        return new_state, {"loss": loss, "accuracy": acc}

    metric_shd = {"loss": NamedSharding(mesh, P()), "accuracy": NamedSharding(mesh, P())}
    compiled = jax.jit(
        step_fn,
        in_shardings=(shd, data_shd, label_shd),
        out_shardings=(shd, metric_shd),
        donate_argnums=0,
    )
    return state, compiled


def default_optimizer(lr: float = 1e-3, weight_decay: float = 1e-4) -> optax.GradientTransformation:
    return optax.adamw(lr, weight_decay=weight_decay)
