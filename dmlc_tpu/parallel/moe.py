"""Expert parallelism: Mixture-of-Experts layer sharded over an ``ep`` axis.

The reference has no expert parallelism (SURVEY.md §2: "Expert parallel:
Absent"). This is the TPU-idiomatic Mesh-TensorFlow/GShard formulation:
routing produces dense one-hot dispatch/combine tensors, expert compute is
one batched einsum over a leading expert axis, and the expert axis is
sharded over ``ep`` — under jit, XLA lowers the token->expert and
expert->token einsums to all_to_all collectives over ICI. No gather/scatter,
no ragged shapes, fully static: exactly the shape the MXU and the compiler
want.

Capacity semantics: each expert processes at most ``capacity`` tokens per
batch; overflow tokens fall through the residual connection (standard GShard
behavior), so shapes stay static regardless of routing skew.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def top1_routing(logits: jax.Array, capacity: int):
    """GShard-style top-1 routing with per-expert capacity.

    logits: [T, E]. Returns (dispatch [T, E, C] one-hot, combine [T, E, C]
    gate-weighted, aux_loss scalar).
    """
    t, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)              # [T, E]
    expert = jnp.argmax(gates, axis=-1)                   # [T]
    onehot = jax.nn.one_hot(expert, e, dtype=logits.dtype)  # [T, E]
    # Position of each token in its expert's queue (cumulative count).
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # [T, E], -1 elsewhere
    kept = (position >= 0) & (position < capacity)
    pos_oh = jax.nn.one_hot(
        position.max(axis=-1).astype(jnp.int32), capacity, dtype=logits.dtype
    )  # [T, C]
    dispatch = onehot[:, :, None] * pos_oh[:, None, :] * kept.max(axis=-1)[:, None, None]
    gate = (gates * onehot).sum(-1)                       # [T] chosen gate value
    combine = dispatch * gate[:, None, None]
    # Load-balancing aux loss (Switch/GShard): mean_gates . mean_assignment * E
    density = onehot.mean(axis=0)
    density_proxy = gates.mean(axis=0)
    aux = (density * density_proxy).sum() * e
    return dispatch, combine, aux


def top2_routing(logits: jax.Array, capacity: int):
    """GShard top-2 routing with per-expert capacity.

    Each token goes to its two highest-gate experts (second choice masked
    off the first), gates renormalized over the pair so kept tokens mix to
    weight ~1. Second-choice tokens queue BEHIND every first-choice token
    at the same expert (the GShard position offset), so under pressure the
    primary assignment wins capacity. Returns (dispatch [T, E, C],
    combine [T, E, C], aux_loss) like top1_routing.
    """
    t, e = logits.shape
    if e < 2:
        raise ValueError(f"top-2 routing needs >= 2 experts, got {e}")
    gates = jax.nn.softmax(logits, axis=-1)                      # [T, E]
    expert1 = jnp.argmax(gates, axis=-1)                          # [T]
    mask1 = jax.nn.one_hot(expert1, e, dtype=logits.dtype)
    gates_wo1 = jnp.where(mask1 > 0, -jnp.inf, gates)
    expert2 = jnp.argmax(gates_wo1, axis=-1)
    mask2 = jax.nn.one_hot(expert2, e, dtype=logits.dtype)

    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - 1.0                # [T, E]
    # Second choices queue after ALL first choices at that expert.
    pos2 = (jnp.cumsum(mask2, axis=0) + mask1.sum(axis=0)[None, :]) * mask2 - 1.0

    g1 = (gates * mask1).sum(-1)                                  # [T]
    g2 = (gates * mask2).sum(-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def build(mask, pos, gate):
        kept = (pos >= 0) & (pos < capacity)
        pos_oh = jax.nn.one_hot(
            pos.max(axis=-1).astype(jnp.int32), capacity, dtype=logits.dtype
        )
        dispatch = mask[:, :, None] * pos_oh[:, None, :] * kept.max(axis=-1)[:, None, None]
        return dispatch, dispatch * gate[:, None, None]

    d1, c1 = build(mask1, pos1, g1)
    d2, c2 = build(mask2, pos2, g2)
    # Aux loss on the PRIMARY assignment (Switch/GShard convention).
    density = mask1.mean(axis=0)
    density_proxy = gates.mean(axis=0)
    aux = (density * density_proxy).sum() * e
    return d1 + d2, c1 + c2, aux


class MoEMlp(nn.Module):
    """Expert-parallel MLP block: router -> E expert FFNs -> combine.

    Input [T, D] tokens (flatten batch x sequence first), output [T, D].
    Expert params have leading axis E — shard it over ``ep`` with
    ``moe_param_shardings``.
    """

    num_experts: int
    hidden_dim: int
    capacity_factor: float = 1.25
    # 1 = Switch-style single expert per token; 2 = GShard top-2 (second
    # choice queues behind first choices, gates renormalized per pair).
    router_top_k: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        t, d = x.shape
        e = self.num_experts
        # Top-2 sends ~2x the tokens through experts; scale capacity with k
        # so the drop rate stays comparable across router settings.
        capacity = max(1, int(self.capacity_factor * self.router_top_k * t / e))
        router = nn.Dense(e, dtype=jnp.float32, param_dtype=jnp.float32, name="router")
        if self.router_top_k == 1:
            routing = top1_routing
        elif self.router_top_k == 2:
            routing = top2_routing
        else:
            raise ValueError(f"router_top_k must be 1 or 2, got {self.router_top_k}")
        dispatch, combine, aux = routing(router(x.astype(jnp.float32)), capacity)
        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.dtype)

        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(), (e, d, self.hidden_dim), jnp.float32
        )
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(), (e, self.hidden_dim, d), jnp.float32
        )
        # Token -> expert buffers: XLA lowers this to an all_to_all when the
        # e axis is sharded over ep.
        xs = jnp.einsum("tec,td->ecd", dispatch, x.astype(self.dtype))  # [E, C, D]
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xs, w_in.astype(self.dtype)))
        ys = jnp.einsum("ech,ehd->ecd", h, w_out.astype(self.dtype))    # [E, C, D]
        # Expert -> token combine (the reverse all_to_all) + residual for
        # dropped tokens (combine rows are all-zero for them).
        out = jnp.einsum("tec,ecd->td", combine, ys)
        self.sow("intermediates", "aux_loss", aux)
        return x + out.astype(x.dtype)


def moe_param_spec(path: tuple[str, ...], leaf) -> P:
    """Partition rule: expert weights shard their leading E axis over ep;
    the router stays replicated."""
    names = [str(p) for p in path]
    if any(n in ("w_in", "w_out") for n in names):
        return P("ep")
    return P()


def moe_param_shardings(mesh: Mesh, variables):
    def one(path, leaf):
        names = tuple(str(getattr(p, "key", p)) for p in path)
        return NamedSharding(mesh, moe_param_spec(names, leaf))

    return jax.tree_util.tree_map_with_path(one, variables)


def shard_moe_params(mesh: Mesh, variables):
    return jax.tree_util.tree_map(
        jax.device_put, variables, moe_param_shardings(mesh, variables)
    )
