"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``pp``
mesh axis.

The reference has no intra-model parallelism of any kind (SURVEY.md §2:
"Pipeline parallel: Absent" — every forward runs whole on one CPU). Here
pipelining is TPU-first: each device along ``pp`` holds ONE stage's
parameters (stacked stage params sharded on their leading axis), and
activations move stage-to-stage with ``lax.ppermute`` — one ICI hop per
tick — inside a ``lax.scan`` systolic schedule. Microbatches fill the
pipeline, steady-state keeps every stage busy, and the drain phase empties
it: ``n_micro + n_stages - 1`` ticks total. The whole schedule is one
compiled XLA program; no Python control flow at dispatch time.

Composes with ``dp`` (shard the microbatch dim) and with the tp rules in
mesh.py (shard inside stage_fn's matmuls) on the same mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from dmlc_tpu.parallel.compat import axis_size, shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_stage_params: list) -> jax.Array:
    """Stack per-stage parameter pytrees along a new leading 'stage' axis;
    ``pipeline_apply`` shards that axis over pp via its shard_map in_specs."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def _pipeline_local(params, x, *, stage_fn, axis_name: str, n_micro: int):
    """Per-device body under shard_map.

    params: this stage's params (leading stage axis of size 1, squeezed).
    x: [n_micro_local? no — full] microbatched input [n_micro, mb, ...],
       meaningful on stage 0 (identical copies elsewhere are ignored).
    Returns [n_micro, mb, ...] outputs, valid on every device after the
    final broadcast (all devices return the last stage's results).
    """
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda a: a[0], params)  # drop stage axis
    mb_shape = x.shape[1:]

    # Probe the stage output shape/dtype statically.
    out_shape = jax.eval_shape(stage_fn, params, jax.ShapeDtypeStruct(mb_shape, x.dtype))
    assert out_shape.shape == mb_shape, (
        "pipeline stages must preserve activation shape "
        f"(got {out_shape.shape} from {mb_shape})"
    )

    total = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 injects microbatch t (zeros past the fill phase);
        # other stages consume what the ring delivered.
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        injected = lax.dynamic_index_in_dim(x, mb_idx, axis=0, keepdims=False)
        inp = jnp.where(stage == 0, injected, recv)
        out = stage_fn(params, inp)
        # Last stage banks microbatch (t - (n_stages-1)) when it's valid.
        done_idx = t - (n_stages - 1)
        outputs = jnp.where(
            (stage == n_stages - 1) & (done_idx >= 0),
            lax.dynamic_update_index_in_dim(
                outputs, out.astype(outputs.dtype), jnp.clip(done_idx, 0, n_micro - 1), axis=0
            ),
            outputs,
        )
        recv_next = lax.ppermute(out, axis_name, perm)
        return (recv_next, outputs), None

    recv0 = jnp.zeros(mb_shape, x.dtype)
    outputs0 = jnp.zeros((n_micro, *mb_shape), x.dtype)
    (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(total))
    # Broadcast the last stage's banked outputs to every pp rank so the
    # result has a plain replicated-over-pp layout.
    gathered = lax.all_gather(outputs, axis_name)  # [n_stages, n_micro, ...]
    return gathered[n_stages - 1]


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x,
    mesh: Mesh,
    *,
    n_micro: int,
    axis_name: str = "pp",
    batch_axis: str | None = "dp",
):
    """Run ``x`` through the pipeline.

    stage_fn(params, activation[mb, ...]) -> activation[mb, ...]
    stacked_params: pytree with leading stage axis == mesh.shape[axis_name]
    x: [batch, ...]; batch must divide into n_micro microbatches (and each
    microbatch over the mesh's ``batch_axis`` when present — dp and pp
    compose: every dp replica pipelines its own slice of each microbatch).
    Returns [batch, ...] outputs.
    """
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(f"batch {batch} not divisible into {n_micro} microbatches")
    mb = batch // n_micro
    use_dp = batch_axis is not None and batch_axis in mesh.axis_names
    if use_dp and mb % mesh.shape[batch_axis]:
        raise ValueError(
            f"microbatch {mb} not divisible over {batch_axis}={mesh.shape[batch_axis]}"
        )
    xm = x.reshape(n_micro, mb, *x.shape[1:])
    data_spec = P(None, batch_axis) if use_dp else P()

    param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    fn = partial(
        _pipeline_local, stage_fn=stage_fn, axis_name=axis_name, n_micro=n_micro
    )
    out = shard_map(
        fn,
        mesh=mesh,
        in_specs=(param_specs, data_spec),
        out_specs=data_spec,
        check_vma=False,  # outputs are made uniform over pp by the all_gather
    )(stacked_params, xm)
    return out.reshape(batch, *out.shape[2:])


def reference_apply(stage_fn: Callable, per_stage_params: list, x):
    """Sequential single-device reference for parity tests."""
    for p in per_stage_params:
        x = stage_fn(p, x)
    return x
