"""ctypes bindings for the native (C++) data-plane library.

``decode_resize_batch`` is the high-throughput replacement for the PIL path
in ops/preprocess.py — libjpeg DCT-domain downscaling + thread-pooled
triangle resampling (PIL BILINEAR semantics), one call per shard. The
library builds from native/ via make; when it is absent the callers fall
back to PIL transparently, so nothing in the framework hard-requires the
toolchain at runtime.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path

import numpy as np

log = logging.getLogger(__name__)

_LIB_PATH = Path(__file__).parent / "libdmlc_native.so"
_SRC_DIR = Path(__file__).parent.parent.parent / "native"
# v2: persistent decode pool (dmlc_pool_size/dmlc_pool_shutdown) replacing
# the spawn-and-join-per-call threading of v1.
_ABI_VERSION = 2

_lib = None
_load_failed = False


def _load():
    """Bind to an ALREADY-BUILT library. Never compiles: _load sits on the
    serving hot path (load_batch -> available()), and a surprise g++ run
    there would stall the first inference shard. Compilation happens only
    through ensure_built()/build(), called from node startup and bench."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if not _LIB_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
        if lib.dmlc_native_abi_version() != _ABI_VERSION:
            log.warning("native library ABI mismatch; rebuild with native.build()")
            return None
        lib.dmlc_decode_resize_batch.restype = ctypes.c_int
        lib.dmlc_decode_resize_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
        ]
        lib.dmlc_pool_size.restype = ctypes.c_int
        lib.dmlc_pool_size.argtypes = []
        lib.dmlc_pool_shutdown.restype = None
        lib.dmlc_pool_shutdown.argtypes = []
        _lib = lib
    except Exception as e:
        log.warning("native image pipeline unavailable (%s); using PIL", e)
        _load_failed = True
    return _lib


def build() -> None:
    """Compile the library (g++ via make). Raises on failure."""
    global _lib, _load_failed
    subprocess.run(
        ["make", "-s"], cwd=_SRC_DIR, check=True, capture_output=True, text=True
    )
    _lib, _load_failed = None, False  # rebind on next use


def _stale() -> bool:
    """Is the .so missing or older than any native source? Checked in
    Python so a prebuilt library on a toolchain-less host never spawns
    make (and fresh libraries are never needlessly re-linked under a
    concurrently-starting fleet)."""
    if not _LIB_PATH.exists():
        return True
    so_mtime = _LIB_PATH.stat().st_mtime
    sources = list(_SRC_DIR.glob("*.cpp")) + [_SRC_DIR / "Makefile"]
    return any(s.exists() and s.stat().st_mtime > so_mtime for s in sources)


def ensure_built() -> bool:
    """Build if missing or source-stale (best effort) and report
    availability. Call at node startup / bench setup — never from the
    per-shard path."""
    if not _load_failed and _stale():
        try:
            build()
        except Exception as e:
            log.warning("native build failed (%s); PIL fallback stays active", e)
    return available()


def available() -> bool:
    return _load() is not None


def decode_resize_batch(
    paths,
    size: int = 224,
    workers: int = 0,
    out: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Decode+resize JPEGs -> (uint8 [N, size, size, 3], status int32 [N]).

    ``out``, when given, is a caller-owned reusable arena the batch decodes
    into (C-contiguous uint8 [N, size, size, 3]) — repeated batches then
    allocate nothing per call; None allocates fresh. status[i] != 0 marks a
    failed decode (that slot is zeros). ``workers`` sizes the library's
    persistent worker pool (grow-only; 0 = hardware concurrency). Raises
    RuntimeError if the native library is unavailable — callers that want
    the automatic PIL fallback go through ops.preprocess.load_batch.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native image pipeline not available")
    n = len(paths)
    shape = (n, size, size, 3)
    if out is None:
        out = np.empty(shape, np.uint8)
    elif (
        not isinstance(out, np.ndarray)
        or out.shape != shape
        or out.dtype != np.uint8
        or not out.flags["C_CONTIGUOUS"]
    ):
        raise ValueError(f"out must be a C-contiguous uint8 array of shape {shape}")
    status = np.zeros(n, np.int32)
    if n == 0:
        return out, status
    c_paths = (ctypes.c_char_p * n)(*[str(p).encode() for p in paths])
    lib.dmlc_decode_resize_batch(
        c_paths,
        n,
        size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        status.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        int(workers),
    )
    return out, status


def pool_size() -> int:
    """Worker count of the library's persistent decode pool (0 before the
    first batch or when the library is absent)."""
    lib = _load()
    return int(lib.dmlc_pool_size()) if lib is not None else 0


def pool_shutdown() -> None:
    """Join the persistent pool's workers (no-op without the library).
    Restartable: the next decode call re-grows the pool."""
    lib = _load()
    if lib is not None:
        lib.dmlc_pool_shutdown()
