"""Replayable load generation + SLO certification over the sim fabric.

The observability plane (scrape trees, adaptive trace sampling, SLO burn
rates) is only trustworthy if it can be DEMONSTRATED against known traffic
— so this module replays a fully seeded workload through a simulated fleet
on the virtual clock and emits a certification document
(``slo_cert.json``, docs/OPERATIONS.md) any run with the same seed
reproduces byte-for-byte in its integer fields:

- **Open-loop arrivals** — an inhomogeneous Poisson process (Lewis-Shedler
  thinning against the peak rate), so load does NOT back off when the
  fleet slows down; that is what makes deadline misses and sheds honest.
- **Traffic shape** — a base rate modulated by a diurnal sinusoid and
  scripted flash crowds (start/duration/multiplier), mixing predict and
  generate requests across models by weight.
- **Simulated members** — each member admits through a token bucket on the
  virtual clock (overflow -> ``Overloaded`` shed), serves with a seeded
  jittered service time (a deterministic slow minority models stragglers,
  and queue pressure inflates them further), raising ``DeadlineExceeded``
  when the simulated service cannot fit the caller's remaining budget and
  occasionally evicting generate requests under pressure.
- **The real observability plane** — the leader scrapes through the real
  ``ScrapeTreeCoordinator``/``ScrapeDelegate`` tree, folds profiles with
  the real ``CostProfiler``/``SloEvaluator``, and the real tracer head-
  samples requests — errors force-recorded — so the certificate measures
  the plane this repo ships, not a mock of it.

The certificate pins: per-model p50/p99 vs objective, SLO burn rates
(read from the same ``SloEvaluator`` state the leader alerts on), shed /
deadline / eviction counts, leader scrape-RPC cost vs the 4*sqrt(N)
tree bound, sampling effectiveness, and that 100% of error and
deadline-exceeded request traces survived into the merged fleet trace.
``validate_slo_cert`` is the schema gate CI runs (tools/slo_cert.py).
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

from dmlc_tpu.cluster import observe, tenant as tenant_mod, tracectx
from dmlc_tpu.cluster.critpath import CritPathAnalyzer, FleetCritPath
from dmlc_tpu.cluster.flight import FlightRecorder
from dmlc_tpu.cluster.profile import CostProfiler
from dmlc_tpu.cluster.sentinel import DriftSentinel
from dmlc_tpu.cluster.rpc import (
    DeadlineExceeded,
    Overloaded,
    RpcError,
    RpcUnreachable,
    SimRpcNetwork,
)
from dmlc_tpu.cluster.scrapetree import ScrapeDelegate, ScrapeTreeCoordinator
from dmlc_tpu.scheduler.autoscaler import Autoscaler, ScaleTarget
from dmlc_tpu.scheduler.placement import SloEvaluator, SloObjective, tenant_lane
from dmlc_tpu.utils import tracing
from dmlc_tpu.utils.metrics import Registry
from dmlc_tpu.utils.tracing import traced_methods

SLO_CERT_VERSION = 1

# Per-request deadline budget by traffic kind (seconds of virtual time).
KIND_DEADLINE_S = {"predict": 0.5, "generate": 2.0}

# Mean simulated service time by kind; jittered per request, inflated on
# the deterministic slow minority and again under admission pressure.
KIND_SERVICE_S = {"predict": 0.08, "generate": 0.45}


# ---------------------------------------------------------------------------
# Traffic shape
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TrafficMix:
    """One slice of the offered traffic: a model served by one kind of
    request, drawn with probability proportional to ``weight``, on behalf
    of ``tenant`` (cluster/tenant.py; the default tenant is the legacy
    single-tenant traffic, byte-identical on the wire)."""

    model: str
    kind: str  # "predict" | "generate"
    weight: float = 1.0
    tenant: str = tenant_mod.DEFAULT_TENANT


@dataclass(frozen=True)
class FlashCrowd:
    """A scripted step burst: rate multiplies by ``multiplier`` for
    ``duration_s`` starting at ``start_s`` (overlapping crowds stack).
    A crowd scoped to ``tenant`` multiplies ONLY that tenant's mixes —
    the tenant-isolation certification drives exactly this: tenant A
    surges 10x while tenant B's offered load never moves."""

    start_s: float
    duration_s: float
    multiplier: float
    tenant: str | None = None

    def factor_at(self, t: float, tenant: str | None = None) -> float:
        if self.tenant is not None and tenant is not None \
                and tenant != self.tenant:
            return 1.0
        return self.multiplier if self.start_s <= t < self.start_s + self.duration_s else 1.0


@dataclass(frozen=True)
class TrafficSpec:
    """A fully seeded workload description — same spec, same arrivals."""

    duration_s: float
    base_rps: float
    mixes: tuple[TrafficMix, ...]
    diurnal_amplitude: float = 0.0   # 0..1: rate swings +-amplitude
    diurnal_period_s: float = 86400.0
    flash_crowds: tuple[FlashCrowd, ...] = ()
    seed: int = 0

    def _diurnal_at(self, t: float) -> float:
        if self.diurnal_amplitude <= 0.0:
            return 1.0
        return 1.0 + self.diurnal_amplitude * math.sin(
            2.0 * math.pi * t / self.diurnal_period_s
        )

    def mix_rates_at(self, t: float) -> list[float]:
        """Per-mix instantaneous offered rate: the base split by weight,
        then modulated by the diurnal and by every crowd that applies to
        the mix's tenant (unscoped crowds apply to everyone)."""
        total_w = sum(max(0.0, m.weight) for m in self.mixes) or 1.0
        diurnal = self._diurnal_at(t)
        out = []
        for m in self.mixes:
            rate = self.base_rps * max(0.0, m.weight) / total_w * diurnal
            for crowd in self.flash_crowds:
                rate *= crowd.factor_at(t, m.tenant)
            out.append(max(0.0, rate))
        return out

    def rate_at(self, t: float) -> float:
        """Instantaneous offered rate (requests/s of virtual time)."""
        return sum(self.mix_rates_at(t))

    def peak_rate(self) -> float:
        """An upper bound on ``rate_at`` — the thinning envelope. Assumes
        the worst case of every crowd overlapping; a loose bound only
        costs rejected candidates, never correctness."""
        peak = self.base_rps * (1.0 + max(0.0, self.diurnal_amplitude))
        for crowd in self.flash_crowds:
            peak *= max(1.0, crowd.multiplier)
        return max(peak, 1e-9)

    def tenants(self) -> list[str]:
        """Every tenant the mixes name, default included, sorted."""
        return sorted({m.tenant for m in self.mixes})

    def to_wire(self) -> dict:
        return {
            "duration_s": self.duration_s,
            "base_rps": self.base_rps,
            "seed": self.seed,
            "diurnal_amplitude": self.diurnal_amplitude,
            "diurnal_period_s": self.diurnal_period_s,
            "mixes": [
                {"model": m.model, "kind": m.kind, "weight": m.weight,
                 # Default tenant omitted: a tenant-less spec's wire form
                 # (and thus its certificate) stays byte-identical.
                 **({"tenant": m.tenant}
                    if m.tenant != tenant_mod.DEFAULT_TENANT else {})}
                for m in self.mixes
            ],
            "flash_crowds": [
                {"start_s": c.start_s, "duration_s": c.duration_s,
                 "multiplier": c.multiplier,
                 **({"tenant": c.tenant} if c.tenant is not None else {})}
                for c in self.flash_crowds
            ],
        }


class OpenLoopArrivals:
    """Inhomogeneous Poisson arrivals by Lewis-Shedler thinning: candidate
    gaps are exponential at the peak rate; each candidate survives with
    probability ``rate_at(t) / peak``. Open-loop by construction — the
    schedule never waits for the system under test."""

    def __init__(self, spec: TrafficSpec):
        self.spec = spec
        self._rng = random.Random(spec.seed ^ 0xA11)
        if sum(max(0.0, m.weight) for m in spec.mixes) <= 0:
            raise ValueError("TrafficSpec.mixes must carry positive weight")

    def _pick_mix(self, t: float) -> TrafficMix:
        """Draw a mix proportional to its INSTANTANEOUS rate: during a
        tenant-scoped flash crowd the surging tenant's mixes own most of
        the arrivals, exactly as a real crowd would. With no tenant-scoped
        crowds every mix scales identically and this reduces to the static
        weight draw (same RNG call count — legacy seeds replay bit-for-bit)."""
        rates = self.spec.mix_rates_at(t)
        total = sum(rates)
        x = self._rng.random() * total
        for mix, r in zip(self.spec.mixes, rates):
            x -= r
            if x <= 0:
                return mix
        return self.spec.mixes[-1]

    def __iter__(self) -> Iterator[tuple[float, TrafficMix]]:
        lam = self.spec.peak_rate()
        t = 0.0
        while True:
            t += self._rng.expovariate(lam)
            if t >= self.spec.duration_s:
                return
            if self._rng.random() * lam <= self.spec.rate_at(t):
                yield t, self._pick_mix(t)


# ---------------------------------------------------------------------------
# Simulated members
# ---------------------------------------------------------------------------


class SimMember:
    """One simulated serving member: token-bucket admission on the virtual
    clock, seeded jittered service times, deterministic stragglers, and
    kv-pressure evictions for generate traffic. Serves the REAL
    observability surface (ObsService + ScrapeDelegate) next to the fake
    workload verbs, so scrapes and traces exercise production code."""

    SLOW_EVERY = 7        # every 7th member is a straggler
    SLOW_FACTOR = 4.0     # straggler service-time multiplier
    PRESSURE_GAIN = 3.0   # service inflation at full admission pressure
    EVICT_PRESSURE = 0.5   # generate evictions start above this utilization
    EVICT_P = 0.25         # ... with this probability
    # Per-stage decomposition of one simulated service: the critpath plane
    # attributes request time to (stage, member), so the sim reports where
    # its pretend time went. Fractions sum to 1.
    STAGE_FRACTIONS = (("decode", 0.35), ("compute", 0.65))

    def __init__(self, net: SimRpcNetwork, addr: str, index: int, *,
                 seed: int, capacity_qps: float, scrape_timeout_s: float,
                 tenants: dict[str, tenant_mod.TenantSpec] | None = None):
        self.net = net
        self.addr = addr
        self.slow = (index % self.SLOW_EVERY) == self.SLOW_EVERY - 1
        self.rng = random.Random((seed << 16) ^ (index * 0x9E37) ^ 0x51AB)
        self.registry = Registry()
        self.capacity_qps = max(1e-6, capacity_qps)
        self.burst = max(2.0, self.capacity_qps)
        self._tokens = self.burst
        self._last_refill = net.clock()
        # Per-tenant token buckets (the sim analogue of AdmissionGate's
        # TenantLedger): a declared tenant refills at share * capacity, so
        # its flash crowd drains ITS bucket and sheds typed over_quota
        # while the member-wide bucket — and every other tenant — keeps
        # serving. Empty = no enforcement, bit-identical legacy behavior.
        self.tenants = dict(tenants or {})
        self._tenant_buckets: dict[str, list[float]] = {}
        for name, spec in self.tenants.items():
            rate = max(1e-6, spec.share * self.capacity_qps)
            burst = max(2.0, rate)
            self._tenant_buckets[name] = [burst, net.clock(), rate, burst]
        # Evictions charged to a tenant whose OWN pressure was below the
        # eviction line (i.e. somebody else's surge would have been the
        # trigger). The quota ordering makes this structurally zero; the
        # counter exists so the certificate PROVES it rather than assumes.
        self.cross_tenant_evictions = 0
        # Injected per-stage slowdown ({stage: factor}) — the drift
        # scenario's fault: ONE member's decode turning 5x mid-replay.
        self.stage_slowdown: dict[str, float] = {}
        self.obs = observe.ObsService(self.registry, lane=addr)
        self.delegate = ScrapeDelegate(
            net.client(addr), timeout_s=scrape_timeout_s, concurrency=1,
            metrics=self.registry.counters,
        )
        net.serve(addr, self.methods())

    def set_stage_slowdown(self, stage: str, factor: float) -> None:
        """Inject (or clear, factor=1) a service-stage slowdown — the
        drift sentinel certification's mid-replay fault."""
        if factor == 1.0:
            self.stage_slowdown.pop(stage, None)
        else:
            self.stage_slowdown[stage] = float(factor)

    def set_capacity(self, capacity_qps: float) -> None:
        """Autoscaler actuation in the sim: a capacity change models
        replicas joining/leaving this member's serving pool. Buckets keep
        their current fill; only refill rates and ceilings move."""
        self.capacity_qps = max(1e-6, capacity_qps)
        self.burst = max(2.0, self.capacity_qps)
        self._tokens = min(self._tokens, self.burst)
        for name, spec in self.tenants.items():
            bucket = self._tenant_buckets[name]
            bucket[2] = max(1e-6, spec.share * self.capacity_qps)
            bucket[3] = max(2.0, bucket[2])
            bucket[0] = min(bucket[0], bucket[3])

    def methods(self) -> dict:
        table = traced_methods({
            "job.predict": self._serve_request,
            "job.generate": self._serve_request,
        })
        table.update(self.obs.methods())
        table.update(self.delegate.methods())
        return table

    def _admit(self, tenant: str) -> tuple[float, float]:
        """Take one token or shed; returns (member utilization, the
        pressure the requester's SERVICE should see) — with tenants
        enforced, that pressure is the requester's OWN bucket: over-share
        work queues behind its own quota (the sim analogue of the
        DynamicBatcher/SlotScheduler displacement ordering), so one
        tenant's surge inflates its own latency and eviction odds, never
        another tenant's within-quota work."""
        now = self.net.clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last_refill) * self.capacity_qps
        )
        self._last_refill = now
        utilization = 1.0 - self._tokens / self.burst
        evict_pressure = utilization
        bucket = self._tenant_buckets.get(tenant) if self.tenants else None
        if self.tenants:
            if bucket is None:
                # Unknown tenant: charged against the residual low-priority
                # share, exactly like TenantLedger's UNKNOWN_SHARE stance.
                spec = tenant_mod.spec_for(tenant, self.tenants)
                rate = max(1e-6, spec.share * self.capacity_qps)
                burst = max(2.0, rate)
                bucket = self._tenant_buckets[tenant] = [burst, now, rate, burst]
            bucket[0] = min(bucket[3], bucket[0] + (now - bucket[1]) * bucket[2])
            bucket[1] = now
            evict_pressure = 1.0 - bucket[0] / bucket[3]
            if bucket[0] < 1.0:
                self.registry.counters.inc("shed")
                self.registry.counters.inc("shed_over_quota")
                raise Overloaded(
                    f"{self.addr}: tenant {tenant!r} at quota",
                    retry_after_s=0.1, tenant=tenant, quota="over_quota",
                )
        if self._tokens < 1.0:
            self.registry.counters.inc("shed")
            raise Overloaded(
                f"{self.addr}: admission queue full", retry_after_s=0.1,
                tenant=tenant, quota="gate_full",
            )
        self._tokens -= 1.0
        if bucket is not None:
            bucket[0] -= 1.0
        return utilization, evict_pressure

    def _serve_request(self, p: dict) -> dict:
        kind = str(p.get("kind") or "predict")
        # The ambient tenant, carried by the RPC frame's `n` field and
        # re-bound server-side (cluster/rpc.serve_with_deadline) — the
        # same wire threading production members see.
        tenant = tenant_mod.current()
        self.registry.counters.inc("requests")
        utilization, pressure = self._admit(tenant)
        service = KIND_SERVICE_S.get(kind, 0.1) * (0.5 + self.rng.random())
        if self.slow:
            service *= self.SLOW_FACTOR
        # With no tenant table, ``pressure`` IS the member utilization —
        # legacy runs are bit-identical. With tenants enforced it is the
        # requester's own-quota pressure, so a surging tenant's latency
        # degrades (and burns ITS SLO lane) while within-quota tenants
        # keep their service times.
        service *= 1.0 + self.PRESSURE_GAIN * pressure
        # Per-stage breakdown + injected slowdowns. The no-fault path adds
        # exactly 0.0, keeping legacy seeded latencies bit-identical; a
        # slowed stage stretches the total by its share * (factor - 1).
        stages = {
            stage: service * frac * self.stage_slowdown.get(stage, 1.0)
            for stage, frac in self.STAGE_FRACTIONS
        }
        service += sum(
            service * frac * (self.stage_slowdown.get(stage, 1.0) - 1.0)
            for stage, frac in self.STAGE_FRACTIONS
        )
        if (
            kind == "generate"
            and pressure > self.EVICT_PRESSURE
            and self.rng.random() < self.EVICT_P
        ):
            # Recorded assertion: with tenants enforced the eviction
            # trigger IS the requester's own-bucket pressure, so a
            # within-quota tenant can never stand here — mirroring
            # SlotScheduler's victim ordering. If a future edit decouples
            # trigger from victim, this counter (summed into the
            # certificate's cross_tenant_evictions, pinned at zero) is
            # what catches it.
            if self.tenants and pressure <= self.EVICT_PRESSURE:
                self.cross_tenant_evictions += 1
            self.registry.counters.inc("evicted")
            raise RpcError(f"evicted: {self.addr} kv-cache pressure")
        budget = float(p.get("deadline_s") or KIND_DEADLINE_S.get(kind, 1.0))
        if service >= budget:
            # The caller would wait out its whole budget; the sim raises
            # the same verdict the deadline fabric would without dragging
            # the shared virtual clock forward per straggler.
            self.registry.counters.inc("deadline_exceeded")
            raise DeadlineExceeded(
                f"{self.addr}/{kind}: simulated service {service:.3f}s "
                f"exceeds {budget:.3f}s budget"
            )
        self.registry.latency(f"rpc/job.{kind}").record(service)
        return {"service_s": service, "stages": stages}


# ---------------------------------------------------------------------------
# Request bookkeeping
# ---------------------------------------------------------------------------


@dataclass
class ModelTally:
    kind: str = "predict"
    requests: int = 0
    ok: int = 0
    shed: int = 0
    shed_over_quota: int = 0  # subset of shed: typed tenant-quota refusals
    deadline: int = 0
    evicted: int = 0
    error: int = 0
    latencies: list[float] = field(default_factory=list)

    def percentile(self, p: float) -> float | None:
        if not self.latencies:
            return None
        ordered = sorted(self.latencies)
        rank = max(0, math.ceil(p / 100.0 * len(ordered)) - 1)
        return ordered[rank]


class ReplayHarness:
    """One seeded certification run: N simulated members + a leader
    running the real scrape tree / profiler / SLO evaluator / tracer,
    driven by an ``OpenLoopArrivals`` schedule on the virtual clock.
    ``run()`` returns the ``slo_cert.json`` document."""

    def __init__(
        self,
        n_members: int,
        spec: TrafficSpec,
        *,
        objectives: dict[str, SloObjective] | None = None,
        sample_rate: float = 1.0,
        spans_per_s_budget: float = 0.0,
        scrape_interval_s: float = 10.0,
        scrape_timeout_s: float = 1.0,
        burn_force_sample_s: float = 15.0,
        fast_burn: float = 6.0,
        slow_burn: float = 1.5,
        fast_window_s: float | None = None,
        capacity_headroom: float = 2.0,
        tenants: dict[str, tenant_mod.TenantSpec] | None = None,
        autoscale: bool = False,
        autoscale_max_units: int = 8,
        autoscale_clear_windows: int = 3,
        autoscale_moves_budget: int = 2,
        drift: dict[str, Any] | None = None,
        sentinel_min_samples: int = 20,
        sentinel_confirm_windows: int = 3,
        sentinel_drift_factor: float = 2.0,
    ):
        if n_members < 2:
            raise ValueError("certification needs at least 2 members")
        self.spec = spec
        self.sample_rate = float(sample_rate)
        self.spans_per_s_budget = float(spans_per_s_budget)
        self.scrape_interval_s = float(scrape_interval_s)
        self.burn_force_sample_s = float(burn_force_sample_s)
        # Declared tenant table (cluster/tenant.py specs). When the spec's
        # mixes name tenants that aren't declared, they still flow — as
        # unknown low-priority tenants, like the production gates.
        self.tenant_specs = dict(tenants or {})

        self.net = SimRpcNetwork()
        self.leader_addr = "leader:0"
        self.member_addrs = [f"m{i:03d}:1" for i in range(n_members)]
        self.per_member_qps = capacity_headroom * spec.base_rps / n_members
        self.members = [
            SimMember(self.net, addr, i, seed=spec.seed,
                      capacity_qps=self.per_member_qps,
                      scrape_timeout_s=scrape_timeout_s,
                      tenants=self.tenant_specs)
            for i, addr in enumerate(self.member_addrs)
        ]
        self.leader_registry = Registry()
        self.leader_obs = observe.ObsService(
            self.leader_registry, lane=self.leader_addr
        )
        self.net.serve(self.leader_addr, self.leader_obs.methods())
        self.client = self.net.client(self.leader_addr)
        self.coordinator = ScrapeTreeCoordinator(
            self.client, clock=self.net.clock, timeout_s=scrape_timeout_s,
            concurrency=1, metrics=self.leader_registry.counters,
        )
        self.profiler = CostProfiler(
            window_s=5.0, windows=64, clock=self.net.clock, seed=spec.seed
        )
        # Root-cause plane under certification (OBSERVABILITY §9): every
        # served request's synthesized span DAG is charged into the REAL
        # critpath analyzer on the virtual clock; the fleet fold feeds burn
        # attribution and the REAL drift sentinel, exactly as on a leader.
        self.replan_requests: list[str] = []
        self.flight = FlightRecorder(clock=self.net.clock, node="loadgen")
        self.critpath = CritPathAnalyzer(
            window_s=float(scrape_interval_s), windows=16,
            clock=self.net.clock, seed=spec.seed,
        )
        self.fleet_critpath = FleetCritPath()
        self.sentinel = DriftSentinel(
            drift_factor=float(sentinel_drift_factor),
            min_samples=int(sentinel_min_samples),
            confirm_windows=int(sentinel_confirm_windows),
            force_sample_s=float(burn_force_sample_s) or 15.0,
            flight_note=self.flight.note,
            force_sample=self._drift_force_sample,
            request_replan=self.replan_requests.append,
        )
        # The injected fault: {"member": index, "stage": name, "factor": x,
        # "at_fraction": when} — ONE member's stage slows mid-replay, and
        # the certificate must show the sentinel naming it.
        self.drift = dict(drift) if drift else None
        self._drift_applied = False
        self._drift_injected_cycle: int | None = None
        self._drift_alert_cycle: int | None = None
        self.drift_alerts: list[dict[str, Any]] = []
        self.drift_force_windows = 0
        self._trace_seq = 0
        if objectives is None:
            objectives = self.default_objectives(spec)
        self.objectives = objectives
        # The fast window bounds detection latency: the evaluator needs
        # roughly fast_burn * error_budget * window of over-objective
        # samples before it alerts, so a tight-convergence scenario (the
        # autoscaler certification) passes a short window here.
        if fast_window_s is None:
            fast_window_s = min(30.0, spec.duration_s)
        self.slo = SloEvaluator(
            self.profiler, objectives,
            fast_window_s=min(float(fast_window_s), spec.duration_s),
            slow_window_s=spec.duration_s,
            fast_burn=fast_burn, slow_burn=slow_burn, stage="dispatch",
            metrics=self.leader_registry.counters,
            # Per-tenant burn lanes: every non-default tenant the traffic
            # names gets its own model@tenant lane, scored against the
            # model objective on that tenant's traffic only.
            tenants=[t for t in spec.tenants()
                     if t != tenant_mod.DEFAULT_TENANT],
            flight=self.flight,
            # Burn alerts name their critical-path culprit — the field the
            # certificate's critpath gate requires on every burn event.
            attribution=self.fleet_critpath.culprit,
        )
        self._dispatch_rng = random.Random(spec.seed ^ 0xD15)
        self.tallies: dict[str, ModelTally] = {}
        # tenant -> model -> tally (the certificate's per-tenant section).
        self.tenant_tallies: dict[str, dict[str, ModelTally]] = {}
        self.error_traces: set[str] = set()
        self.scrape_cycles = 0
        self.leader_scrape_rpcs = 0
        self.stale_spans_total = 0
        self.redelegations_total = 0
        self.force_windows = 0
        # The elastic loop under certification (scheduler/autoscaler.py):
        # the REAL Autoscaler on the virtual clock, actuating simulated
        # capacity units (each unit = the baseline per-member qps, i.e. a
        # replica's worth of serving). The certificate pins convergence:
        # scale-up within the fast-burn windows, scale-down after quiet.
        self.autoscaler: Autoscaler | None = None
        self._capacity_units = 1
        self._first_burn_cycle: int | None = None
        self._first_up_cycle: int | None = None
        self._first_down_cycle: int | None = None
        self._breach_after_down = False
        if autoscale:
            self.autoscaler = Autoscaler(
                flight=self.flight,
                metrics=self.leader_registry.counters,
                clock=self.net.clock,
                clear_windows=autoscale_clear_windows,
                moves_budget=autoscale_moves_budget,
            )
            self.autoscaler.register(ScaleTarget(
                "sim_capacity",
                get=lambda: self._capacity_units,
                apply=self._apply_capacity_units,
                lo=1,
                hi=max(1, int(autoscale_max_units)),
            ))

    def _apply_capacity_units(self, units: int) -> int:
        self._capacity_units = max(1, int(units))
        for member in self.members:
            member.set_capacity(self.per_member_qps * self._capacity_units)
        return self._capacity_units

    def _drift_force_sample(self, seconds: float) -> None:
        """Sentinel actuation: a confirmed drift opens a forced-sampling
        window fleet-wide — the same hook a burning SLO uses — so the
        traces that explain the shift are captured while it is happening."""
        tracing.tracer.force_sampling(seconds)
        observe.force_fleet_sampling(
            self.client, self.member_addrs, seconds, timeout=1.0,
        )
        self.drift_force_windows += 1

    @staticmethod
    def default_objectives(spec: TrafficSpec) -> dict[str, SloObjective]:
        """One objective per model in the mix: a latency bound between the
        nominal and straggler service time for its kind, so a healthy
        fleet passes and a straggler-heavy one visibly burns budget."""
        out: dict[str, SloObjective] = {}
        for mix in spec.mixes:
            bound = KIND_SERVICE_S.get(mix.kind, 0.1) * 2.5
            out.setdefault(
                mix.model,
                SloObjective(model=mix.model, latency_s=bound, availability=0.95),
            )
        return out

    # ---- the drive loop ------------------------------------------------

    def run(self) -> dict:
        tracer = tracing.tracer
        prev_enabled = tracer.enabled
        tracer.reset()
        tracer.enabled = True
        tracer.set_sampling(
            rate=self.sample_rate, spans_per_s=self.spans_per_s_budget,
            clock=self.net.clock,
        )
        try:
            next_scrape = self.scrape_interval_s
            for t, mix in OpenLoopArrivals(self.spec):
                while next_scrape <= t:
                    if next_scrape > self.net.now:
                        self.net.advance(next_scrape - self.net.now)
                    self._scrape_cycle()
                    next_scrape += self.scrape_interval_s
                if t > self.net.now:
                    self.net.advance(t - self.net.now)
                self._dispatch(mix)
            while next_scrape <= self.spec.duration_s:
                if next_scrape > self.net.now:
                    self.net.advance(next_scrape - self.net.now)
                self._scrape_cycle()
                next_scrape += self.scrape_interval_s
            merged_trace = observe.collect_fleet_trace(
                self.client,
                [self.leader_addr, *self.member_addrs],
                timeout=5.0, clock_samples=1,
            )
            sampling = tracer.sampling_summary()
            return self._certificate(merged_trace, sampling)
        finally:
            # Restore the process-global tracer exactly as found: default
            # rate, controller off, REAL clock back in (the sim clock must
            # not leak into later users of the tracer).
            tracer.enabled = prev_enabled
            tracer.set_sampling(rate=1.0, spans_per_s=0.0, clock=time.monotonic)
            tracer.reset()

    def _scrape_cycle(self) -> None:
        result = self.coordinator.scrape(self.member_addrs)
        self.scrape_cycles += 1
        self.leader_scrape_rpcs += result.leader_rpcs
        self.stale_spans_total += len(result.stale_spans)
        self.redelegations_total += result.redelegations
        for addr, reply in result.members.items():
            self.profiler.ingest_scrape(addr, reply)
        # Root-cause fold BEFORE the SLO evaluation: the analyzer snapshot
        # lands in the fleet fold, the sentinel judges the folded table,
        # and only then does the evaluator run — so a burn alert fired
        # this cycle carries the freshest culprit attribution.
        self.fleet_critpath.fold("sim", self.critpath.snapshot())
        fired = self.sentinel.tick(self.fleet_critpath.table())
        if fired:
            self.drift_alerts.extend(fired)
            if self._drift_alert_cycle is None:
                self._drift_alert_cycle = self.scrape_cycles
        state = self.slo.evaluate()
        burning = self.slo.burning_models()
        if self.autoscaler is not None:
            if burning and self._first_burn_cycle is None:
                self._first_burn_cycle = self.scrape_cycles
            decisions = self.autoscaler.tick(
                burning, {lane: st.get("fast", 0.0)
                          for lane, st in state.items()},
            )
            for decision in decisions:
                if decision["direction"] == "up" \
                        and self._first_up_cycle is None:
                    self._first_up_cycle = self.scrape_cycles
                if decision["direction"] == "down" \
                        and self._first_down_cycle is None:
                    self._first_down_cycle = self.scrape_cycles
            if burning and self._first_down_cycle is not None \
                    and self.scrape_cycles > self._first_down_cycle:
                # A burn AFTER the scale-down would mean the shrink broke
                # the SLO it just restored — the flap the hysteresis and
                # clear-window discipline exist to prevent.
                self._breach_after_down = True
        if burning and self.burn_force_sample_s > 0:
            # The same hook the real leader runs (cluster/node.py): a model
            # burning budget flips the whole fleet to forced sampling.
            tracing.tracer.force_sampling(self.burn_force_sample_s)
            observe.force_fleet_sampling(
                self.client, self.member_addrs, self.burn_force_sample_s,
                timeout=1.0,
            )
            self.force_windows += 1

    def _tally_pair(self, mix: TrafficMix) -> tuple[ModelTally, ModelTally]:
        """(per-model aggregate, per-(tenant, model)) tallies for one
        request; both counted on every outcome so the certificate's tenant
        outcome counts sum exactly like the model ones."""
        tally = self.tallies.setdefault(mix.model, ModelTally(kind=mix.kind))
        per_tenant = self.tenant_tallies.setdefault(mix.tenant, {})
        tenant_tally = per_tenant.setdefault(mix.model, ModelTally(kind=mix.kind))
        return tally, tenant_tally

    def _record_latency(self, mix: TrafficMix, member: str,
                        latency: float) -> None:
        """One observed latency into the SLO lanes: the bare model lane
        (the aggregate every legacy consumer reads) AND, for a non-default
        tenant, the model@tenant composite the per-tenant burn is scored
        on."""
        self.profiler.record(mix.model, member, "dispatch", latency)
        lane = tenant_lane(mix.model, mix.tenant)
        if lane != mix.model:
            self.profiler.record(lane, member, "dispatch", latency)

    def _inject_drift_if_due(self) -> None:
        """Apply the configured mid-replay stage fault once its time
        arrives: ONE member's stage slows by the configured factor, and
        from here on the certificate's detection timeline is live."""
        if self.drift is None or self._drift_applied:
            return
        if self.net.now < float(self.drift.get("at_fraction", 0.5)) \
                * self.spec.duration_s:
            return
        idx = int(self.drift.get("member", 0)) % len(self.members)
        stage = str(self.drift.get("stage", "decode"))
        factor = float(self.drift.get("factor", 5.0))
        self.members[idx].set_stage_slowdown(stage, factor)
        self._drift_applied = True
        self._drift_injected_cycle = self.scrape_cycles
        self.flight.note(
            "drift_injected", member=self.member_addrs[idx],
            stage=stage, factor=factor,
        )

    def _emit_trace(self, mix: TrafficMix, member: str, latency: float,
                    stages: dict[str, Any]) -> None:
        """Synthesize the served request's span DAG — the same tree the
        real dispatch path traces (root -> dispatch -> rpc -> host/decode
        then device/forward) — and charge it into the critpath analyzer,
        so burn attribution and the drift sentinel run on the real
        extraction math, not on the sim's own stage numbers."""
        self._trace_seq += 1
        trace = f"sim{self._trace_seq}"
        sid = f"{trace}-"
        t0 = self.net.now
        decode_s = max(0.0, float(stages.get("decode", 0.0)))
        compute_s = max(0.0, float(stages.get("compute", 0.0)))
        self.critpath.ingest([
            {"name": "loadgen/request", "trace": trace, "span": sid + "root",
             "start": t0, "dur": latency, "attrs": {"model": mix.model}},
            {"name": "scheduler/dispatch", "trace": trace, "span": sid + "d",
             "parent": sid + "root", "start": t0, "dur": latency,
             "lane": self.leader_addr},
            {"name": f"rpc/job.{mix.kind}", "trace": trace, "span": sid + "r",
             "parent": sid + "d", "start": t0, "dur": latency,
             "lane": member},
            {"name": "host/decode", "trace": trace, "span": sid + "dec",
             "parent": sid + "r", "start": t0, "dur": decode_s,
             "lane": member},
            {"name": "device/forward", "trace": trace, "span": sid + "f",
             "parent": sid + "r", "start": t0 + decode_s, "dur": compute_s,
             "lane": member},
        ])

    def _dispatch(self, mix: TrafficMix) -> None:
        self._inject_drift_if_due()
        member = self.member_addrs[
            self._dispatch_rng.randrange(len(self.member_addrs))
        ]
        budget = KIND_DEADLINE_S.get(mix.kind, 1.0)
        tally, tenant_tally = self._tally_pair(mix)
        tally.requests += 1
        tenant_tally.requests += 1
        trace_id = ""
        try:
            with tenant_mod.bind(mix.tenant), tracing.tracer.span(
                "loadgen/request", model=mix.model, kind=mix.kind
            ):
                ctx = tracectx.current()
                trace_id = ctx.trace_id if ctx is not None else ""
                reply = self.client.call(
                    member, f"job.{mix.kind}",
                    {"model": mix.model, "kind": mix.kind, "deadline_s": budget},
                    timeout=budget,
                )
        except Overloaded as e:
            tally.shed += 1
            tenant_tally.shed += 1
            if getattr(e, "quota", None) == "over_quota":
                tally.shed_over_quota += 1
                tenant_tally.shed_over_quota += 1
            self.error_traces.add(trace_id)
            return
        except DeadlineExceeded:
            tally.deadline += 1
            tenant_tally.deadline += 1
            tally.latencies.append(budget)
            tenant_tally.latencies.append(budget)
            self.error_traces.add(trace_id)
            # The caller waited its whole budget: that latency is real and
            # lands in the SLO lane as an over-objective observation.
            self._record_latency(mix, member, budget)
            return
        except (RpcUnreachable, RpcError) as e:
            if "evicted:" in str(e):
                tally.evicted += 1
                tenant_tally.evicted += 1
            else:
                tally.error += 1
                tenant_tally.error += 1
            self.error_traces.add(trace_id)
            return
        tally.ok += 1
        tenant_tally.ok += 1
        latency = float(reply["service_s"])
        tally.latencies.append(latency)
        tenant_tally.latencies.append(latency)
        self._record_latency(mix, member, latency)
        stages = reply.get("stages")
        if isinstance(stages, dict):
            self._emit_trace(mix, member, latency, stages)

    # ---- certificate ---------------------------------------------------

    @staticmethod
    def _jsonsafe(value):
        """NaN/inf -> None recursively: the certificate must be strict
        JSON (the profiler's percentile is NaN on an empty lane)."""
        if isinstance(value, float) and not math.isfinite(value):
            return None
        if isinstance(value, dict):
            return {k: ReplayHarness._jsonsafe(v) for k, v in value.items()}
        if isinstance(value, list):
            return [ReplayHarness._jsonsafe(v) for v in value]
        return value

    def _certificate(self, merged_trace: dict, sampling: dict) -> dict:
        slo_status = self.slo.status()
        merged_trace_ids = {
            ev["args"]["trace"]
            for ev in merged_trace.get("traceEvents", ())
            if ev.get("ph") == "X" and "trace" in (ev.get("args") or {})
        }
        error_traces = {t for t in self.error_traces if t}
        present = error_traces & merged_trace_ids
        n = len(self.member_addrs)
        cycles = max(1, self.scrape_cycles)
        obs_calls = sum(
            1 for _, method in self.net.calls if method.startswith("obs.")
        )
        models: dict[str, dict] = {}
        for model in sorted(self.tallies):
            tally = self.tallies[model]
            slo_model = (slo_status.get("models") or {}).get(model, {})
            models[model] = {
                "kind": tally.kind,
                "requests": tally.requests,
                "ok": tally.ok,
                "shed": tally.shed,
                "shed_over_quota": tally.shed_over_quota,
                "deadline": tally.deadline,
                "evicted": tally.evicted,
                "error": tally.error,
                "p50_s": tally.percentile(50),
                "p99_s": tally.percentile(99),
                "objective_latency_s": slo_model.get("objective_latency_s"),
                "availability": slo_model.get("availability"),
                "fast_burn": slo_model.get("fast_burn", 0.0),
                "slow_burn": slo_model.get("slow_burn", 0.0),
                "fast_alert": slo_model.get("fast_alert", False),
                "slow_alert": slo_model.get("slow_alert", False),
            }
        extra: dict[str, dict] = {}
        tenants_doc = self._tenants_section()
        if tenants_doc is not None:
            extra["tenants"] = tenants_doc
        autoscaler_doc = self._autoscaler_section()
        if autoscaler_doc is not None:
            extra["autoscaler"] = autoscaler_doc
        extra["critpath"] = self._critpath_section()
        return self._jsonsafe({
            "version": SLO_CERT_VERSION,
            "seed": self.spec.seed,
            "spec": {
                **self.spec.to_wire(),
                "members": n,
                "sample_rate": self.sample_rate,
                "spans_per_s_budget": self.spans_per_s_budget,
                "scrape_interval_s": self.scrape_interval_s,
            },
            "models": models,
            "slo": slo_status,
            "observability": {
                "scrape_cycles": self.scrape_cycles,
                "leader_scrape_rpcs_total": self.leader_scrape_rpcs,
                "leader_rpcs_per_cycle_avg": self.leader_scrape_rpcs / cycles,
                "members": n,
                "direct_equivalent_rpcs_per_cycle": n,
                "sqrt_bound_rpcs_per_cycle": 4.0 * math.sqrt(n),
                "bound_ok": (
                    self.leader_scrape_rpcs / cycles <= 4.0 * math.sqrt(n)
                ),
                "stale_spans_total": self.stale_spans_total,
                "redelegations_total": self.redelegations_total,
                "scrape_rpc_fraction": (
                    obs_calls / len(self.net.calls) if self.net.calls else 0.0
                ),
                "force_windows": self.force_windows,
                "sampling": sampling,
            },
            "traces": {
                "error_requests": len(error_traces),
                "error_traces_in_merged": len(present),
                "all_errors_sampled": error_traces <= merged_trace_ids,
                "merged_events": sum(
                    1 for ev in merged_trace.get("traceEvents", ())
                    if ev.get("ph") == "X"
                ),
            },
            **extra,
        })

    def _tenants_section(self) -> dict | None:
        """Per-tenant certification: outcome counts per (tenant, model),
        each tenant-model p99 judged against the MODEL's objective, and
        the fleet-summed cross-tenant eviction count the isolation pin
        requires to be zero. Absent entirely for tenant-less traffic —
        legacy certificates don't grow a section of empty rows."""
        only_default = set(self.tenant_tallies) <= {tenant_mod.DEFAULT_TENANT}
        if not self.tenant_specs and only_default:
            return None
        tenants: dict[str, dict] = {}
        for tenant in sorted(set(self.tenant_tallies) | set(self.tenant_specs)):
            spec = tenant_mod.spec_for(tenant, self.tenant_specs)
            per_model: dict[str, dict] = {}
            totals = ModelTally()
            for model, tally in sorted(
                (self.tenant_tallies.get(tenant) or {}).items()
            ):
                objective = self.objectives.get(model)
                p99 = tally.percentile(99)
                per_model[model] = {
                    "kind": tally.kind,
                    "requests": tally.requests,
                    "ok": tally.ok,
                    "shed": tally.shed,
                    "shed_over_quota": tally.shed_over_quota,
                    "deadline": tally.deadline,
                    "evicted": tally.evicted,
                    "error": tally.error,
                    "p50_s": tally.percentile(50),
                    "p99_s": p99,
                    "objective_latency_s": (
                        objective.latency_s if objective else None
                    ),
                    "certified": (
                        p99 is None or objective is None
                        or p99 <= objective.latency_s
                    ),
                }
                totals.requests += tally.requests
                totals.ok += tally.ok
                totals.shed += tally.shed
                totals.shed_over_quota += tally.shed_over_quota
                totals.deadline += tally.deadline
                totals.evicted += tally.evicted
                totals.error += tally.error
            tenants[tenant] = {
                "priority": spec.priority,
                "share": spec.share,
                "requests": totals.requests,
                "ok": totals.ok,
                "shed": totals.shed,
                "shed_over_quota": totals.shed_over_quota,
                "deadline": totals.deadline,
                "evicted": totals.evicted,
                "error": totals.error,
                "models": per_model,
                "certified": all(
                    body["certified"] for body in per_model.values()
                ),
            }
        return {
            "declared": sorted(self.tenant_specs),
            "cross_tenant_evictions": sum(
                m.cross_tenant_evictions for m in self.members
            ),
            "tenants": tenants,
        }

    def _critpath_section(self) -> dict:
        """Root-cause evidence: the folded critical-path table the culprit
        attribution reads, the sentinel's lane states, every burn and
        drift flight event, and — when a drift fault was injected — the
        detection timeline the certification pins (injection cycle, alert
        cycle, the alerts themselves, forced-sampling windows, replan
        requests)."""
        flight = self.flight.to_wire()
        burn_events = [e for e in flight["events"]
                       if e.get("kind") in ("slo_fast_burn", "slo_slow_burn")]
        drift_events = [
            e for e in flight["events"]
            if str(e.get("kind", "")).startswith(("latency_drift", "drift_"))
        ]
        out: dict[str, Any] = {
            "table": self.fleet_critpath.table(),
            "sentinel": self.sentinel.status(),
            "burn_events": burn_events,
            "drift_events": drift_events,
        }
        if self.drift is not None:
            cycles = None
            if self._drift_alert_cycle is not None \
                    and self._drift_injected_cycle is not None:
                cycles = self._drift_alert_cycle - self._drift_injected_cycle
            out["drift"] = {
                "spec": dict(self.drift),
                "injected_member": self.member_addrs[
                    int(self.drift.get("member", 0)) % len(self.members)
                ],
                "injected": self._drift_applied,
                "injected_cycle": self._drift_injected_cycle,
                "alert_cycle": self._drift_alert_cycle,
                "cycles_to_alert": cycles,
                "alerts": list(self.drift_alerts),
                "force_windows": self.drift_force_windows,
                "replan_requests": list(self.replan_requests),
            }
        return out

    def _autoscaler_section(self) -> dict | None:
        """Convergence evidence for the elastic loop: when the first burn
        was seen, how many scrape cycles until the first scale-up, whether
        the fleet scaled back down after quiet, and whether the SLO burned
        again AFTER the scale-down (it must not). The full decision ring —
        every one also flight-recorded — rides along."""
        if self.autoscaler is None:
            return None
        up_cycles = None
        if self._first_burn_cycle is not None and self._first_up_cycle is not None:
            up_cycles = self._first_up_cycle - self._first_burn_cycle + 1
        return {
            "enabled": True,
            "capacity_units": self._capacity_units,
            "first_burn_cycle": self._first_burn_cycle,
            "first_up_cycle": self._first_up_cycle,
            "first_down_cycle": self._first_down_cycle,
            "scale_up_cycles": up_cycles,
            "scaled_down": self._first_down_cycle is not None,
            "breach_after_scale_down": self._breach_after_down,
            "decisions": list(self.autoscaler.decisions),
            "flight_recorded": (
                self.flight.to_wire()["recorded"]
                if self.flight is not None else 0
            ),
        }


# ---------------------------------------------------------------------------
# Certificate schema gate
# ---------------------------------------------------------------------------

_NUM = (int, float)

# section -> {field: required types} — hand-rolled (no jsonschema dep);
# None in a type tuple marks the field as nullable.
_CERT_SHAPE: dict[str, dict[str, tuple]] = {
    "spec": {
        "duration_s": _NUM, "base_rps": _NUM, "seed": (int,),
        "members": (int,), "sample_rate": _NUM, "scrape_interval_s": _NUM,
        "mixes": (list,), "flash_crowds": (list,),
    },
    "observability": {
        "scrape_cycles": (int,), "leader_scrape_rpcs_total": (int,),
        "leader_rpcs_per_cycle_avg": _NUM, "members": (int,),
        "sqrt_bound_rpcs_per_cycle": _NUM, "bound_ok": (bool,),
        "stale_spans_total": (int,), "redelegations_total": (int,),
        "sampling": (dict,),
    },
    "traces": {
        "error_requests": (int,), "error_traces_in_merged": (int,),
        "all_errors_sampled": (bool,), "merged_events": (int,),
    },
}

_MODEL_SHAPE: dict[str, tuple] = {
    "kind": (str,), "requests": (int,), "ok": (int,), "shed": (int,),
    "shed_over_quota": (int,),
    "deadline": (int,), "evicted": (int,), "error": (int,),
    "p50_s": (*_NUM, type(None)), "p99_s": (*_NUM, type(None)),
    "fast_burn": _NUM, "slow_burn": _NUM,
    "fast_alert": (bool,), "slow_alert": (bool,),
}

_TENANT_SHAPE: dict[str, tuple] = {
    "priority": (str,), "share": _NUM,
    "requests": (int,), "ok": (int,), "shed": (int,),
    "shed_over_quota": (int,), "deadline": (int,), "evicted": (int,),
    "error": (int,), "models": (dict,), "certified": (bool,),
}


def validate_slo_cert(doc: dict) -> list[str]:
    """Structural validation of one certificate document; returns the list
    of problems (empty = valid). CI fails the seeded smoke leg on any."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    if doc.get("version") != SLO_CERT_VERSION:
        problems.append(f"version must be {SLO_CERT_VERSION}")
    if not isinstance(doc.get("seed"), int):
        problems.append("seed must be an integer")
    for section, shape in _CERT_SHAPE.items():
        body = doc.get(section)
        if not isinstance(body, dict):
            problems.append(f"missing section {section!r}")
            continue
        for key, types in shape.items():
            if key not in body:
                problems.append(f"{section}.{key} missing")
            elif not isinstance(body[key], types) or (
                isinstance(body[key], bool) and bool not in types
            ):
                problems.append(f"{section}.{key} has wrong type")
    slo = doc.get("slo")
    if not isinstance(slo, dict) or not isinstance(slo.get("models"), dict):
        problems.append("slo.models missing")
    models = doc.get("models")
    if not isinstance(models, dict) or not models:
        problems.append("models section missing or empty")
        return problems
    for model, body in models.items():
        if not isinstance(body, dict):
            problems.append(f"models.{model} is not an object")
            continue
        for key, types in _MODEL_SHAPE.items():
            if key not in body:
                problems.append(f"models.{model}.{key} missing")
            elif not isinstance(body[key], types) or (
                isinstance(body[key], bool) and bool not in types
            ):
                problems.append(f"models.{model}.{key} has wrong type")
        counted = sum(
            int(body.get(k) or 0)
            for k in ("ok", "shed", "deadline", "evicted", "error")
        )
        if counted != int(body.get("requests") or 0):
            problems.append(f"models.{model}: outcome counts != requests")
    problems.extend(_validate_tenants(doc, models))
    problems.extend(_validate_autoscaler(doc))
    problems.extend(validate_sessions(doc))
    problems.extend(_validate_critpath(doc))
    return problems


def _validate_tenants(doc: dict, models: dict) -> list[str]:
    """The per-tenant section's invariants (optional section — absent on
    tenant-less certificates): every tenant's outcome counts must sum to
    its requests, the tenants' request totals must account for EXACTLY the
    model totals (no request untallied, none double-counted), and the
    cross-tenant eviction count must be present (the isolation pin reads
    it)."""
    body = doc.get("tenants")
    if body is None:
        return []
    problems: list[str] = []
    if not isinstance(body, dict) or not isinstance(body.get("tenants"), dict):
        return ["tenants section is not an object with a tenants map"]
    if not isinstance(body.get("cross_tenant_evictions"), int):
        problems.append("tenants.cross_tenant_evictions missing")
    tenant_requests = 0
    for tenant, tbody in body["tenants"].items():
        if not isinstance(tbody, dict):
            problems.append(f"tenants.{tenant} is not an object")
            continue
        for key, types in _TENANT_SHAPE.items():
            if key not in tbody:
                problems.append(f"tenants.{tenant}.{key} missing")
            elif not isinstance(tbody[key], types) or (
                isinstance(tbody[key], bool) and bool not in types
            ):
                problems.append(f"tenants.{tenant}.{key} has wrong type")
        counted = sum(
            int(tbody.get(k) or 0)
            for k in ("ok", "shed", "deadline", "evicted", "error")
        )
        if counted != int(tbody.get("requests") or 0):
            problems.append(f"tenants.{tenant}: outcome counts != requests")
        for model, mbody in (tbody.get("models") or {}).items():
            if not isinstance(mbody, dict):
                problems.append(f"tenants.{tenant}.models.{model} not an object")
                continue
            mcounted = sum(
                int(mbody.get(k) or 0)
                for k in ("ok", "shed", "deadline", "evicted", "error")
            )
            if mcounted != int(mbody.get("requests") or 0):
                problems.append(
                    f"tenants.{tenant}.models.{model}: "
                    "outcome counts != requests"
                )
        tenant_requests += int(tbody.get("requests") or 0)
    model_requests = sum(
        int((m or {}).get("requests") or 0) for m in models.values()
        if isinstance(m, dict)
    )
    if tenant_requests != model_requests:
        problems.append(
            f"tenants request total {tenant_requests} != "
            f"models request total {model_requests}"
        )
    return problems


def _validate_critpath(doc: dict) -> list[str]:
    """The root-cause section's invariants (optional section — absent on
    pre-critpath certificates): every charged model's lane shares must sum
    to 1 (never more), every burn alert for a model the table attributes
    must carry its named culprit, and a run that injected a drift fault
    must show the sentinel detecting it — the right (model, stage, member)
    named, the forced-sampling window opened, the replan requested."""
    body = doc.get("critpath")
    if body is None:
        return []
    problems: list[str] = []
    if not isinstance(body, dict) or not isinstance(body.get("table"), dict):
        return ["critpath section is not an object with a table"]
    models = body["table"].get("models")
    if not isinstance(models, dict):
        return ["critpath.table.models missing"]
    for model, mbody in models.items():
        lanes = (mbody or {}).get("lanes")
        if not isinstance(lanes, list) or not lanes:
            problems.append(f"critpath.{model}: no lanes")
            continue
        total = 0.0
        for ln in lanes:
            share = float((ln or {}).get("share") or 0.0)
            if share < 0.0 or share > 1.0 + 1e-9:
                problems.append(f"critpath.{model}: share {share} out of range")
            total += share
        if total > 1.0 + 1e-6 or abs(total - 1.0) > 1e-6:
            problems.append(f"critpath.{model}: shares sum {total:.8f} != 1")
    burns = body.get("burn_events")
    if not isinstance(burns, list):
        problems.append("critpath.burn_events missing")
        burns = []
    for i, ev in enumerate(burns):
        if not isinstance(ev, dict):
            problems.append(f"critpath.burn_events[{i}] not an object")
            continue
        if str(ev.get("model") or "") not in models:
            continue  # the table never attributed this lane; nothing owed
        if "culprit_stage" not in ev or "culprit_member" not in ev \
                or "critpath_share" not in ev:
            problems.append(f"critpath.burn_events[{i}] lacks culprit")
    drift = body.get("drift")
    if drift is None:
        return problems
    if not isinstance(drift, dict):
        return [*problems, "critpath.drift is not an object"]
    if not drift.get("injected"):
        problems.append("critpath.drift: fault was never injected")
        return problems
    spec = drift.get("spec") or {}
    member = str(drift.get("injected_member") or "")
    stage = str(spec.get("stage") or "decode")
    alerts = drift.get("alerts")
    if not isinstance(alerts, list) or not alerts:
        problems.append("critpath.drift: sentinel never alerted")
        return problems
    first = alerts[0] if isinstance(alerts[0], dict) else {}
    if str(first.get("member")) != member or str(first.get("stage")) != stage:
        problems.append(
            "critpath.drift: first alert names "
            f"({first.get('stage')}, {first.get('member')}), "
            f"fault was ({stage}, {member})"
        )
    if not isinstance(drift.get("cycles_to_alert"), int):
        problems.append("critpath.drift: cycles_to_alert missing")
    if int(drift.get("force_windows") or 0) < 1:
        problems.append("critpath.drift: no forced-sampling window opened")
    replans = drift.get("replan_requests")
    if not isinstance(replans, list) or not replans:
        problems.append("critpath.drift: no replan requested")
    elif not any(member in str(r) and stage in str(r) for r in replans):
        problems.append("critpath.drift: replan reason names no culprit")
    return problems


def _validate_autoscaler(doc: dict) -> list[str]:
    """The autoscaler section's invariants (optional section): decision
    list present and every decision carries a direction + trigger, the
    flight-recorded count covers the decisions, and a clean run never
    burned after its scale-down."""
    body = doc.get("autoscaler")
    if body is None:
        return []
    problems: list[str] = []
    if not isinstance(body, dict):
        return ["autoscaler section is not an object"]
    decisions = body.get("decisions")
    if not isinstance(decisions, list):
        problems.append("autoscaler.decisions missing")
        decisions = []
    for i, decision in enumerate(decisions):
        if not isinstance(decision, dict) or "direction" not in decision \
                or "trigger" not in decision:
            problems.append(f"autoscaler.decisions[{i}] lacks direction/trigger")
    recorded = body.get("flight_recorded")
    if not isinstance(recorded, int) or recorded < len(decisions):
        problems.append("autoscaler.flight_recorded < decisions")
    if not isinstance(body.get("breach_after_scale_down"), bool):
        problems.append("autoscaler.breach_after_scale_down missing")
    return problems


# ---------------------------------------------------------------------------
# The canonical tenant-isolation scenario
# ---------------------------------------------------------------------------
#
# One definition, three consumers: tests/test_autoscaler.py pins its
# verdicts across the chaos-seed matrix, tools/slo_cert.py --tenants
# replays it standalone, and tools/ci_check.sh runs that per seed leg.
# Tenant "acme" (low priority, half share) takes a 10x flash crowd while
# the default tenant's steady traffic rides the same members; the
# certificate must show acme shedding typed over-quota inside its own
# allowance, the default tenant's p99 certified, zero cross-tenant
# evictions, and the autoscaler scaling up on the burn edge then back
# down after quiet without re-breaching.

ISOLATION_TENANTS: dict[str, dict[str, object]] = {
    "acme": {"priority": "low", "share": 0.5},
}


def two_tenant_flash_spec(
    seed: int,
    *,
    base_rps: float = 40.0,
    duration_s: float = 240.0,
    surge_start_s: float = 30.0,
    surge_duration_s: float = 30.0,
    surge_multiplier: float = 10.0,
) -> TrafficSpec:
    """The pinned two-tenant traffic shape: default tenant serves a
    steady predict+generate mix; tenant ``acme`` runs generate traffic
    and takes a tenant-scoped flash crowd."""
    return TrafficSpec(
        mixes=(
            TrafficMix("resnet50", "predict", 0.5),
            TrafficMix("llm-7b", "generate", 0.2),
            TrafficMix("llm-7b", "generate", 0.3, tenant="acme"),
        ),
        base_rps=base_rps,
        duration_s=duration_s,
        flash_crowds=(
            FlashCrowd(
                start_s=surge_start_s,
                duration_s=surge_duration_s,
                multiplier=surge_multiplier,
                tenant="acme",
            ),
        ),
        seed=seed,
    )


def tenant_isolation_harness(
    n_members: int, seed: int, **overrides: Any
) -> ReplayHarness:
    """ReplayHarness wired for the isolation certification: quota
    enforcement on, the real autoscaler actuating sim capacity, a short
    fast-burn window (detection latency bounds how much of the surge
    leaks into latency before the scale-up), and a clear-window run
    longer than the surge so the scale-down happens after quiet, not
    mid-crowd."""
    params: dict[str, Any] = dict(
        tenants=tenant_mod.parse_tenants(ISOLATION_TENANTS),
        autoscale=True,
        autoscale_max_units=8,
        autoscale_clear_windows=12,
        capacity_headroom=2.0,
        scrape_interval_s=2.5,
        fast_window_s=5.0,
    )
    params.update(overrides)
    return ReplayHarness(n_members, two_tenant_flash_spec(seed), **params)


# ---------------------------------------------------------------------------
# The canonical drift-sentinel scenario
# ---------------------------------------------------------------------------
#
# One definition, three consumers: tests/test_critpath.py pins its
# verdicts across the chaos-seed matrix, tools/slo_cert.py --critpath
# replays it standalone, and tools/ci_check.sh runs that per seed leg.
# A steady single-model predict load rides four members (none of them a
# SLOW_EVERY straggler); at half-replay EXACTLY ONE member's decode stage
# slows 5x. The certificate must show the sentinel naming (model, decode,
# that member) within three detection windows of the injection, the next
# fast-burn alert carrying the same culprit, a forced-sampling window
# opening, and a placement replan requested with the culprit in its
# reason — all read back from the flight recorder.

DRIFT_MEMBER_INDEX = 1
DRIFT_STAGE = "decode"
DRIFT_FACTOR = 5.0
DRIFT_SCRAPE_INTERVAL_S = 2.5
DRIFT_FAST_WINDOW_S = 5.0
# Detection bound the certification pins: the sentinel must name the
# culprit within this many fast-burn windows of the injection.
DRIFT_DETECT_FAST_WINDOWS = 3


def drift_soak_spec(
    seed: int, *, base_rps: float = 40.0, duration_s: float = 240.0,
) -> TrafficSpec:
    """The pinned drift traffic shape: one steady predict mix, no flash
    crowds — the injected stage fault is the ONLY latency shift in the
    run, so any alert the sentinel raises is attributable to it."""
    return TrafficSpec(
        mixes=(TrafficMix("resnet50", "predict", 1.0),),
        base_rps=base_rps,
        duration_s=duration_s,
        seed=seed,
    )


def drift_sentinel_harness(
    n_members: int, seed: int, **overrides: Any
) -> ReplayHarness:
    """ReplayHarness wired for the drift certification: scrape cadence ==
    analyzer window (every fold carries one fresh window of samples), a
    short fast-burn window with a threshold the one-member slowdown
    clearly crosses (frac-over ~0.11 of a 0.05 budget => burn ~2.3), and
    the 5x decode fault on one member at half-replay."""
    params: dict[str, Any] = dict(
        scrape_interval_s=DRIFT_SCRAPE_INTERVAL_S,
        fast_window_s=DRIFT_FAST_WINDOW_S,
        fast_burn=1.5,
        drift={
            "member": DRIFT_MEMBER_INDEX, "stage": DRIFT_STAGE,
            "factor": DRIFT_FACTOR, "at_fraction": 0.5,
        },
        sentinel_min_samples=20,
        sentinel_confirm_windows=3,
        sentinel_drift_factor=2.0,
    )
    params.update(overrides)
    return ReplayHarness(n_members, drift_soak_spec(seed), **params)


# ---------------------------------------------------------------------------
# The canonical session-churn scenario
# ---------------------------------------------------------------------------
#
# One definition, three consumers again: tests/test_genrouter.py pins its
# verdicts across the chaos-seed matrix, tools/slo_cert.py --sessions
# replays it standalone, and tools/ci_check.sh runs that per seed leg.
# Sixteen generation streams across two tenants ride real GenerateWorkers
# behind the real session router; the seeded schedule kills two members
# mid-decode and drains a third, and the certificate's ``sessions``
# section must show every stream completing token-identically to its
# unkilled reference — zero lost, zero duplicated — with migrations
# bounded by the sessions actually resident at each disruption and the
# drain dropping nothing.


def _session_plan(prompt: list[int], seed: int, n: int) -> list[int]:
    """A toy decoder's full output: token i is a pure function of
    (prompt, seed, i) — the same contract the engine's position-seeded
    sampling provides, so resume-from-prefix continues identically."""
    return [int(prompt[0]) * 1000 + int(seed) % 97 * 10 + i + 1
            for i in range(n)]


class _SessionDecoder:
    """Deterministic GenerationBackend stand-in with the resume-from-prefix
    entry: ``resume_tokens`` skips the already-delivered positions."""

    def __init__(self, member: str, prefills: dict[str, int]):
        self.member = member
        self.prefills = prefills  # shared across members: sid -> count
        self.live: list[tuple[Any, list[int]]] = []

    def submit(self, prompt: list[int], *, max_new_tokens: int,
               temperature: float = 0.0, eos_id: int | None = None,
               request_id: str = "", seed: int | None = None,
               resume_tokens: Any = None) -> Any:
        from dmlc_tpu.generate.slots import GenStream

        stream = GenStream(request_id)
        done = [int(t) for t in resume_tokens] if resume_tokens else []
        full = _session_plan(prompt, seed or 0, len(done) + int(max_new_tokens))
        self.prefills[request_id] = self.prefills.get(request_id, 0) + 1
        self.live.append((stream, full[len(done):]))
        return stream

    def step(self) -> None:
        for stream, remaining in self.live:
            if stream.done or stream.cancelled:
                continue
            if remaining:
                stream.push([remaining.pop(0)])
            if not remaining:
                stream.finish()


class SessionChurnHarness:
    """Generate-heavy churn against the REAL session tier: ``n_members``
    real ``GenerateWorker``s over deterministic toy decoders on a
    ``SimRpcNetwork``, fronted by a real ``GenRouter`` holding the tenant
    ledger (``ISOLATION_TENANTS``). The seeded schedule interleaves decode
    steps, client polls, and leader ticks with ``kills`` member crashes
    mid-decode and ``drains`` operator drains; ``run()`` drives everything
    to completion and returns the sessions-section certificate document."""

    def __init__(self, n_members: int, seed: int, *, streams: int = 16,
                 kills: int = 2, drains: int = 1, max_rounds: int = 600):
        if n_members < kills + drains + 1:
            raise ValueError("need a survivor: n_members > kills + drains")
        self.n_members = int(n_members)
        self.seed = int(seed)
        self.streams = int(streams)
        self.kills = int(kills)
        self.drains = int(drains)
        self.max_rounds = int(max_rounds)

    def run(self) -> dict[str, Any]:
        from dmlc_tpu.generate.worker import GenerateWorker
        from dmlc_tpu.scheduler.genrouter import GenRouter

        rng = random.Random(self.seed)
        net = SimRpcNetwork()
        alive = {f"m{i}" for i in range(self.n_members)}
        prefills: dict[str, int] = {}
        decoders: dict[str, _SessionDecoder] = {}
        for m in sorted(alive):
            decoders[m] = _SessionDecoder(m, prefills)
            worker = GenerateWorker(
                {"toy": decoders[m]},  # type: ignore[dict-item]
                session_ttl_s=1e9, clock=net.clock,
            )
            net.serve(m, worker.methods())
        router = GenRouter(
            net.client("L"),
            lambda: sorted(alive),
            tenants=tenant_mod.parse_tenants(ISOLATION_TENANTS),
            max_sessions=4 * self.streams,
            drain_deadline_s=0.0,
            session_ttl_s=1e9,
            timeout_s=5.0,
            clock=net.clock,
        )
        router.is_leading = True
        router.epoch = [1, "L"]
        net.serve("L", router.methods())

        # Seeded stream population across the two tenants. Each stream's
        # reference is its plan — what an unkilled run would deliver.
        clients: list[dict[str, Any]] = []
        for i in range(self.streams):
            tenant = "acme" if i % 2 else tenant_mod.DEFAULT_TENANT
            prompt, sd = [i + 1], self.seed * 1000 + i
            tokens = rng.randint(6, 12)
            clients.append({
                "cid": f"c{i}", "tenant": tenant, "prompt": prompt,
                "seed": sd, "plan": _session_plan(prompt, sd, tokens),
                "tokens": tokens, "gen_id": None, "acked": 0,
                "consumed": [], "finished": False, "lost": False,
            })
        for c in clients:
            with tenant_mod.bind(c["tenant"]):
                reply = net.client(c["cid"]).call("L", "job.generate", {
                    "model": "toy", "prompt": c["prompt"],
                    "max_new_tokens": c["tokens"], "seed": c["seed"],
                })
            c["gen_id"] = reply["gen_id"]

        # Seeded disruption schedule: kills and the drain land on distinct
        # members at distinct rounds, each mid-decode.
        rounds = sorted(rng.sample(range(2, 2 + 4 * (self.kills + self.drains)),
                                   self.kills + self.drains))
        events = (["kill"] * self.kills) + (["drain"] * self.drains)
        rng.shuffle(events)
        schedule = dict(zip(rounds, events))
        disrupted: set[str] = set()
        migration_budget = 0
        drain_members: list[str] = []
        drain_resident: set[str] = set()

        def residents(member: str) -> list[str]:
            return [s["id"] for s in router.sessions_table()
                    if s["member"] == member
                    and s["state"] in ("running", "migrating")]

        done = 0
        for rnd in range(self.max_rounds):
            event = schedule.get(rnd)
            if event is not None:
                hosting = sorted(
                    m for m in alive - disrupted
                    if residents(m)
                ) or sorted(alive - disrupted)
                victim = rng.choice(hosting)
                disrupted.add(victim)
                migration_budget += len(residents(victim))
                if event == "kill":
                    alive.discard(victim)
                    net.crash(victim)
                else:
                    drain_members.append(victim)
                    drain_resident.update(residents(victim))
                    router.drain(victim, reason="loadgen")
            for m in sorted(alive):
                decoders[m].step()
            router.tick()
            done = 0
            for c in clients:
                if c["finished"] or c["lost"]:
                    done += 1
                    continue
                try:
                    r = net.client(c["cid"]).call("L", "job.generate_poll", {
                        "gen_id": c["gen_id"], "ack": c["acked"],
                    })
                except (RpcUnreachable, RpcError):
                    continue
                for seq, toks in sorted(r.get("chunks", [])):
                    if seq <= c["acked"]:
                        continue
                    c["acked"] = seq
                    c["consumed"].extend(int(t) for t in toks)
                if r.get("done") and not r.get("chunks"):
                    if r.get("error"):
                        c["lost"] = True
                    else:
                        c["finished"] = True
            if done == len(clients):
                break

        return self._certify(router, clients, migration_budget,
                             drain_members, drain_resident)

    def _certify(self, router: Any, clients: list[dict[str, Any]],
                 migration_budget: int, drain_members: list[str],
                 drain_resident: set[str]) -> dict[str, Any]:
        migrations_by_sid = {
            s["id"]: int(s["migrations"]) for s in router.sessions_table()
        }
        drains_doc = router.draining()
        tenants: dict[str, dict[str, int]] = {}
        completed = lost = duplicated = drain_lost = 0
        max_migrations = 0
        total_migrations = 0
        for c in clients:
            t = tenants.setdefault(c["tenant"], {
                "streams": 0, "completed": 0, "lost": 0,
                "duplicated": 0, "migrations": 0,
            })
            t["streams"] += 1
            ok = c["finished"] and c["consumed"] == c["plan"]
            dup = c["consumed"] != c["plan"][: len(c["consumed"])]
            m = migrations_by_sid.get(c["gen_id"], 0)
            completed += int(ok)
            t["completed"] += int(ok)
            if not ok:
                lost += 1
                t["lost"] += 1
                if c["gen_id"] in drain_resident:
                    drain_lost += 1
            duplicated += int(dup)
            t["duplicated"] += int(dup)
            total_migrations += m
            t["migrations"] += m
            max_migrations = max(max_migrations, m)
        certified = (
            completed == len(clients) and lost == 0 and duplicated == 0
            and total_migrations <= migration_budget and drain_lost == 0
            and all(d.get("complete") for d in drains_doc.values())
        )
        return {
            "version": SLO_CERT_VERSION,
            "seed": self.seed,
            "sessions": {
                "members": self.n_members,
                "streams": len(clients),
                "completed": completed,
                "lost": lost,
                "duplicated": duplicated,
                "kills": self.kills,
                "drains": self.drains,
                "migrations": total_migrations,
                "migration_budget": migration_budget,
                "max_migrations_per_stream": max_migrations,
                "drain_completed": all(
                    bool(d.get("complete")) for d in drains_doc.values()
                ) if drains_doc else True,
                "drain_lost": drain_lost,
                "tenants": tenants,
                "certified": certified,
            },
        }


def session_churn_harness(
    n_members: int, seed: int, **overrides: Any
) -> SessionChurnHarness:
    """SessionChurnHarness wired for the survivable-generation
    certification: sixteen streams over two tenants on four members, two
    seeded kills mid-decode and one drain (docs/GENERATE.md)."""
    params: dict[str, Any] = dict(streams=16, kills=2, drains=1)
    params.update(overrides)
    return SessionChurnHarness(n_members, seed, **params)


_SESSION_SHAPE: dict[str, tuple] = {
    "members": (int,), "streams": (int,), "completed": (int,),
    "lost": (int,), "duplicated": (int,), "kills": (int,),
    "drains": (int,), "migrations": (int,), "migration_budget": (int,),
    "max_migrations_per_stream": (int,), "drain_completed": (bool,),
    "drain_lost": (int,), "tenants": (dict,), "certified": (bool,),
}


def validate_sessions(doc: dict) -> list[str]:
    """The sessions section's invariants (optional section — absent on
    certificates without generation churn): every verdict field present
    and typed, completed + lost accounting for every stream, and the
    per-tenant breakdown summing exactly to the fleet totals."""
    body = doc.get("sessions")
    if body is None:
        return []
    problems: list[str] = []
    if not isinstance(body, dict):
        return ["sessions section is not an object"]
    for key, types in _SESSION_SHAPE.items():
        if key not in body:
            problems.append(f"sessions.{key} missing")
        elif not isinstance(body[key], types) or (
            isinstance(body[key], bool) and bool not in types
        ):
            problems.append(f"sessions.{key} has wrong type")
    # Arithmetic invariants run only over well-typed fields: a tampered
    # "zero" string is already reported above and must not crash the
    # validator (it judges hostile docs, it doesn't trust them).
    def num(v: Any) -> int:
        return int(v) if isinstance(v, (int, float)) and \
            not isinstance(v, bool) else 0

    if num(body.get("completed")) + num(body.get("lost")) != \
            num(body.get("streams")):
        problems.append("sessions: completed + lost != streams")
    tenants = body.get("tenants")
    if isinstance(tenants, dict):
        for name, tbody in tenants.items():
            if not isinstance(tbody, dict):
                problems.append(f"sessions.tenants.{name} is not an object")
        for key in ("streams", "completed", "lost", "migrations"):
            tallied = sum(
                num(t.get(key)) for t in tenants.values()
                if isinstance(t, dict)
            )
            if tallied != num(body.get(key)):
                problems.append(
                    f"sessions: tenant {key} total {tallied} != "
                    f"fleet {key} {body.get(key)}"
                )
    return problems


__all__ = [
    "ISOLATION_TENANTS",
    "SLO_CERT_VERSION",
    "FlashCrowd",
    "ModelTally",
    "OpenLoopArrivals",
    "ReplayHarness",
    "SessionChurnHarness",
    "SimMember",
    "TrafficMix",
    "TrafficSpec",
    "session_churn_harness",
    "tenant_isolation_harness",
    "two_tenant_flash_spec",
    "validate_sessions",
    "validate_slo_cert",
]
