"""Replayable load generation + SLO certification over the sim fabric.

The observability plane (scrape trees, adaptive trace sampling, SLO burn
rates) is only trustworthy if it can be DEMONSTRATED against known traffic
— so this module replays a fully seeded workload through a simulated fleet
on the virtual clock and emits a certification document
(``slo_cert.json``, docs/OPERATIONS.md) any run with the same seed
reproduces byte-for-byte in its integer fields:

- **Open-loop arrivals** — an inhomogeneous Poisson process (Lewis-Shedler
  thinning against the peak rate), so load does NOT back off when the
  fleet slows down; that is what makes deadline misses and sheds honest.
- **Traffic shape** — a base rate modulated by a diurnal sinusoid and
  scripted flash crowds (start/duration/multiplier), mixing predict and
  generate requests across models by weight.
- **Simulated members** — each member admits through a token bucket on the
  virtual clock (overflow -> ``Overloaded`` shed), serves with a seeded
  jittered service time (a deterministic slow minority models stragglers,
  and queue pressure inflates them further), raising ``DeadlineExceeded``
  when the simulated service cannot fit the caller's remaining budget and
  occasionally evicting generate requests under pressure.
- **The real observability plane** — the leader scrapes through the real
  ``ScrapeTreeCoordinator``/``ScrapeDelegate`` tree, folds profiles with
  the real ``CostProfiler``/``SloEvaluator``, and the real tracer head-
  samples requests — errors force-recorded — so the certificate measures
  the plane this repo ships, not a mock of it.

The certificate pins: per-model p50/p99 vs objective, SLO burn rates
(read from the same ``SloEvaluator`` state the leader alerts on), shed /
deadline / eviction counts, leader scrape-RPC cost vs the 4*sqrt(N)
tree bound, sampling effectiveness, and that 100% of error and
deadline-exceeded request traces survived into the merged fleet trace.
``validate_slo_cert`` is the schema gate CI runs (tools/slo_cert.py).
"""

from __future__ import annotations

import math
import random
import time
from dataclasses import dataclass, field
from typing import Iterator

from dmlc_tpu.cluster import observe, tracectx
from dmlc_tpu.cluster.profile import CostProfiler
from dmlc_tpu.cluster.rpc import (
    DeadlineExceeded,
    Overloaded,
    RpcError,
    RpcUnreachable,
    SimRpcNetwork,
)
from dmlc_tpu.cluster.scrapetree import ScrapeDelegate, ScrapeTreeCoordinator
from dmlc_tpu.scheduler.placement import SloEvaluator, SloObjective
from dmlc_tpu.utils import tracing
from dmlc_tpu.utils.metrics import Registry
from dmlc_tpu.utils.tracing import traced_methods

SLO_CERT_VERSION = 1

# Per-request deadline budget by traffic kind (seconds of virtual time).
KIND_DEADLINE_S = {"predict": 0.5, "generate": 2.0}

# Mean simulated service time by kind; jittered per request, inflated on
# the deterministic slow minority and again under admission pressure.
KIND_SERVICE_S = {"predict": 0.08, "generate": 0.45}


# ---------------------------------------------------------------------------
# Traffic shape
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TrafficMix:
    """One slice of the offered traffic: a model served by one kind of
    request, drawn with probability proportional to ``weight``."""

    model: str
    kind: str  # "predict" | "generate"
    weight: float = 1.0


@dataclass(frozen=True)
class FlashCrowd:
    """A scripted step burst: rate multiplies by ``multiplier`` for
    ``duration_s`` starting at ``start_s`` (overlapping crowds stack)."""

    start_s: float
    duration_s: float
    multiplier: float

    def factor_at(self, t: float) -> float:
        return self.multiplier if self.start_s <= t < self.start_s + self.duration_s else 1.0


@dataclass(frozen=True)
class TrafficSpec:
    """A fully seeded workload description — same spec, same arrivals."""

    duration_s: float
    base_rps: float
    mixes: tuple[TrafficMix, ...]
    diurnal_amplitude: float = 0.0   # 0..1: rate swings +-amplitude
    diurnal_period_s: float = 86400.0
    flash_crowds: tuple[FlashCrowd, ...] = ()
    seed: int = 0

    def rate_at(self, t: float) -> float:
        """Instantaneous offered rate (requests/s of virtual time)."""
        rate = self.base_rps
        if self.diurnal_amplitude > 0.0:
            rate *= 1.0 + self.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / self.diurnal_period_s
            )
        for crowd in self.flash_crowds:
            rate *= crowd.factor_at(t)
        return max(0.0, rate)

    def peak_rate(self) -> float:
        """An upper bound on ``rate_at`` — the thinning envelope. Assumes
        the worst case of every crowd overlapping; a loose bound only
        costs rejected candidates, never correctness."""
        peak = self.base_rps * (1.0 + max(0.0, self.diurnal_amplitude))
        for crowd in self.flash_crowds:
            peak *= max(1.0, crowd.multiplier)
        return max(peak, 1e-9)

    def to_wire(self) -> dict:
        return {
            "duration_s": self.duration_s,
            "base_rps": self.base_rps,
            "seed": self.seed,
            "diurnal_amplitude": self.diurnal_amplitude,
            "diurnal_period_s": self.diurnal_period_s,
            "mixes": [
                {"model": m.model, "kind": m.kind, "weight": m.weight}
                for m in self.mixes
            ],
            "flash_crowds": [
                {"start_s": c.start_s, "duration_s": c.duration_s,
                 "multiplier": c.multiplier}
                for c in self.flash_crowds
            ],
        }


class OpenLoopArrivals:
    """Inhomogeneous Poisson arrivals by Lewis-Shedler thinning: candidate
    gaps are exponential at the peak rate; each candidate survives with
    probability ``rate_at(t) / peak``. Open-loop by construction — the
    schedule never waits for the system under test."""

    def __init__(self, spec: TrafficSpec):
        self.spec = spec
        self._rng = random.Random(spec.seed ^ 0xA11)
        self._weights = [max(0.0, m.weight) for m in spec.mixes]
        self._total_weight = sum(self._weights)
        if self._total_weight <= 0:
            raise ValueError("TrafficSpec.mixes must carry positive weight")

    def _pick_mix(self) -> TrafficMix:
        x = self._rng.random() * self._total_weight
        for mix, w in zip(self.spec.mixes, self._weights):
            x -= w
            if x <= 0:
                return mix
        return self.spec.mixes[-1]

    def __iter__(self) -> Iterator[tuple[float, TrafficMix]]:
        lam = self.spec.peak_rate()
        t = 0.0
        while True:
            t += self._rng.expovariate(lam)
            if t >= self.spec.duration_s:
                return
            if self._rng.random() * lam <= self.spec.rate_at(t):
                yield t, self._pick_mix()


# ---------------------------------------------------------------------------
# Simulated members
# ---------------------------------------------------------------------------


class SimMember:
    """One simulated serving member: token-bucket admission on the virtual
    clock, seeded jittered service times, deterministic stragglers, and
    kv-pressure evictions for generate traffic. Serves the REAL
    observability surface (ObsService + ScrapeDelegate) next to the fake
    workload verbs, so scrapes and traces exercise production code."""

    SLOW_EVERY = 7        # every 7th member is a straggler
    SLOW_FACTOR = 4.0     # straggler service-time multiplier
    PRESSURE_GAIN = 3.0   # service inflation at full admission pressure
    EVICT_PRESSURE = 0.5   # generate evictions start above this utilization
    EVICT_P = 0.25         # ... with this probability

    def __init__(self, net: SimRpcNetwork, addr: str, index: int, *,
                 seed: int, capacity_qps: float, scrape_timeout_s: float):
        self.net = net
        self.addr = addr
        self.slow = (index % self.SLOW_EVERY) == self.SLOW_EVERY - 1
        self.rng = random.Random((seed << 16) ^ (index * 0x9E37) ^ 0x51AB)
        self.registry = Registry()
        self.capacity_qps = max(1e-6, capacity_qps)
        self.burst = max(2.0, self.capacity_qps)
        self._tokens = self.burst
        self._last_refill = net.clock()
        self.obs = observe.ObsService(self.registry, lane=addr)
        self.delegate = ScrapeDelegate(
            net.client(addr), timeout_s=scrape_timeout_s, concurrency=1,
            metrics=self.registry.counters,
        )
        net.serve(addr, self.methods())

    def methods(self) -> dict:
        table = traced_methods({
            "job.predict": self._serve_request,
            "job.generate": self._serve_request,
        })
        table.update(self.obs.methods())
        table.update(self.delegate.methods())
        return table

    def _admit(self) -> float:
        """Take one token or shed; returns utilization in [0, 1]."""
        now = self.net.clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last_refill) * self.capacity_qps
        )
        self._last_refill = now
        utilization = 1.0 - self._tokens / self.burst
        if self._tokens < 1.0:
            self.registry.counters.inc("shed")
            raise Overloaded(
                f"{self.addr}: admission queue full", retry_after_s=0.1
            )
        self._tokens -= 1.0
        return utilization

    def _serve_request(self, p: dict) -> dict:
        kind = str(p.get("kind") or "predict")
        self.registry.counters.inc("requests")
        utilization = self._admit()
        service = KIND_SERVICE_S.get(kind, 0.1) * (0.5 + self.rng.random())
        if self.slow:
            service *= self.SLOW_FACTOR
        service *= 1.0 + self.PRESSURE_GAIN * utilization
        if (
            kind == "generate"
            and utilization > self.EVICT_PRESSURE
            and self.rng.random() < self.EVICT_P
        ):
            self.registry.counters.inc("evicted")
            raise RpcError(f"evicted: {self.addr} kv-cache pressure")
        budget = float(p.get("deadline_s") or KIND_DEADLINE_S.get(kind, 1.0))
        if service >= budget:
            # The caller would wait out its whole budget; the sim raises
            # the same verdict the deadline fabric would without dragging
            # the shared virtual clock forward per straggler.
            self.registry.counters.inc("deadline_exceeded")
            raise DeadlineExceeded(
                f"{self.addr}/{kind}: simulated service {service:.3f}s "
                f"exceeds {budget:.3f}s budget"
            )
        self.registry.latency(f"rpc/job.{kind}").record(service)
        return {"service_s": service}


# ---------------------------------------------------------------------------
# Request bookkeeping
# ---------------------------------------------------------------------------


@dataclass
class ModelTally:
    kind: str = "predict"
    requests: int = 0
    ok: int = 0
    shed: int = 0
    deadline: int = 0
    evicted: int = 0
    error: int = 0
    latencies: list[float] = field(default_factory=list)

    def percentile(self, p: float) -> float | None:
        if not self.latencies:
            return None
        ordered = sorted(self.latencies)
        rank = max(0, math.ceil(p / 100.0 * len(ordered)) - 1)
        return ordered[rank]


class ReplayHarness:
    """One seeded certification run: N simulated members + a leader
    running the real scrape tree / profiler / SLO evaluator / tracer,
    driven by an ``OpenLoopArrivals`` schedule on the virtual clock.
    ``run()`` returns the ``slo_cert.json`` document."""

    def __init__(
        self,
        n_members: int,
        spec: TrafficSpec,
        *,
        objectives: dict[str, SloObjective] | None = None,
        sample_rate: float = 1.0,
        spans_per_s_budget: float = 0.0,
        scrape_interval_s: float = 10.0,
        scrape_timeout_s: float = 1.0,
        burn_force_sample_s: float = 15.0,
        fast_burn: float = 6.0,
        slow_burn: float = 1.5,
        capacity_headroom: float = 2.0,
    ):
        if n_members < 2:
            raise ValueError("certification needs at least 2 members")
        self.spec = spec
        self.sample_rate = float(sample_rate)
        self.spans_per_s_budget = float(spans_per_s_budget)
        self.scrape_interval_s = float(scrape_interval_s)
        self.burn_force_sample_s = float(burn_force_sample_s)

        self.net = SimRpcNetwork()
        self.leader_addr = "leader:0"
        self.member_addrs = [f"m{i:03d}:1" for i in range(n_members)]
        per_member_qps = capacity_headroom * spec.base_rps / n_members
        self.members = [
            SimMember(self.net, addr, i, seed=spec.seed,
                      capacity_qps=per_member_qps,
                      scrape_timeout_s=scrape_timeout_s)
            for i, addr in enumerate(self.member_addrs)
        ]
        self.leader_registry = Registry()
        self.leader_obs = observe.ObsService(
            self.leader_registry, lane=self.leader_addr
        )
        self.net.serve(self.leader_addr, self.leader_obs.methods())
        self.client = self.net.client(self.leader_addr)
        self.coordinator = ScrapeTreeCoordinator(
            self.client, clock=self.net.clock, timeout_s=scrape_timeout_s,
            concurrency=1, metrics=self.leader_registry.counters,
        )
        self.profiler = CostProfiler(
            window_s=5.0, windows=64, clock=self.net.clock, seed=spec.seed
        )
        if objectives is None:
            objectives = self.default_objectives(spec)
        self.objectives = objectives
        self.slo = SloEvaluator(
            self.profiler, objectives,
            fast_window_s=min(30.0, spec.duration_s),
            slow_window_s=spec.duration_s,
            fast_burn=fast_burn, slow_burn=slow_burn, stage="dispatch",
            metrics=self.leader_registry.counters,
        )
        self._dispatch_rng = random.Random(spec.seed ^ 0xD15)
        self.tallies: dict[str, ModelTally] = {}
        self.error_traces: set[str] = set()
        self.scrape_cycles = 0
        self.leader_scrape_rpcs = 0
        self.stale_spans_total = 0
        self.redelegations_total = 0
        self.force_windows = 0

    @staticmethod
    def default_objectives(spec: TrafficSpec) -> dict[str, SloObjective]:
        """One objective per model in the mix: a latency bound between the
        nominal and straggler service time for its kind, so a healthy
        fleet passes and a straggler-heavy one visibly burns budget."""
        out: dict[str, SloObjective] = {}
        for mix in spec.mixes:
            bound = KIND_SERVICE_S.get(mix.kind, 0.1) * 2.5
            out.setdefault(
                mix.model,
                SloObjective(model=mix.model, latency_s=bound, availability=0.95),
            )
        return out

    # ---- the drive loop ------------------------------------------------

    def run(self) -> dict:
        tracer = tracing.tracer
        prev_enabled = tracer.enabled
        tracer.reset()
        tracer.enabled = True
        tracer.set_sampling(
            rate=self.sample_rate, spans_per_s=self.spans_per_s_budget,
            clock=self.net.clock,
        )
        try:
            next_scrape = self.scrape_interval_s
            for t, mix in OpenLoopArrivals(self.spec):
                while next_scrape <= t:
                    if next_scrape > self.net.now:
                        self.net.advance(next_scrape - self.net.now)
                    self._scrape_cycle()
                    next_scrape += self.scrape_interval_s
                if t > self.net.now:
                    self.net.advance(t - self.net.now)
                self._dispatch(mix)
            while next_scrape <= self.spec.duration_s:
                if next_scrape > self.net.now:
                    self.net.advance(next_scrape - self.net.now)
                self._scrape_cycle()
                next_scrape += self.scrape_interval_s
            merged_trace = observe.collect_fleet_trace(
                self.client,
                [self.leader_addr, *self.member_addrs],
                timeout=5.0, clock_samples=1,
            )
            sampling = tracer.sampling_summary()
            return self._certificate(merged_trace, sampling)
        finally:
            # Restore the process-global tracer exactly as found: default
            # rate, controller off, REAL clock back in (the sim clock must
            # not leak into later users of the tracer).
            tracer.enabled = prev_enabled
            tracer.set_sampling(rate=1.0, spans_per_s=0.0, clock=time.monotonic)
            tracer.reset()

    def _scrape_cycle(self) -> None:
        result = self.coordinator.scrape(self.member_addrs)
        self.scrape_cycles += 1
        self.leader_scrape_rpcs += result.leader_rpcs
        self.stale_spans_total += len(result.stale_spans)
        self.redelegations_total += result.redelegations
        for addr, reply in result.members.items():
            self.profiler.ingest_scrape(addr, reply)
        self.slo.evaluate()
        burning = self.slo.burning_models()
        if burning and self.burn_force_sample_s > 0:
            # The same hook the real leader runs (cluster/node.py): a model
            # burning budget flips the whole fleet to forced sampling.
            tracing.tracer.force_sampling(self.burn_force_sample_s)
            observe.force_fleet_sampling(
                self.client, self.member_addrs, self.burn_force_sample_s,
                timeout=1.0,
            )
            self.force_windows += 1

    def _dispatch(self, mix: TrafficMix) -> None:
        member = self.member_addrs[
            self._dispatch_rng.randrange(len(self.member_addrs))
        ]
        budget = KIND_DEADLINE_S.get(mix.kind, 1.0)
        tally = self.tallies.setdefault(mix.model, ModelTally(kind=mix.kind))
        tally.requests += 1
        trace_id = ""
        try:
            with tracing.tracer.span(
                "loadgen/request", model=mix.model, kind=mix.kind
            ):
                ctx = tracectx.current()
                trace_id = ctx.trace_id if ctx is not None else ""
                reply = self.client.call(
                    member, f"job.{mix.kind}",
                    {"model": mix.model, "kind": mix.kind, "deadline_s": budget},
                    timeout=budget,
                )
        except Overloaded:
            tally.shed += 1
            self.error_traces.add(trace_id)
            return
        except DeadlineExceeded:
            tally.deadline += 1
            tally.latencies.append(budget)
            self.error_traces.add(trace_id)
            # The caller waited its whole budget: that latency is real and
            # lands in the SLO lane as an over-objective observation.
            self.profiler.record(mix.model, member, "dispatch", budget)
            return
        except (RpcUnreachable, RpcError) as e:
            if "evicted:" in str(e):
                tally.evicted += 1
            else:
                tally.error += 1
            self.error_traces.add(trace_id)
            return
        tally.ok += 1
        latency = float(reply["service_s"])
        tally.latencies.append(latency)
        self.profiler.record(mix.model, member, "dispatch", latency)

    # ---- certificate ---------------------------------------------------

    @staticmethod
    def _jsonsafe(value):
        """NaN/inf -> None recursively: the certificate must be strict
        JSON (the profiler's percentile is NaN on an empty lane)."""
        if isinstance(value, float) and not math.isfinite(value):
            return None
        if isinstance(value, dict):
            return {k: ReplayHarness._jsonsafe(v) for k, v in value.items()}
        if isinstance(value, list):
            return [ReplayHarness._jsonsafe(v) for v in value]
        return value

    def _certificate(self, merged_trace: dict, sampling: dict) -> dict:
        slo_status = self.slo.status()
        merged_trace_ids = {
            ev["args"]["trace"]
            for ev in merged_trace.get("traceEvents", ())
            if ev.get("ph") == "X" and "trace" in (ev.get("args") or {})
        }
        error_traces = {t for t in self.error_traces if t}
        present = error_traces & merged_trace_ids
        n = len(self.member_addrs)
        cycles = max(1, self.scrape_cycles)
        obs_calls = sum(
            1 for _, method in self.net.calls if method.startswith("obs.")
        )
        models: dict[str, dict] = {}
        for model in sorted(self.tallies):
            tally = self.tallies[model]
            slo_model = (slo_status.get("models") or {}).get(model, {})
            models[model] = {
                "kind": tally.kind,
                "requests": tally.requests,
                "ok": tally.ok,
                "shed": tally.shed,
                "deadline": tally.deadline,
                "evicted": tally.evicted,
                "error": tally.error,
                "p50_s": tally.percentile(50),
                "p99_s": tally.percentile(99),
                "objective_latency_s": slo_model.get("objective_latency_s"),
                "availability": slo_model.get("availability"),
                "fast_burn": slo_model.get("fast_burn", 0.0),
                "slow_burn": slo_model.get("slow_burn", 0.0),
                "fast_alert": slo_model.get("fast_alert", False),
                "slow_alert": slo_model.get("slow_alert", False),
            }
        return self._jsonsafe({
            "version": SLO_CERT_VERSION,
            "seed": self.spec.seed,
            "spec": {
                **self.spec.to_wire(),
                "members": n,
                "sample_rate": self.sample_rate,
                "spans_per_s_budget": self.spans_per_s_budget,
                "scrape_interval_s": self.scrape_interval_s,
            },
            "models": models,
            "slo": slo_status,
            "observability": {
                "scrape_cycles": self.scrape_cycles,
                "leader_scrape_rpcs_total": self.leader_scrape_rpcs,
                "leader_rpcs_per_cycle_avg": self.leader_scrape_rpcs / cycles,
                "members": n,
                "direct_equivalent_rpcs_per_cycle": n,
                "sqrt_bound_rpcs_per_cycle": 4.0 * math.sqrt(n),
                "bound_ok": (
                    self.leader_scrape_rpcs / cycles <= 4.0 * math.sqrt(n)
                ),
                "stale_spans_total": self.stale_spans_total,
                "redelegations_total": self.redelegations_total,
                "scrape_rpc_fraction": (
                    obs_calls / len(self.net.calls) if self.net.calls else 0.0
                ),
                "force_windows": self.force_windows,
                "sampling": sampling,
            },
            "traces": {
                "error_requests": len(error_traces),
                "error_traces_in_merged": len(present),
                "all_errors_sampled": error_traces <= merged_trace_ids,
                "merged_events": sum(
                    1 for ev in merged_trace.get("traceEvents", ())
                    if ev.get("ph") == "X"
                ),
            },
        })


# ---------------------------------------------------------------------------
# Certificate schema gate
# ---------------------------------------------------------------------------

_NUM = (int, float)

# section -> {field: required types} — hand-rolled (no jsonschema dep);
# None in a type tuple marks the field as nullable.
_CERT_SHAPE: dict[str, dict[str, tuple]] = {
    "spec": {
        "duration_s": _NUM, "base_rps": _NUM, "seed": (int,),
        "members": (int,), "sample_rate": _NUM, "scrape_interval_s": _NUM,
        "mixes": (list,), "flash_crowds": (list,),
    },
    "observability": {
        "scrape_cycles": (int,), "leader_scrape_rpcs_total": (int,),
        "leader_rpcs_per_cycle_avg": _NUM, "members": (int,),
        "sqrt_bound_rpcs_per_cycle": _NUM, "bound_ok": (bool,),
        "stale_spans_total": (int,), "redelegations_total": (int,),
        "sampling": (dict,),
    },
    "traces": {
        "error_requests": (int,), "error_traces_in_merged": (int,),
        "all_errors_sampled": (bool,), "merged_events": (int,),
    },
}

_MODEL_SHAPE: dict[str, tuple] = {
    "kind": (str,), "requests": (int,), "ok": (int,), "shed": (int,),
    "deadline": (int,), "evicted": (int,), "error": (int,),
    "p50_s": (*_NUM, type(None)), "p99_s": (*_NUM, type(None)),
    "fast_burn": _NUM, "slow_burn": _NUM,
    "fast_alert": (bool,), "slow_alert": (bool,),
}


def validate_slo_cert(doc: dict) -> list[str]:
    """Structural validation of one certificate document; returns the list
    of problems (empty = valid). CI fails the seeded smoke leg on any."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    if doc.get("version") != SLO_CERT_VERSION:
        problems.append(f"version must be {SLO_CERT_VERSION}")
    if not isinstance(doc.get("seed"), int):
        problems.append("seed must be an integer")
    for section, shape in _CERT_SHAPE.items():
        body = doc.get(section)
        if not isinstance(body, dict):
            problems.append(f"missing section {section!r}")
            continue
        for key, types in shape.items():
            if key not in body:
                problems.append(f"{section}.{key} missing")
            elif not isinstance(body[key], types) or (
                isinstance(body[key], bool) and bool not in types
            ):
                problems.append(f"{section}.{key} has wrong type")
    slo = doc.get("slo")
    if not isinstance(slo, dict) or not isinstance(slo.get("models"), dict):
        problems.append("slo.models missing")
    models = doc.get("models")
    if not isinstance(models, dict) or not models:
        problems.append("models section missing or empty")
        return problems
    for model, body in models.items():
        if not isinstance(body, dict):
            problems.append(f"models.{model} is not an object")
            continue
        for key, types in _MODEL_SHAPE.items():
            if key not in body:
                problems.append(f"models.{model}.{key} missing")
            elif not isinstance(body[key], types) or (
                isinstance(body[key], bool) and bool not in types
            ):
                problems.append(f"models.{model}.{key} has wrong type")
        counted = sum(
            int(body.get(k) or 0)
            for k in ("ok", "shed", "deadline", "evicted", "error")
        )
        if counted != int(body.get("requests") or 0):
            problems.append(f"models.{model}: outcome counts != requests")
    return problems


__all__ = [
    "SLO_CERT_VERSION",
    "FlashCrowd",
    "ModelTally",
    "OpenLoopArrivals",
    "ReplayHarness",
    "SimMember",
    "TrafficMix",
    "TrafficSpec",
    "validate_slo_cert",
]
