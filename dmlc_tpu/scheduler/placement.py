"""SLO burn-rate monitoring + profile-driven placement (the control loop).

This module spends the observability plane: ``CostProfiler`` lanes
(cluster/profile.py) feed two decision-makers the JobScheduler consults —

- **SloEvaluator** — per-model latency/availability objectives declared in
  ClusterConfig (``slo_objectives``). Burn rate is the SRE-workbook form:
  the fraction of observations over the latency objective, divided by the
  error budget (1 - availability target), over two horizons — a *fast*
  window that catches cliffs in minutes and a *slow* window that catches
  smolder. Alert transitions (with hysteresis, so a fleet hovering at the
  line does not flap) land in the flight recorder, the metrics counters,
  and per-model registry gauges; a fast-burn transition also pings the
  scheduler to replan placement NOW instead of on the next periodic pass.

- **PlacementAdvisor** — solves model -> member assignment from measured
  per-member dispatch cost instead of blind round-robin. Greedy
  cost-balancing: members whose decayed mean cost exceeds
  ``exclude_factor`` x the fleet median are excluded (with a re-entry
  hysteresis band so a recovering member must come well back under the
  line), the rest are dealt to jobs by capacity (chip weight / measured
  cost), and dispatch-pool weights scale inversely with cost so a slow
  member that stays assigned still receives proportionally fewer shards.
  Plans are throttled by a max-moves-per-window budget and a relative
  improvement threshold — rebalancing is itself a disturbance, and an
  advisor that reshuffles the fleet every tick is worse than round-robin.

Every decision stamps the flight recorder (lint rule O2 enforces this for
any future profile-reading scheduler path): placement must never be
invisible in a postmortem.

Both classes are sans-IO (injected clocks, no RPC, leaf locks only) so the
seeded sim soak (tests/test_placement.py) drives the whole loop —
degradation -> fast burn -> replan -> recovery — on the virtual clock.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from time import monotonic
from typing import Any, Callable

from dmlc_tpu.cluster import tenant as tenant_mod

log = logging.getLogger(__name__)


def tenant_lane(model: str, tenant: str) -> str:
    """Composite profiler model key for one tenant's share of a model's
    traffic (``model@tenant``). The default tenant rides the bare model
    lane, so a tenant-less fleet records exactly what it always did; the
    dispatch paths record BOTH the bare lane (the aggregate every existing
    consumer reads) and the composite one when a non-default tenant is
    ambient."""
    if not tenant or tenant == tenant_mod.DEFAULT_TENANT:
        return model
    return f"{model}@{tenant}"


# ---------------------------------------------------------------------------
# SLO evaluation: multi-window burn rates
# ---------------------------------------------------------------------------


@dataclass
class SloObjective:
    """One model's serving objective: ``latency_s`` is the per-shard
    dispatch latency bound, ``availability`` the target fraction of
    dispatches under it (error budget = 1 - availability)."""

    model: str
    latency_s: float
    availability: float = 0.99

    @property
    def error_budget(self) -> float:
        return max(1e-9, 1.0 - self.availability)

    @classmethod
    def from_config(cls, objectives: dict) -> "dict[str, SloObjective]":
        """Parse the ClusterConfig ``slo_objectives`` mapping
        (``{model: {"latency_s": s, "availability": a}}``)."""
        out: dict[str, SloObjective] = {}
        for model, spec in (objectives or {}).items():
            out[model] = cls(
                model=model,
                latency_s=float(spec["latency_s"]),
                availability=float(spec.get("availability", 0.99)),
            )
        return out


class SloEvaluator:
    """Evaluates burn rates from profiler lanes on every call (the leader
    runs it on the scrape cadence). Stateful only for alert edges."""

    # An alert clears only once burn falls below this fraction of its
    # threshold: hysteresis against flapping at the line.
    CLEAR_FRACTION = 0.5

    def __init__(
        self,
        profiler: Any,
        objectives: dict[str, SloObjective],
        *,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        fast_burn: float = 14.0,
        slow_burn: float = 2.0,
        stage: str = "dispatch",
        metrics: Any = None,
        flight: Any = None,
        registry: Any = None,
        on_fast_burn: Callable[[str], None] | None = None,
        tenants: list[str] | None = None,
        tenant_guard: Any = None,
        attribution: Callable[[str], dict[str, Any] | None] | None = None,
    ) -> None:
        self.profiler = profiler
        self.objectives = dict(objectives)
        # Root-cause hook (cluster/critpath.FleetCritPath.culprit): maps a
        # model to its top critical-path contributor so every burn alert
        # names (stage, member, critpath_share) instead of just the model.
        self.attribution = attribution
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.stage = stage
        self.metrics = metrics
        self.flight = flight
        self.on_fast_burn = on_fast_burn
        # Declared tenants (utils/config ``tenants``): each gets its own
        # burn lane per model, scored against the MODEL's objective — the
        # per-tenant promise is the same latency bound, evaluated on that
        # tenant's traffic only (profiler lane ``model@tenant``).
        self.tenants = sorted(tenants or [])
        # utils/metrics.TenantLabelGuard (optional): bounds per-tenant
        # gauge label cardinality.
        self.tenant_guard = tenant_guard
        # lane -> {"fast": burn, "slow": burn, "fast_alert": bool, ...}
        # where lane is the model (aggregate) or "model@tenant".
        self._state: dict[str, dict] = {
            lane: {"fast": 0.0, "slow": 0.0, "fast_alert": False,
                   "slow_alert": False}
            for m in self.objectives for lane in self._lanes(m)
        }
        self._lock = threading.Lock()
        if registry is not None:
            for model in self.objectives:
                for lane in self._lanes(model):
                    name = lane if lane == model else self._gauge_label(lane, model)
                    registry.gauge(
                        f"slo_fast_burn_{name}",
                        lambda ln=lane: self._state[ln]["fast"],
                    )
                    registry.gauge(
                        f"slo_slow_burn_{name}",
                        lambda ln=lane: self._state[ln]["slow"],
                    )

    def _lanes(self, model: str) -> list[str]:
        """The aggregate lane plus one per declared tenant."""
        return [model] + [f"{model}@{t}" for t in self.tenants]

    def _gauge_label(self, lane: str, model: str) -> str:
        tenant = lane[len(model) + 1:]
        if self.tenant_guard is not None:
            tenant = self.tenant_guard.label(tenant)
        return f"{model}@{tenant}"

    def _culprit(self, model: str) -> dict[str, Any]:
        """Flight-note fields naming the model's top critical-path
        contributor; empty when attribution is unwired or has no data yet
        (a burn note without a culprit beats no burn note)."""
        if self.attribution is None:
            return {}
        try:
            top = self.attribution(model)
        except Exception:  # the alert must land even if attribution dies
            log.exception("slo attribution failed for %s", model)
            return {}
        if not top:
            return {}
        return {
            "culprit_stage": str(top.get("stage", "")),
            "culprit_member": str(top.get("member", "")),
            "critpath_share": float(top.get("critpath_share", 0.0)),
        }

    def _burn(self, obj: SloObjective, horizon_s: float,
              lane: str | None = None) -> float:
        frac = self.profiler.frac_over(
            obj.latency_s, model=lane or obj.model, stage=self.stage,
            horizon_s=horizon_s,
        )
        return frac / obj.error_budget

    def evaluate(self) -> dict[str, dict]:
        """One evaluation pass over every objective — aggregate per model
        plus one lane per declared (model, tenant). Returns the per-lane
        state after the pass. Alert edge-transitions record flight events
        and counters; entering fast burn fires ``on_fast_burn`` (after the
        evaluator's own lock is released — the callback takes the
        scheduler's lock)."""
        fired: list[str] = []
        with self._lock:
            for model, obj in sorted(self.objectives.items()):
                for lane in self._lanes(model):
                    tenant = lane[len(model) + 1:] if lane != model else None
                    st = self._state[lane]
                    st["fast"] = self._burn(obj, self.fast_window_s, lane=lane)
                    st["slow"] = self._burn(obj, self.slow_window_s, lane=lane)
                    for win, threshold in (("fast", self.fast_burn),
                                           ("slow", self.slow_burn)):
                        alert_key = f"{win}_alert"
                        if not st[alert_key] and st[win] >= threshold:
                            st[alert_key] = True
                            if self.metrics is not None:
                                self.metrics.inc(f"slo_{win}_burn_alerts")
                            if self.flight is not None:
                                culprit = self._culprit(model)
                                self.flight.note(
                                    f"slo_{win}_burn", model=model,
                                    burn=round(st[win], 3), threshold=threshold,
                                    objective_s=obj.latency_s,
                                    **({"tenant": tenant} if tenant else {}),
                                    **culprit,
                                )
                            log.warning("SLO %s burn for %s: %.1fx budget "
                                        "(threshold %.1fx)", win, lane,
                                        st[win], threshold)
                            if win == "fast":
                                fired.append(lane)
                        elif st[alert_key] and \
                                st[win] <= self.CLEAR_FRACTION * threshold:
                            st[alert_key] = False
                            if self.flight is not None:
                                self.flight.note(
                                    "slo_burn_clear", model=model, window=win,
                                    burn=round(st[win], 3),
                                    **({"tenant": tenant} if tenant else {}),
                                )
            out = {m: dict(st) for m, st in self._state.items()}
        if self.on_fast_burn is not None:
            for lane in fired:
                self.on_fast_burn(lane)
        return out

    def status(self) -> dict:
        """The ``obs.slo`` reply / CLI ``slo`` verb payload."""
        with self._lock:
            state = {m: dict(st) for m, st in self._state.items()}
        out: dict = {
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn_threshold": self.fast_burn,
            "slow_burn_threshold": self.slow_burn,
            "models": {},
        }
        for model, obj in sorted(self.objectives.items()):
            st = state.get(model, {})
            body: dict = {
                "objective_latency_s": obj.latency_s,
                "availability": obj.availability,
                "p99_s": self.profiler.percentile(
                    99, model=model, stage=self.stage,
                    horizon_s=self.fast_window_s,
                ),
                "fast_burn": st.get("fast", 0.0),
                "slow_burn": st.get("slow", 0.0),
                "fast_alert": st.get("fast_alert", False),
                "slow_alert": st.get("slow_alert", False),
            }
            if self.attribution is not None:
                try:
                    body["culprit"] = self.attribution(model)
                except Exception:
                    log.exception("slo attribution failed for %s", model)
                    body["culprit"] = None
            if self.tenants:
                body["tenants"] = {
                    t: {
                        "p99_s": self.profiler.percentile(
                            99, model=f"{model}@{t}", stage=self.stage,
                            horizon_s=self.fast_window_s,
                        ),
                        "fast_burn": state.get(f"{model}@{t}", {}).get("fast", 0.0),
                        "slow_burn": state.get(f"{model}@{t}", {}).get("slow", 0.0),
                        "fast_alert": state.get(f"{model}@{t}", {}).get(
                            "fast_alert", False),
                        "slow_alert": state.get(f"{model}@{t}", {}).get(
                            "slow_alert", False),
                    }
                    for t in self.tenants
                }
            out["models"][model] = body
        return out

    def burning_models(self) -> list[str]:
        """Lanes currently in fast-burn alert (bare models plus any
        ``model@tenant`` composites) — what the leader's forced-sampling
        hook, the autoscaler, and the SLO-cert harness key off."""
        with self._lock:
            return sorted(
                m for m, st in self._state.items() if st.get("fast_alert")
            )


# ---------------------------------------------------------------------------
# Placement: greedy cost-balancing with hysteresis + move budget
# ---------------------------------------------------------------------------


@dataclass
class PlacementPlan:
    """One solved assignment: job -> members, plus per-member dispatch-pool
    weights (shards land proportionally to weight)."""

    assignment: dict[str, list[str]] = field(default_factory=dict)
    weights: dict[str, dict[str, int]] = field(default_factory=dict)
    excluded: list[str] = field(default_factory=list)
    moves: int = 0
    trigger: str = ""
    # job -> gang width: the job's members act as ONE placement unit (a chip
    # gang in member rank order, docs/SHARDING.md) instead of a dispatch
    # pool. Set when the model fits NO single member's HBM headroom but an
    # even ceil-share across `width` members fits each of them.
    gangs: dict[str, int] = field(default_factory=dict)


class PlacementAdvisor:
    """Turns profiler lanes into assignment plans. ``advise`` is called
    under the scheduler lock, so it must stay non-blocking and touch only
    leaf locks (the profiler's, the flight recorder's)."""

    MAX_WEIGHT = 8          # weight amplification cap per member
    REENTER_FRACTION = 0.7  # an excluded member re-enters below this x line

    def __init__(
        self,
        profiler: Any,
        *,
        flight: Any = None,
        metrics: Any = None,
        clock: Callable[[], float] = monotonic,
        max_moves: int = 2,
        window_s: float = 60.0,
        hysteresis: float = 0.15,
        exclude_factor: float = 3.0,
        stage: str = "dispatch",
        decode_idle: Callable[[str], float | None] | None = None,
        blob_locality: Callable[[str], float | None] | None = None,
        ingest_bias: float = 0.3,
        headroom: Callable[[str], float | None] | None = None,
        model_bytes: Callable[[str], float | None] | None = None,
    ) -> None:
        self.profiler = profiler
        self.flight = flight
        self.metrics = metrics
        self.clock = clock
        self.max_moves = int(max_moves)
        self.window_s = float(window_s)
        self.hysteresis = float(hysteresis)
        self.exclude_factor = float(exclude_factor)
        self.stage = stage
        # Ingest-aware placement (docs/INGEST.md §Decode tier): optional
        # per-member reads of idle decode lanes (the scraped
        # ``decode_lane_idle`` gauge) and SDFS blob locality (fraction of
        # the directory with a replica on that member). A member that can
        # FEED its chips is worth more than one that must pull every
        # pixel over the wire.
        self.decode_idle = decode_idle
        self.blob_locality = blob_locality
        self.ingest_bias = float(ingest_bias)
        # Memory-headroom HARD constraint (cluster/devicemon.py, docs/
        # OBSERVABILITY.md §8): per-member HBM headroom bytes (scraped
        # hbm_limit - hbm_in_use) and per-model analytic resident bytes.
        # A (job, member) pair whose KNOWN headroom cannot hold the KNOWN
        # model bytes is never assigned — unlike the ingest bias this is a
        # refusal, not a weighting. None on either side = no constraint
        # (unknown never blocks).
        self.headroom = headroom
        self.model_bytes = model_bytes
        self._last_blocked: dict[str, list[str]] = {}
        self._last_ingest: dict[str, float] = {}
        self._last_plan: PlacementPlan | None = None
        self._excluded: set[str] = set()
        self._moves_used = 0
        self._window_start: float | None = None
        # Replica targets (scheduler/autoscaler.py): per-job bound on how
        # many members the solver may deal to the job. The greedy dealer
        # naturally spreads every eligible member across jobs, so SHRINKING
        # the target is the actuation that matters (growing = raising it
        # back). For a gang job the target instead WIDENS the gang past its
        # minimal memory-fit width — more shards, more aggregate HBM
        # bandwidth — and never shrinks below what fits. Empty = unbounded
        # (pre-autoscaler behavior, bit for bit).
        self.replica_targets: dict[str, int] = {}

    def set_replica_target(self, job: str, target: int | None) -> None:
        """Bound (or, for gangs, widen to) ``target`` members for ``job``.
        None or <= 0 clears the bound."""
        if target is None or target <= 0:
            self.replica_targets.pop(job, None)
        else:
            self.replica_targets[job] = int(target)

    # ---- cost model ----------------------------------------------------

    def _costs(self, members: list[str]) -> tuple[dict[str, float], float]:
        """(per-member decayed mean dispatch cost, fleet median over the
        measured ones). Unmeasured members cost the median (innocent until
        profiled); with nothing measured anywhere, everyone costs 1.0."""
        measured = {}
        for m in members:
            c = self.profiler.mean_cost(m, stage=self.stage)
            if c is not None and c > 0:
                measured[m] = c
        if measured:
            ordered = sorted(measured.values())
            median = ordered[len(ordered) // 2]
        else:
            median = 1.0
        return {m: measured.get(m, median) for m in members}, median

    def _ingest_factors(self, members: list[str]) -> dict[str, float]:
        """Ingest-aware capacity multipliers: idle decode lanes (normalized
        to the fleet's best) and SDFS blob locality each add up to
        ``ingest_bias`` to a member's effective capacity — bounded
        [1, 1 + 2*bias], so ingest breaks ties and biases assignment but
        never overrides a measured dispatch-cost cliff. Empty when neither
        signal is wired (the pre-decode-tier behavior, bit for bit)."""
        if self.decode_idle is None and self.blob_locality is None:
            return {}
        idle: dict[str, float] = {}
        if self.decode_idle is not None:
            for m in members:
                try:
                    v = self.decode_idle(m)
                except Exception:
                    v = None
                if v is not None and v > 0:
                    idle[m] = float(v)
        max_idle = max(idle.values(), default=0.0)
        out: dict[str, float] = {}
        for m in members:
            f = 1.0
            if max_idle > 0:
                f += self.ingest_bias * idle.get(m, 0.0) / max_idle
            if self.blob_locality is not None:
                try:
                    loc = self.blob_locality(m)
                except Exception:
                    loc = None
                if loc:
                    f += self.ingest_bias * min(1.0, max(0.0, float(loc)))
            out[m] = round(f, 3)
        return out

    def _need_and_room(
        self, jobs: list[str], members: list[str]
    ) -> tuple[dict[str, float], dict[str, float]]:
        """(job -> known model resident bytes, member -> known HBM headroom
        bytes). Unknown on either side is simply absent (never constrains)."""
        need: dict[str, float] = {}
        room: dict[str, float] = {}
        if self.headroom is None or self.model_bytes is None:
            return need, room
        for job in jobs:
            try:
                b = self.model_bytes(job)
            except Exception:  # noqa: BLE001 - telemetry read; treat as unknown
                b = None
            if b is not None and b > 0:
                need[job] = float(b)
        for m in members:
            try:
                h = self.headroom(m)
            except Exception:  # noqa: BLE001 - telemetry read; treat as unknown
                h = None
            if h is not None:
                room[m] = float(h)
        return need, room

    def _blocked_pairs(
        self, jobs: list[str], members: list[str]
    ) -> dict[str, set[str]]:
        """job -> members that MUST NOT serve it solo: the member's reported
        HBM headroom (bytes) is known and smaller than the model's known
        analytic resident bytes. Either side unknown = unconstrained."""
        need, room = self._need_and_room(jobs, members)
        blocked: dict[str, set[str]] = {}
        for job, nbytes in need.items():
            bad = {m for m, h in room.items() if h < nbytes}
            if bad:
                blocked[job] = bad
        return blocked

    def _gang_plan(
        self,
        job: str,
        eligible: list[str],
        costs: dict[str, float],
        chip_weight: dict[str, int],
        need_bytes: float,
        room: dict[str, float],
    ) -> tuple[list[str], int] | None:
        """Trade replica count against shard width for a job NO single
        member can hold: the SMALLEST width whose even ceil-share of the
        model's resident bytes fits each chosen member's known headroom
        (minimal width leaves the most replica capacity for every other
        job). Members are chosen by cost-lane capacity — chip weight over
        measured dispatch cost — so the gang lands on the members that can
        actually feed it; unknown headroom never blocks, mirroring
        ``_blocked_pairs``. None when even the widest gang cannot fit."""
        ranked = sorted(
            eligible,
            key=lambda m: (
                -chip_weight.get(m, 1) / max(1e-9, costs.get(m, 1.0)),
                m,
            ),
        )
        for width in range(2, len(ranked) + 1):
            share = need_bytes / width
            fits = [m for m in ranked if room.get(m, float("inf")) >= share]
            if len(fits) >= width:
                want = self.replica_targets.get(job)
                if want is not None and want > width:
                    # Autoscaler asked for more fan-out than the minimal
                    # fit: widen while enough members hold the (smaller)
                    # per-shard share. Memory fit still wins — the target
                    # never narrows a gang below what fits.
                    for w2 in range(min(want, len(ranked)), width, -1):
                        share2 = need_bytes / w2
                        fits2 = [
                            m for m in ranked
                            if room.get(m, float("inf")) >= share2
                        ]
                        if len(fits2) >= w2:
                            return fits2[:w2], w2
                return fits[:width], width
        return None

    def _exclusions(self, costs: dict[str, float], median: float) -> set[str]:
        """Sticky outlier set: enter above ``exclude_factor`` x median,
        leave below ``REENTER_FRACTION`` x that line (hysteresis). Never
        excludes down to fewer members than jobs need — availability wins."""
        line = self.exclude_factor * median
        out = set()
        for m, c in sorted(costs.items()):
            if m in self._excluded:
                if c > self.REENTER_FRACTION * line:
                    out.add(m)
            elif c > line:
                out.add(m)
        return out

    @staticmethod
    def _plan_estimate(plan: PlacementPlan, jobs: dict[str, int],
                       costs: dict[str, float], chip_weight: dict[str, int]) -> float:
        """Estimated makespan: max over jobs of demand / service rate,
        where a member's rate is chips / measured cost."""
        worst = 0.0
        for name, members in plan.assignment.items():
            demand = max(1, jobs.get(name, 0))
            rate = sum(
                chip_weight.get(m, 1) / max(1e-9, costs.get(m, 1.0))
                for m in members
            )
            worst = max(worst, demand / rate if rate > 0 else float("inf"))
        return worst

    # ---- the solver ----------------------------------------------------

    def advise(
        self,
        jobs: dict[str, int],
        members: list[str],
        chip_weight: dict[str, int] | None = None,
        trigger: str = "periodic",
    ) -> PlacementPlan | None:
        """Solve job -> member placement from current profiles. ``jobs``
        maps job name to remaining demand (queries left); ``members`` is
        the eligible fleet (gray-demoted members already removed by the
        scheduler). Returns None when there is nothing to place (caller
        keeps its round-robin fallback)."""
        if not jobs or not members:
            return None
        chip_weight = chip_weight or {m: 1 for m in members}
        costs, median = self._costs(sorted(members))
        excluded = self._exclusions(costs, median)
        eligible = [m for m in sorted(members) if m not in excluded]
        if len(eligible) < len(jobs):
            # Not enough healthy members to give every job one: re-admit
            # the cheapest excluded members until every job can be served.
            readmit = sorted(excluded, key=lambda m: (costs[m], m))
            while len(eligible) < len(jobs) and readmit:
                back = readmit.pop(0)
                excluded.discard(back)
                eligible.append(back)
            eligible.sort()
        self._excluded = set(excluded)

        # Ingest-aware weighting AFTER exclusion (outliers are judged on
        # raw dispatch cost alone): a member's effective cost shrinks with
        # idle decode capacity and blob locality, which flows into both
        # the greedy deal below and the dispatch-pool weights.
        ingest = self._ingest_factors(sorted(members))
        self._last_ingest = ingest
        if ingest:
            costs = {m: c / ingest.get(m, 1.0) for m, c in costs.items()}

        # Hard headroom refusals, applied inside the solver: unlike the
        # exclusion set above (cost outliers, fleet-wide) a block is per
        # (job, member) — a member too full for vit_l14 may still serve
        # resnet18.
        blocked = self._blocked_pairs(sorted(jobs), sorted(members))
        self._last_blocked = {j: sorted(ms) for j, ms in sorted(blocked.items())}
        if blocked and self.metrics is not None:
            self.metrics.inc("placement_headroom_blocked")

        # Gang formation (docs/SHARDING.md): a job every eligible member is
        # blocked for is NOT refused — it becomes a chip gang wide enough
        # that each member's ceil-share of the model fits its headroom. Gang
        # jobs leave the solo solver (their members stay eligible for other
        # jobs' dispatch pools; the scheduler keeps the flows separate).
        need, room = self._need_and_room(sorted(jobs), sorted(members))
        gang_assign: dict[str, list[str]] = {}
        gang_width: dict[str, int] = {}
        solo_jobs = dict(jobs)
        for job in sorted(jobs):
            bad = blocked.get(job)
            if not bad or not eligible or not set(eligible) <= bad:
                continue
            got = self._gang_plan(
                job, eligible, costs, chip_weight, need[job], room
            )
            if got is None:
                continue  # truly unplaceable: _solve leaves it memberless
            gang_assign[job], gang_width[job] = got
            del solo_jobs[job]
            if self.metrics is not None:
                self.metrics.inc("placement_gangs_formed")

        plan = self._solve(solo_jobs, eligible, costs, chip_weight, blocked)
        for job, gang_members in gang_assign.items():
            plan.assignment[job] = list(gang_members)
            plan.weights[job] = {}
            plan.gangs[job] = gang_width[job]
        plan.excluded = sorted(excluded)
        plan.trigger = trigger

        previous = self._last_plan
        plan.moves = self._count_moves(previous, plan)
        now = self.clock()
        if self._window_start is None or now - self._window_start >= self.window_s:
            self._window_start = now
            self._moves_used = 0

        # A usable cached plan gates the new one behind hysteresis and the
        # move budget; a STALE one (departed members, missing jobs) never
        # does — reality already forced the change. Neither does a change
        # to the EXCLUSION set: exclusions are outlier/SLO-driven removals,
        # and the throughput estimate below would always score removing a
        # member as a loss (less capacity), burying the one change the
        # burn-rate alert exists to force.
        usable = previous is not None and not self._plan_stale(
            previous, jobs, set(members)
        )
        excluded_changed = previous is not None and (
            set(plan.excluded) != set(previous.excluded)
        )
        if usable and not excluded_changed:
            if (plan.moves == 0 and plan.assignment == previous.assignment
                    and plan.gangs == previous.gangs):
                return previous  # identical assignment: keep the cached object
            # Hysteresis: a reshuffle must buy a real improvement.
            old_est = self._plan_estimate(previous, jobs, costs, chip_weight)
            new_est = self._plan_estimate(plan, jobs, costs, chip_weight)
            improvement = (old_est - new_est) / old_est if old_est > 0 else 0.0
            if improvement < self.hysteresis:
                return previous
            # Move budget: bounded churn per window.
            if self._moves_used + plan.moves > self.max_moves:
                if self.metrics is not None:
                    self.metrics.inc("placement_throttled")
                if self.flight is not None:
                    self.flight.note(
                        "placement_throttled", trigger=trigger,
                        moves=plan.moves,
                        budget=self.max_moves - self._moves_used,
                    )
                return previous

        self._moves_used += plan.moves
        self._last_plan = plan
        if self.metrics is not None:
            self.metrics.inc("placement_decisions")
        if self.flight is not None:
            note = dict(
                trigger=trigger,
                moves=plan.moves,
                excluded=",".join(plan.excluded),
                assignment=";".join(
                    f"{n}={len(ms)}" for n, ms in sorted(plan.assignment.items())
                ),
            )
            if any(f > 1.0 for f in ingest.values()):
                # The ingest weighting is part of the routing decision, so
                # it must be reconstructible from the recorder (lint O2).
                note["ingest"] = ",".join(
                    f"{m}={f}" for m, f in sorted(ingest.items()) if f > 1.0
                )
            if blocked:
                # Headroom refusals shaped this plan — a postmortem of a
                # starved job must see WHICH members were refused (lint O2).
                note["headroom_blocked"] = ";".join(
                    f"{j}={','.join(sorted(ms))}" for j, ms in sorted(blocked.items())
                )
            if plan.gangs:
                # A gang is the plan's most consequential shape: which job
                # went multi-chip, how wide, on whom (lint O2).
                note["gangs"] = ";".join(
                    f"{j}:{w}={','.join(plan.assignment[j])}"
                    for j, w in sorted(plan.gangs.items())
                )
            if self.replica_targets:
                # Autoscaler bounds shaped this plan (lint O2).
                note["replica_targets"] = ",".join(
                    f"{j}={t}" for j, t in sorted(self.replica_targets.items())
                )
            self.flight.note("placement_decision", **note)
        return plan

    def _solve(
        self, jobs: dict[str, int], eligible: list[str],
        costs: dict[str, float], chip_weight: dict[str, int],
        blocked: dict[str, set[str]] | None = None,
    ) -> PlacementPlan:
        """Greedy balance: deal members (fastest first) to the job with the
        highest remaining demand per unit of capacity already granted.
        ``blocked`` pairs (headroom refusals) are never dealt — a job every
        member is blocked for ends up with NO members, which is the
        correct answer: dispatching it would OOM the member."""
        names = sorted(jobs)
        blocked = blocked or {}
        capacity = {
            m: chip_weight.get(m, 1) / max(1e-9, costs.get(m, 1.0))
            for m in eligible
        }
        granted = {n: 0.0 for n in names}
        assignment: dict[str, list[str]] = {n: [] for n in names}
        caps = self.replica_targets
        for m in sorted(eligible, key=lambda m: (-capacity[m], m)):
            # Most-starved job first: demand per granted capacity, with
            # empty jobs infinitely starved so everyone gets one member.
            candidates = [
                n for n in names
                if m not in blocked.get(n, ())
                and len(assignment[n]) < caps.get(n, len(eligible) + 1)
            ]
            if not candidates:
                continue  # member too full for every job this pass
            target = max(
                candidates,
                key=lambda n: (
                    float("inf") if not assignment[n]
                    else max(1, jobs[n]) / max(1e-9, granted[n]),
                    -len(assignment[n]),
                    # Most-constrained first on ties: a job refused on more
                    # members must take the members it CAN use, or an
                    # unconstrained peer drains them and strands it.
                    len(blocked.get(n, ())),
                    n,
                ),
            )
            assignment[target].append(m)
            granted[target] += capacity[m]
        weights: dict[str, dict[str, int]] = {}
        for n in names:
            ms = assignment[n]
            if not ms:
                weights[n] = {}
                continue
            # Normalize to the SLOWEST member: it anchors at weight 1 and
            # faster peers scale up with 1/cost (capped, so one fast member
            # cannot starve the interleave of everyone else).
            worst = max(costs.get(m, 1.0) for m in ms)
            weights[n] = {
                m: max(1, min(
                    self.MAX_WEIGHT * max(1, chip_weight.get(m, 1)),
                    round(chip_weight.get(m, 1) * worst / max(1e-9, costs.get(m, 1.0))),
                ))
                for m in ms
            }
        return PlacementPlan(assignment=assignment, weights=weights)

    @staticmethod
    def _count_moves(previous: PlacementPlan | None, plan: PlacementPlan) -> int:
        """Members newly added to a job they weren't serving before (the
        disruptive direction: a move re-points dispatch traffic)."""
        if previous is None:
            return 0
        moves = 0
        for name, ms in plan.assignment.items():
            before = set(previous.assignment.get(name, ()))
            moves += sum(1 for m in ms if m not in before)
        return moves

    def _plan_stale(self, previous: PlacementPlan, jobs: dict[str, int],
                    members: set[str]) -> bool:
        """A cached plan is unusable (bypasses hysteresis/budget) when it
        references departed members, misses a job entirely, or deals a job
        more SOLO members than its replica target allows — a shrink from
        the autoscaler must land this advise, not after the hysteresis
        gate happens to open."""
        for name in jobs:
            ms = previous.assignment.get(name)
            if not ms or any(m not in members for m in ms):
                return True
        for name, target in self.replica_targets.items():
            if name in previous.gangs:
                continue  # gang width is memory-driven; target only widens
            if len(previous.assignment.get(name, ())) > target:
                return True
        return False

    def status(self) -> dict:
        plan = self._last_plan
        return {
            "excluded": sorted(self._excluded),
            "moves_used": self._moves_used,
            "max_moves": self.max_moves,
            "window_s": self.window_s,
            "ingest_factors": {
                m: f for m, f in sorted(self._last_ingest.items()) if f > 1.0
            },
            "headroom_blocked": {
                j: list(ms) for j, ms in sorted(self._last_blocked.items())
            },
            "assignment": {} if plan is None else {
                n: list(ms) for n, ms in sorted(plan.assignment.items())
            },
            "gangs": {} if plan is None else dict(sorted(plan.gangs.items())),
            "replica_targets": dict(sorted(self.replica_targets.items())),
        }


__all__ = ["PlacementAdvisor", "PlacementPlan", "SloEvaluator", "SloObjective"]
