"""GenRouter: leader-routed generation sessions that survive the fleet.

Before this module a generation stream was a per-member island: the client
dialed one member's GenerateWorker and everything recoverable about the
stream — KV pages, slot, undelivered chunks — lived in that member's RAM.
The router makes the stream a FLEET-level object (docs/GENERATE.md
§Routing/§Migration/§Drain):

- **Routing.** ``job.generate`` on the LEADER picks a member by the gauges
  every node already exports (``generate-<model>_slots_active``,
  ``generate-<model>_pages_free``, ``mfu_<model>``), corrected by the
  ledger's own residency view (a just-routed session is not in any scrape
  yet), honoring tenant quotas (cluster/tenant.py) and session affinity
  (same tenant+model prefers its existing member as a tiebreak). Draining
  and breaker-convicted members admit nothing new.
- **Session ledger.** session id → model, prompt, sampling params, RNG
  seed, tenant, deadline budget, placed member, cumulative acked-token
  prefix. The ledger rides the leader-state machinery exactly like
  scheduler/jobs.py job cursors: epoch-keyed ``gen.state`` wire snapshots,
  pulled by the StandbyLeader every sync tick, adopted without ever
  rewinding a delivered prefix — so a promoted leader re-adopts every live
  stream (and re-adoption is idempotent: merging by sid cannot create a
  second placement).
- **Migration.** On membership loss, breaker conviction, member amnesia
  (alive but lost the session), or a drain deadline, the router re-submits
  ``prompt + delivered_prefix`` with the session's seed to a survivor
  (``resume_tokens`` entry, generate/slots.py) — the engine's
  position-seeded sampling RNG makes the continuation token-identical to
  the unkilled reference — and splices the member's restarted chunk seqs
  into its own continuous out-seq space, so the client's cumulative-ack
  dedup keeps working unchanged: exactly-once end to end, nothing lost,
  nothing doubled. The member-side ``job.generate`` is idempotent on a
  caller gen_id, which bounds migration to ≤1 prefill per failure even
  when a promoted leader retries a dead leader's in-flight migration.
- **Drain.** ``job.drain`` flips a member to stop-admitting; resident
  sessions finish within the drain deadline or migrate; the autoscaler's
  scale-down goes through ``release_capacity`` (drain-then-shrink) instead
  of abandoning sessions. Every transition is flight-recorded (``route``,
  ``migrate``, ``drain_start``, ``drain_complete``, ``session_lost``) with
  counters ``gen_sessions_routed``/``gen_migrations`` and the
  ``gen_drain_active`` gauge.

Lock discipline (dmlc-lint L1): the router lock guards ONLY ledger state;
every RPC happens outside it — handlers snapshot under the lock, call,
then fold the reply back under the lock with a staleness check (session
gone or re-placed meanwhile → the reply is dropped). ``members()`` and
``metrics_for()`` are LOCAL reads by contract (membership snapshot, scrape
cache), never network calls.
"""

from __future__ import annotations

import logging
import os
import threading
from collections.abc import Callable, Mapping
from time import monotonic
from typing import Any

from dmlc_tpu.cluster import tenant as tenant_mod
from dmlc_tpu.cluster import tracectx
from dmlc_tpu.cluster.rpc import Overloaded, RpcError, RpcUnreachable
from dmlc_tpu.utils.tracing import traced_methods

log = logging.getLogger(__name__)

#: states in which a session occupies a member slot (or is about to)
_LIVE_STATES = ("running", "migrating")


class Session:
    """One stream's ledger entry — everything a leader needs to re-route,
    migrate, or re-adopt it. ``delivered`` is the token prefix the router
    has folded from member chunk streams (the migration prefill payload);
    ``out_chunks``/``out_seq`` are the router's OWN seq space toward the
    client, spliced continuously across placements; ``member_acked`` is
    the cumulative ack toward the CURRENT placement (resets to 0 on
    migration because a resumed member stream restarts its seqs)."""

    __slots__ = (
        "sid", "model", "prompt", "max_new_tokens", "temperature", "eos_id",
        "seed", "tenant", "deadline_s", "member", "delivered",
        "member_acked", "out_seq", "out_chunks", "client_acked", "state",
        "error", "migrations", "trace", "routed_t", "touched", "tenant_held",
    )

    def __init__(self, sid: str, model: str, prompt: list[int],
                 max_new_tokens: int, temperature: float, eos_id: int | None,
                 seed: int, tenant: str, deadline_s: float | None,
                 member: str, trace: list | None, now: float) -> None:
        self.sid = sid
        self.model = model
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.seed = seed
        self.tenant = tenant
        self.deadline_s = deadline_s
        self.member = member
        self.delivered: list[int] = []
        self.member_acked = 0
        self.out_seq = 0
        self.out_chunks: list[tuple[int, list[int]]] = []
        self.client_acked = 0
        self.state = "running"  # running | migrating | done | lost
        self.error: str | None = None
        self.migrations = 0
        self.trace = trace
        self.routed_t = now
        self.touched = now
        self.tenant_held = False

    def live(self) -> bool:
        return self.state in _LIVE_STATES

    def to_wire(self) -> dict[str, Any]:
        return {
            "model": self.model, "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "temperature": self.temperature, "eos_id": self.eos_id,
            "seed": self.seed, "tenant": self.tenant,
            "deadline_s": self.deadline_s, "member": self.member,
            "delivered": list(self.delivered),
            "member_acked": self.member_acked, "out_seq": self.out_seq,
            "out_chunks": [[seq, list(toks)] for seq, toks in self.out_chunks],
            "client_acked": self.client_acked, "state": self.state,
            "error": self.error, "migrations": self.migrations,
            "trace": self.trace, "routed_t": self.routed_t,
        }

    @classmethod
    def from_wire(cls, sid: str, w: Mapping[str, Any], now: float) -> "Session":
        s = cls(
            sid, str(w["model"]), [int(t) for t in w["prompt"]],
            int(w["max_new_tokens"]), float(w.get("temperature", 0.0)),
            int(w["eos_id"]) if w.get("eos_id") is not None else None,
            int(w.get("seed", 0)), str(w.get("tenant", "default")),
            w.get("deadline_s"), str(w["member"]), w.get("trace"), now,
        )
        s.delivered = [int(t) for t in w.get("delivered", [])]
        s.member_acked = int(w.get("member_acked", 0))
        s.out_seq = int(w.get("out_seq", 0))
        s.out_chunks = [
            (int(seq), [int(t) for t in toks])
            for seq, toks in w.get("out_chunks", [])
        ]
        s.client_acked = int(w.get("client_acked", 0))
        s.state = str(w.get("state", "running"))
        s.error = w.get("error")
        s.migrations = int(w.get("migrations", 0))
        return s


class GenRouter:
    """Leader-side session router + ledger (module docstring)."""

    def __init__(
        self,
        rpc: Any,
        members: Callable[[], list[str]],
        *,
        metrics_for: Callable[[str], Mapping[str, float] | None] | None = None,
        tenants: Any = None,
        max_sessions: int = 256,
        drain_deadline_s: float = 30.0,
        session_ttl_s: float = 600.0,
        timeout_s: float = 10.0,
        retry_policy: Any = None,
        metrics: Any = None,
        flight: Any = None,
        clock: Callable[[], float] = monotonic,
    ) -> None:
        self.rpc = rpc
        self.members = members
        self.metrics_for = metrics_for
        self.max_sessions = int(max_sessions)
        self.drain_deadline_s = float(drain_deadline_s)
        self.session_ttl_s = float(session_ttl_s)
        self.timeout_s = float(timeout_s)
        self.retry_policy = retry_policy
        self.metrics = metrics
        self.flight = flight
        self.clock = clock
        # Set by StandbyLeader on promotion/abdication, like
        # JobScheduler.is_leading/epoch — candidates compare terms.
        self.is_leading = False
        self.epoch: list = [0, ""]
        self._tenants = tenants
        self.ledger = tenant_mod.TenantLedger(tenants, self.max_sessions)
        self._lock = threading.RLock()
        self._sessions: dict[str, Session] = {}
        # member -> {"t", "deadline_s", "complete", "reason"}
        self._drains: dict[str, dict[str, Any]] = {}

    # ---- RPC surface ----------------------------------------------------

    def methods(self) -> dict[str, Any]:
        return traced_methods({
            "job.generate": self._generate,
            "job.generate_poll": self._poll,
            "job.generate_cancel": self._cancel,
            "job.generate_sessions": lambda p: {
                "sessions": self.sessions_table()
            },
            "job.drain": self._drain_rpc,
            "job.undrain": self._undrain_rpc,
            "gen.state": lambda p: self.to_wire(),
        })

    def _require_leading(self) -> None:
        # Same guard as JobScheduler._start_rpc: a deferring standby must
        # not place sessions the acting leader knows nothing about.
        if not self.is_leading:
            raise RpcError("not the active leader")

    # ---- routing --------------------------------------------------------

    def _generate(self, p: dict[str, Any]) -> dict[str, Any]:
        self._require_leading()
        model = str(p["model"])
        prompt = [int(t) for t in p["prompt"]]
        max_new = int(p["max_new_tokens"])
        temperature = float(p.get("temperature", 0.0))
        eos_id = int(p["eos_id"]) if p.get("eos_id") is not None else None
        tenant = tenant_mod.current()
        sid = str(p.get("gen_id") or os.urandom(8).hex())
        if p.get("seed") is not None:
            seed = int(p["seed"])
        else:
            seed = int.from_bytes(os.urandom(4), "big") >> 1
        with self._lock:
            self._sweep_locked()
            existing = self._sessions.get(sid)
            if existing is not None:
                # Idempotent re-submit: the ledger entry IS the answer.
                return {"gen_id": sid, "model": existing.model,
                        "member": existing.member, "resumed": True}
            if self.ledger.would_exceed(tenant):
                self.ledger.note_shed(tenant)
                self._shed_note(tenant, "over_quota")
                raise Overloaded(
                    f"genroute: tenant {tenant!r} at quota "
                    f"({self.ledger.active(tenant)}/{self.ledger.quota(tenant)})",
                    retry_after_s=0.25, tenant=tenant, quota="over_quota",
                )
            live = sum(1 for s in self._sessions.values() if s.live())
            if live >= self.max_sessions:
                self._shed_note(tenant, "gate_full")
                raise Overloaded(
                    f"genroute: session ledger full ({live} live)",
                    retry_after_s=0.25, tenant=tenant, quota="gate_full",
                )
        payload: dict[str, Any] = {
            "model": model, "prompt": prompt, "max_new_tokens": max_new,
            "temperature": temperature, "eos_id": eos_id,
            "gen_id": sid, "seed": seed,
        }
        excluded: set[str] = set()
        target: str | None = None
        for _ in range(8):
            with self._lock:
                candidate = self._pick_locked(model, tenant, excluded)
            if candidate is None:
                raise RpcError(
                    f"no eligible member serves {model!r} "
                    f"(draining/convicted/dead excluded: {sorted(excluded)})"
                )
            try:
                # Outside the lock (L1). Overloaded propagates typed to the
                # client — its retry-after contract is the member's shed.
                self.rpc.call(candidate, "job.generate", payload,
                              timeout=self.timeout_s)
            except RpcUnreachable:
                # Dead-but-not-yet-detected member: try the next one.
                excluded.add(candidate)
                continue
            target = candidate
            break
        if target is None:
            raise RpcError(
                f"every candidate for {model!r} was unreachable: "
                f"{sorted(excluded)}"
            )
        now = self.clock()
        with self._lock:
            if sid in self._sessions:
                # Lost a concurrent duplicate-submit race: the member-side
                # gen_id dedup means both submits share one stream; keep
                # the first ledger entry (no double adoption).
                s = self._sessions[sid]
                return {"gen_id": sid, "model": s.model, "member": s.member,
                        "resumed": True}
            s = Session(sid, model, prompt, max_new, temperature, eos_id,
                        seed, tenant, None, target,
                        tracectx.to_wire(tracectx.current()), now)
            self._sessions[sid] = s
            self.ledger.acquire(tenant)
            s.tenant_held = True
        if self.metrics is not None:
            self.metrics.inc("gen_sessions_routed")
        if self.flight is not None:
            self.flight.note("route", gen_id=sid, model=model, member=target,
                             tenant=tenant, prompt=len(prompt))
        return {"gen_id": sid, "model": model, "member": target}

    def _shed_note(self, tenant: str, verdict: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("shed")
            self.metrics.inc("shed_genroute")
        if self.flight is not None:
            self.flight.note("shed", gate="genroute", tenant=tenant,
                             quota=verdict)

    def _pick_locked(self, model: str, tenant: str,
                     exclude: set[str]) -> str | None:
        """Least-loaded eligible member by the scraped gauges, with the
        ledger's own residency correcting scrape lag and session affinity
        (same tenant+model) breaking ties. Draining and breaker-convicted
        members are never eligible."""
        candidates = []
        for m in self.members():
            if m in exclude or m in self._drains:
                continue
            if self.retry_policy is not None and not self.retry_policy.allow(m):
                continue
            candidates.append(m)
        if not candidates:
            return None
        resident: dict[str, int] = {}
        affinity: set[str] = set()
        for s in self._sessions.values():
            if s.live():
                resident[s.member] = resident.get(s.member, 0) + 1
                if s.tenant == tenant and s.model == model:
                    affinity.add(s.member)

        def load(m: str) -> float:
            g = self.metrics_for(m) if self.metrics_for is not None else None
            g = g or {}
            # A scraped gauge can be PRESENT but None-valued (hbm_*/mfu_*
            # degrade gracefully on CPU backends) — treat None as zero.
            slots = float(g.get(f"generate-{model}_slots_active") or 0.0)
            pages = float(g.get(f"generate-{model}_pages_free") or 0.0)
            mfu = float(g.get(f"mfu_{model}") or 0.0)
            # Busy slots and a hot chip push a member down the order; free
            # KV pages pull it up (pages are what a long prompt needs).
            return resident.get(m, 0) + slots + mfu - 0.01 * pages

        return min(
            candidates,
            key=lambda m: (round(load(m), 3), 0 if m in affinity else 1, m),
        )

    # ---- streaming ------------------------------------------------------

    def _poll(self, p: dict[str, Any]) -> dict[str, Any]:
        self._require_leading()
        sid = str(p["gen_id"])
        ack = int(p.get("ack", 0))
        now = self.clock()
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                raise RpcError(f"unknown generation {sid!r} (done+acked, "
                               "cancelled, or expired)")
            s.touched = now
            if ack > s.client_acked:
                s.client_acked = ack
                s.out_chunks = [c for c in s.out_chunks if c[0] > ack]
            member, macked = s.member, s.member_acked
            fetch = s.state == "running"
        amnesia = False
        if fetch:
            try:
                r = self.rpc.call(member, "job.generate_poll",
                                  {"gen_id": sid, "ack": macked},
                                  timeout=self.timeout_s)
            except RpcUnreachable as e:
                # Serve retained chunks; the tick loop owns the
                # migrate-or-not verdict (one lost poll isn't a conviction).
                log.warning("poll of %s on %s unreachable: %s", sid, member, e)
                r = None
            except RpcError as e:
                r = None
                if "unknown generation" in str(e):
                    # Member amnesia: alive but restarted (or swept) — its
                    # copy of the session is gone for good. Migrate now.
                    amnesia = True
                else:
                    log.warning("poll of %s on %s failed: %s", sid, member, e)
            if r is not None:
                self._fold(sid, member, r)
        if amnesia:
            self._migrate(sid, "member_amnesia")
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                raise RpcError(f"unknown generation {sid!r}")
            return {
                "chunks": [[seq, list(toks)] for seq, toks in s.out_chunks],
                "done": s.state in ("done", "lost"),
                "error": s.error,
            }

    def _fold(self, sid: str, member: str, r: Mapping[str, Any]) -> None:
        """Splice a member poll reply into the session's own seq space.
        Exactly-once: ``member_acked`` is cumulative per placement and the
        ``s.member == member`` staleness check drops replies from a
        placement the session migrated away from mid-call."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is None or s.member != member or s.state != "running":
                return
            for seq, toks in sorted(r.get("chunks", [])):
                seq = int(seq)
                if seq <= s.member_acked:
                    continue
                s.member_acked = seq
                toks = [int(t) for t in toks]
                s.delivered.extend(toks)
                s.out_seq += 1
                s.out_chunks.append((s.out_seq, toks))
            if r.get("done"):
                s.state = "done"
                s.error = r.get("error")
                self._retire_locked(s)

    def _cancel(self, p: dict[str, Any]) -> dict[str, Any]:
        self._require_leading()
        sid = str(p["gen_id"])
        with self._lock:
            s = self._sessions.pop(sid, None)
            if s is None:
                return {"cancelled": False}
            member = s.member if s.state == "running" else None
            self._retire_locked(s)
        if member is not None:
            try:
                self.rpc.call(member, "job.generate_cancel",
                              {"gen_id": sid, "reason": "cancel"},
                              timeout=self.timeout_s)
            except (RpcUnreachable, RpcError) as e:
                # The member-side TTL sweep reaps it eventually.
                log.warning("cancel of %s on %s failed: %s", sid, member, e)
        return {"cancelled": True}

    # ---- drain ----------------------------------------------------------

    def _drain_rpc(self, p: dict[str, Any]) -> dict[str, Any]:
        self._require_leading()
        deadline = p.get("deadline_s")
        return self.drain(str(p["member"]),
                          deadline_s=float(deadline)
                          if deadline is not None else None)

    def _undrain_rpc(self, p: dict[str, Any]) -> dict[str, Any]:
        self._require_leading()
        return self.undrain(str(p["member"]))

    def drain(self, member: str, deadline_s: float | None = None,
              reason: str = "operator") -> dict[str, Any]:
        """Flip ``member`` to stop-admitting. Resident sessions get
        ``deadline_s`` to finish; whoever is still live at the deadline is
        migrated by the tick loop. Idempotent (a re-drain tightens the
        deadline, never extends it)."""
        if deadline_s is None:
            deadline_s = self.drain_deadline_s
        with self._lock:
            d = self._drains.get(member)
            fresh = d is None
            if d is None:
                d = self._drains[member] = {
                    "t": self.clock(), "deadline_s": float(deadline_s),
                    "complete": False, "reason": reason,
                }
            else:
                d["deadline_s"] = min(float(d["deadline_s"]), float(deadline_s))
            resident = sum(1 for s in self._sessions.values()
                           if s.live() and s.member == member)
            effective = float(d["deadline_s"])
        if fresh:
            if self.flight is not None:
                self.flight.note("drain_start", member=member,
                                 deadline_s=effective, resident=resident,
                                 reason=reason)
            log.info("draining %s: %d resident session(s), deadline %.1fs",
                     member, resident, effective)
        return {"member": member, "draining": True,
                "deadline_s": effective, "resident": resident}

    def undrain(self, member: str) -> dict[str, Any]:
        with self._lock:
            was = self._drains.pop(member, None)
        return {"member": member, "draining": False, "was": was is not None}

    def drain_active(self) -> int:
        """The ``gen_drain_active`` gauge: members mid-drain (not complete)."""
        with self._lock:
            return sum(1 for d in self._drains.values() if not d["complete"])

    def draining(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {
                m: {"deadline_s": d["deadline_s"], "complete": d["complete"],
                    "reason": d["reason"],
                    "age_s": round(self.clock() - d["t"], 3)}
                for m, d in self._drains.items()
            }

    def release_capacity(self, model: str, keep: int) -> bool:
        """Autoscaler scale-down seam (scheduler/autoscaler.py drain hook):
        OK to shrink to ``keep`` members only once at most ``keep`` still
        hold live sessions of ``model``. Otherwise initiate a drain on the
        lightest extra member(s) and HOLD the shrink until their sessions
        finish or migrate — scale-down must never abandon a stream."""
        with self._lock:
            hosting: dict[str, int] = {}
            for s in self._sessions.values():
                if s.live() and s.model == model:
                    hosting[s.member] = hosting.get(s.member, 0) + 1
            extra = len(hosting) - int(keep)
            if extra <= 0:
                return True
            victims = [
                m for m in sorted(hosting, key=lambda m: (hosting[m], m))
                if m not in self._drains
            ][:extra]
        for m in victims:
            self.drain(m, reason="autoscale")
        return False

    # ---- migration (tick loop) ------------------------------------------

    def tick(self) -> dict[str, int]:
        """Leader-loop body: migrate sessions off dead, breaker-convicted,
        or deadline-expired-drain members; mark drains complete when they
        empty; sweep expired ledger entries. No-op on a non-leader."""
        if not self.is_leading:
            return {"migrated": 0}
        alive = set(self.members())
        now = self.clock()
        moves: list[tuple[str, str]] = []
        with self._lock:
            self._sweep_locked()
            for sid, s in self._sessions.items():
                if s.state != "running":
                    continue
                m = s.member
                if m not in alive:
                    moves.append((sid, "member_lost"))
                elif self.retry_policy is not None and \
                        not self.retry_policy.allow(m):
                    moves.append((sid, "breaker"))
                else:
                    d = self._drains.get(m)
                    if d is not None and now - d["t"] >= d["deadline_s"]:
                        moves.append((sid, "drain"))
        migrated = 0
        for sid, why in moves:
            if self._migrate(sid, why):
                migrated += 1
        completed: list[str] = []
        with self._lock:
            for member, d in self._drains.items():
                if d["complete"]:
                    continue
                if any(s.live() and s.member == member
                       for s in self._sessions.values()):
                    continue
                d["complete"] = True
                completed.append(member)
        for member in completed:
            if self.flight is not None:
                self.flight.note("drain_complete", member=member)
            log.info("drain of %s complete (no resident sessions)", member)
        return {"migrated": migrated}

    def _migrate(self, sid: str, why: str) -> bool:
        """Move one session to a survivor: re-prefill prompt+delivered with
        the session's seed (token-identical continuation, engine docstring)
        and reset the per-placement ack. The ``migrating`` state is the
        single-flight guard — a concurrent tick/poll cannot double-migrate."""
        with self._lock:
            s = self._sessions.get(sid)
            if s is None or s.state != "running":
                return False
            if s.eos_id is not None and s.delivered and \
                    s.delivered[-1] == s.eos_id:
                # The terminal token already reached the ledger; the member
                # died between it and the done verdict. Nothing to resume.
                s.state = "done"
                self._retire_locked(s)
                return False
            remaining = s.max_new_tokens - len(s.delivered)
            if remaining <= 0:
                s.state = "done"
                self._retire_locked(s)
                return False
            s.state = "migrating"
            old = s.member
            target = self._pick_locked(s.model, s.tenant, {old})
            if target is None:
                self._lost_locked(
                    s, f"no surviving member serves {s.model!r} ({why})"
                )
                return False
            payload = {
                "model": s.model, "prompt": list(s.prompt),
                "max_new_tokens": remaining, "temperature": s.temperature,
                "eos_id": s.eos_id, "gen_id": sid, "seed": s.seed,
                "resume_tokens": list(s.delivered),
            }
            tenant, trace = s.tenant, s.trace
            old_alive = old in set(self.members())
        if old_alive:
            # Drain/breaker path: the old member still holds the slot —
            # release it so it stops decoding dead tokens (reason rides
            # into its session_sweep flight note).
            try:
                self.rpc.call(old, "job.generate_cancel",
                              {"gen_id": sid, "reason": "migrated"},
                              timeout=self.timeout_s)
            except (RpcUnreachable, RpcError) as e:
                log.warning("cancel of %s on %s failed: %s", sid, old, e)
        try:
            # The session's submit-time trace context parents the resumed
            # member's rpc/job.generate + gen/* spans into the SAME trace
            # (tools/trace_smoke.py pins this), and the tenant binding
            # keeps quota attribution across the hop.
            with tenant_mod.bind(tenant):
                with tracectx.bind(tracectx.from_wire(trace)):
                    self.rpc.call(target, "job.generate", payload,
                                  timeout=self.timeout_s)
        except (Overloaded, RpcUnreachable) as e:
            # Target shed or died before prefilling anything: back to
            # ``running`` so the next tick retries another survivor.
            log.warning("resume of %s on %s deferred: %s", sid, target, e)
            with self._lock:
                s2 = self._sessions.get(sid)
                if s2 is not None and s2.state == "migrating":
                    s2.state = "running"
            return False
        except RpcError as e:
            # A refusal (resume prefix exceeds the target's max_prefill,
            # unknown model): terminal for this stream.
            with self._lock:
                s2 = self._sessions.get(sid)
                if s2 is not None and s2.state == "migrating":
                    self._lost_locked(s2, f"resume on {target} refused: {e}")
            return False
        with self._lock:
            s2 = self._sessions.get(sid)
            if s2 is None or s2.state != "migrating":
                return False
            s2.member = target
            s2.member_acked = 0
            s2.migrations += 1
            s2.state = "running"
            delivered = len(s2.delivered)
        if self.metrics is not None:
            self.metrics.inc("gen_migrations")
        if self.flight is not None:
            self.flight.note("migrate", gen_id=sid, from_=old, to=target,
                             reason=why, delivered=delivered)
        log.info("migrated session %s %s -> %s (%s, %d tokens re-prefilled)",
                 sid, old, target, why, delivered)
        return True

    def _lost_locked(self, s: Session, why: str) -> None:
        s.state = "lost"
        s.error = f"session lost: {why}"
        self._retire_locked(s)
        if self.metrics is not None:
            self.metrics.inc("gen_sessions_lost")
        if self.flight is not None:
            self.flight.note("session_lost", gen_id=s.sid, member=s.member,
                             reason=why)
        log.warning("session %s lost: %s", s.sid, why)

    def _retire_locked(self, s: Session) -> None:
        if s.tenant_held:
            s.tenant_held = False
            self.ledger.release(s.tenant)

    def _sweep_locked(self) -> None:
        now = self.clock()
        for sid, s in list(self._sessions.items()):
            if now - s.touched <= self.session_ttl_s:
                continue
            self._sessions.pop(sid)
            self._retire_locked(s)
            if s.live():
                # Abandoned live stream: the member-side TTL sweep reaps
                # its slot; dropping the ledger entry stops routing
                # maintenance for it.
                log.info("swept abandoned session %s", sid)

    # ---- leader-state machinery -----------------------------------------

    def to_wire(self) -> dict[str, Any]:
        """Epoch-keyed ledger snapshot (``gen.state``), the standby sync
        payload — same shape discipline as JobScheduler.to_wire."""
        with self._lock:
            return {
                "epoch": list(self.epoch),
                "sessions": {sid: s.to_wire()
                             for sid, s in self._sessions.items()},
                "drains": {m: dict(d) for m, d in self._drains.items()},
            }

    def adopt_state(self, wire: Mapping[str, Any]) -> int:
        """Copy the leader's ledger (standby sync loop). Never rewinds a
        session's delivered prefix — a stale snapshot must not undo folded
        tokens — and merges by sid, so adoption is idempotent and a sid can
        never be adopted into two entries (the no-duplicate-adoption
        invariant dmlc-mc's ``session_migrate`` scenario checks). Returns
        the number of NEW sids adopted."""
        adopted = 0
        now = self.clock()
        with self._lock:
            for sid, w in dict(wire.get("sessions", {})).items():
                cur = self._sessions.get(sid)
                if cur is not None and \
                        len(cur.delivered) > len(w.get("delivered", ())):
                    continue
                if cur is None:
                    adopted += 1
                self._sessions[sid] = Session.from_wire(sid, w, now)
            for m, d in dict(wire.get("drains", {})).items():
                if m not in self._drains:
                    self._drains[m] = dict(d)
            self._rebuild_ledger_locked()
        return adopted

    def readopt(self) -> int:
        """Promotion hook (StandbyLeader._promote): every live entry keeps
        its placement — the new leader RE-ADOPTS streams, it never
        re-places them (that would be the duplicate-prefill bug the soak
        pins). A migration the dead leader left in flight drops back to
        ``running`` so the tick loop re-drives it; the member-side gen_id
        dedup keeps even a double-driven migration at one prefill."""
        with self._lock:
            n = 0
            for s in self._sessions.values():
                if s.state == "migrating":
                    s.state = "running"
                if s.live():
                    n += 1
            self._rebuild_ledger_locked()
        if self.flight is not None and n:
            self.flight.note("gen_readopt", sessions=n,
                             epoch=list(self.epoch))
        return n

    def _rebuild_ledger_locked(self) -> None:
        self.ledger = tenant_mod.TenantLedger(self._tenants,
                                              self.max_sessions)
        for s in self._sessions.values():
            if s.live():
                self.ledger.acquire(s.tenant)
                s.tenant_held = True
            else:
                s.tenant_held = False

    # ---- observability --------------------------------------------------

    def sessions_table(self) -> list[dict[str, Any]]:
        """The CLI ``sessions`` verb's rows, route order."""
        with self._lock:
            return [
                {"id": s.sid, "model": s.model, "member": s.member,
                 "tenant": s.tenant, "delivered": len(s.delivered),
                 "state": s.state, "migrations": s.migrations}
                for s in sorted(self._sessions.values(),
                                key=lambda s: (s.routed_t, s.sid))
            ]

    def status(self) -> dict[str, Any]:
        with self._lock:
            live = sum(1 for s in self._sessions.values() if s.live())
            total = len(self._sessions)
        return {
            "sessions": live,
            "total": total,
            "drains": self.draining(),
        }
