"""Leader-side ML job scheduling: assignment, shard dispatch, metrics, resume.

Capability parity with the reference's L4 (src/services.rs):

- ``Job`` tracks finished/correct counts, latency samples, and assigned
  members (services.rs:54-81)
- every assignment pass splits the active membership evenly across running
  jobs (services.rs:199-211: 50/50 for its 2 static jobs)
- dispatch picks an assigned member and issues a predict RPC, recording
  correctness + wall latency (services.rs:407-433)
- ``jobs`` report: accuracy + mean/std/median/p90/p95/p99 (main.rs:282-309)
- resume-from-cursor: a re-elected leader continues from
  ``finished_prediction_count`` (services.rs:410-411,221-227)

Redesigned, not translated: the dispatch unit is a *shard* of the query list
(config.dispatch_shard_size), not one image per RPC — the member answers a
whole shard with one batched XLA execution, which is how the >10k img/s/chip
target is reachable at all (the reference's 1-image-per-0.5 s tick caps at
2 qps/job, services.rs:408). Shards are handed out round-robin over the
job's assigned members; correctness is judged on the leader against the
synset order of synset_words.txt (services.rs:170-184).

Concurrency model: many dispatcher threads call ``dispatch_once``
simultaneously (the reference fired queries fire-and-forget,
services.rs:418-421); each call reserves a distinct shard offset under the
lock, blocks on its member's RPC, then records the result. Results may
arrive out of order, so they buffer per-offset and only a *contiguous
prefix* is counted into ``finished`` — the durable cursor the standby
leaders replicate. Failed shards requeue with the failed member excluded;
a shard raced to two members counts exactly once (offset-keyed dedup).
"""

from __future__ import annotations

import logging
import threading
from collections.abc import Callable
from dataclasses import dataclass, field
from time import monotonic

from dmlc_tpu.cluster.rpc import (
    DeadlineExceeded,
    Overloaded,
    Rpc,
    RpcError,
    RpcUnreachable,
)
from dmlc_tpu.scheduler.worker import gang_slice
from dmlc_tpu.utils.metrics import Counters, LatencyStats
from dmlc_tpu.utils.tracing import traced_methods, tracer

log = logging.getLogger(__name__)


@dataclass
class Job:
    """One inference job over a labeled query list."""

    model_name: str
    queries: list[tuple[str, int]]  # (synset_id, true_class_index)
    finished: int = 0               # contiguous-prefix cursor (replicated)
    correct: int = 0
    running: bool = False
    assigned: list[str] = field(default_factory=list)
    # Weighted dispatch pool: each assigned member repeated by its chip
    # count, interleaved — round-robin picks then land shards on hosts in
    # proportion to their device capacity (the north star's ICI-local
    # placement: a 8-chip host gets 8x the shards of a 1-chip host).
    dispatch_pool: list[str] = field(default_factory=list)
    query_stats: LatencyStats = field(default_factory=LatencyStats)
    shard_stats: LatencyStats = field(default_factory=LatencyStats)
    # Per-member shard latency (leader-local observability — the
    # reference's `jobs` report aggregated only per job).
    member_stats: dict = field(default_factory=dict)
    _next_member: int = 0
    # Cached shard_stats p50 for hedge eligibility: the percentile is a sort
    # of up to 4096 reservoir samples, and the check runs on every idle
    # dispatcher poll under the scheduler lock — recompute only after a new
    # sample lands (None = dirty).
    _median_cache: float | None = None
    # --- in-flight bookkeeping (leader-local, never replicated) ---------
    next_offset: int = 0                      # reservation cursor
    outstanding: dict = field(default_factory=dict)   # offset -> {members in flight}
    buffered: dict = field(default_factory=dict)      # offset -> (preds, elapsed)
    retry_q: list = field(default_factory=list)       # [(offset, excluded members)]
    failed: dict = field(default_factory=dict)        # offset -> {members that failed it}
    dispatch_t: dict = field(default_factory=dict)    # offset -> first-dispatch stamp
    # Shards completed via gang dispatch (one collective SPMD execution
    # across the whole mesh group) this term — the jobs report's evidence
    # that the mesh group is serving collectively.
    gang_shards: int = 0
    # Gang ranks whose shard slice was decode-prefetched before the
    # collective (decode overlapped with the previous shard's execution);
    # at steady state this tracks gang_shards * world.
    gang_staged_ranks: int = 0
    # Consecutive gang failures with no success in between. A config-level
    # incompatibility (e.g. shard slice exceeding the engines' per-process
    # batch cap) fails INSTANTLY on every member, so unbounded whole-gang
    # retry would busy-loop forever; past a small cap the job is stopped
    # with the error surfaced in the report instead.
    gang_consec_failures: int = 0
    # Advisor-planned gang width (docs/SHARDING.md): when >= 2 the job's
    # assigned members are ONE placement unit — a chip gang in sorted-member
    # rank order — and dispatch rides the collective gang path instead of
    # the per-member pool. 0 = solo dispatch. Leader-plan-local (a new
    # leader replans from its own advisor; never replicated).
    gang_world: int = 0
    last_error: str = ""
    # Wall-clock throughput window (leader-local, this term only): first
    # dispatch and latest completion stamps from the scheduler's timer.
    first_dispatch_t: float | None = None
    last_result_t: float | None = None
    finished_at_start: int = 0                # cursor when this term began

    @property
    def done(self) -> bool:
        return self.finished >= len(self.queries)

    def reset_inflight(self) -> None:
        """Drop all in-flight bookkeeping back to the durable cursor (after
        adopting replicated state, or on resume)."""
        self.next_offset = self.finished
        self.outstanding.clear()
        self.buffered.clear()
        self.retry_q.clear()
        self.failed.clear()
        self.dispatch_t.clear()

    @property
    def accuracy(self) -> float:
        return self.correct / self.finished if self.finished else 0.0

    @property
    def throughput_qps(self) -> float:
        """Completed queries/second over this leadership term's dispatch
        window (0.0 before any result). The reference reported only
        latencies (main.rs:282-309); at shard scale the cluster rate is the
        headline number, so it rides the jobs report too."""
        if self.first_dispatch_t is None or self.last_result_t is None:
            return 0.0
        dt = self.last_result_t - self.first_dispatch_t
        done = self.finished - self.finished_at_start
        return done / dt if dt > 0 and done > 0 else 0.0

    def report(self) -> dict:
        return {
            "model": self.model_name,
            "running": self.running,
            "finished": self.finished,
            "total": len(self.queries),
            "correct": self.correct,
            "accuracy": self.accuracy,
            "throughput_qps": self.throughput_qps,
            "assigned": list(self.assigned),
            "gang_shards": self.gang_shards,
            "gang_staged_ranks": self.gang_staged_ranks,
            "gang_world": self.gang_world,
            "last_error": self.last_error,
            "query_latency": self.query_stats.summary(),
            "shard_latency": self.shard_stats.summary(),
            "member_latency": {m: s.summary() for m, s in self.member_stats.items()},
        }

    def to_wire(self) -> dict:
        """Replication payload for standby leaders (services.rs:228-236)."""
        return {
            "model": self.model_name,
            "finished": self.finished,
            "correct": self.correct,
            "running": self.running,
            "query_samples": self.query_stats.to_wire(),
            "shard_samples": self.shard_stats.to_wire(),
            # Breaker diagnostics ride along: a failover must not erase WHY
            # a job was stopped (the surviving leader's report is exactly
            # where the operator will look).
            "gang_shards": self.gang_shards,
            "gang_staged_ranks": self.gang_staged_ranks,
            "last_error": self.last_error,
        }

    def adopt_wire(self, w: dict) -> None:
        self.finished = int(w["finished"])
        self.correct = int(w["correct"])
        self.running = bool(w["running"])
        self.query_stats = LatencyStats.from_wire(w["query_samples"])
        self.shard_stats = LatencyStats.from_wire(w["shard_samples"])
        self.gang_shards = int(w.get("gang_shards", 0))
        self.gang_staged_ranks = int(w.get("gang_staged_ranks", 0))
        self.last_error = str(w.get("last_error", ""))
        self._median_cache = None
        self.reset_inflight()
        # The throughput window is term-local: a new leader measures its own
        # dispatch rate, not wall time since a dead leader's first shard.
        self.first_dispatch_t = None
        self.last_result_t = None
        self.finished_at_start = self.finished


class JobScheduler:
    """The leader's scheduler: owns the jobs, splits members, hands shards.

    ``timer`` is an injected wall-clock callable so the simulator can fake
    latency measurements deterministically.
    """

    def __init__(
        self,
        rpc: Rpc,
        active_members,
        jobs: dict[str, list[tuple[str, int]]],
        shard_size: int = 64,
        timer=None,
        shard_timeout_s: float = 120.0,
        member_weight=None,
        hedge_tail: bool = True,
        mesh_group=None,
        retry_policy=None,
        gray_factor: float = 0.0,
        gray_min_latency_s: float = 0.25,
        gray_probe_interval_s: float = 5.0,
        metrics: Counters | None = None,
        flight=None,
        profiler=None,
        advisor=None,
    ):
        import time

        self.rpc = rpc
        self.active_members = active_members
        self.shard_size = int(shard_size)
        self.timer = timer or time.perf_counter
        self.shard_timeout_s = float(shard_timeout_s)
        # Overload control (docs/OVERLOAD.md): the node-shared retry
        # governor (cluster/retrypolicy.py) — dispatch consults the
        # per-member breaker before every RPC and spends a retry token for
        # every requeued shard re-dispatch, so a dead or drowning member
        # costs bounded probe traffic instead of a retry storm. None (the
        # sim-test default) disables gating entirely.
        self.retry_policy = retry_policy
        # Gray-failure ejection: a member whose EWMA shard latency exceeds
        # gray_factor x the fleet median (and the absolute floor), or whose
        # breaker keeps reopening, is demoted — no new shards, one canary
        # shard per probe interval — and restored when it recovers.
        # Crashes already requeue; this catches slow-but-alive members
        # membership cannot see. 0 disables.
        self.gray_factor = float(gray_factor)
        self.gray_min_latency_s = float(gray_min_latency_s)
        self.gray_probe_interval_s = float(gray_probe_interval_s)
        self.metrics = metrics if metrics is not None else Counters()
        # Flight recorder (cluster/flight.py, optional): demotions,
        # restorations, and gang job stops are the transitions a postmortem
        # reconstructs first.
        self.flight = flight
        # Closed-loop placement (docs/OBSERVABILITY.md §5): the profiler
        # receives every dispatch's measured cost; the advisor turns those
        # profiles into assignment plans consulted by assign_once. Either
        # None keeps the round-robin baseline (the sim tests' default).
        self.profiler = profiler
        self.advisor = advisor
        # Replan trigger: set by gray transitions, membership changes, and
        # the SLO evaluator's fast-burn callback; consumed (and cleared) by
        # the next assignment pass so the advisor knows WHY it ran.
        self._replan_trigger: str | None = None
        self._last_member_set: frozenset = frozenset()
        # member addr -> {"ewma", "demoted", "reason", "last_probe",
        # "opens_mark"} (leader-local; a new leader re-learns the fleet).
        self._health: dict[str, dict] = {}
        self.demoted: set[str] = set()
        # Tail hedging (backup requests): once a job has no fresh shards to
        # reserve, idle dispatchers re-send the oldest still-outstanding
        # shard to a DIFFERENT member instead of sleeping — one straggler
        # can no longer hold the job's completion hostage for its full
        # latency (or the shard timeout). Safe by construction: results
        # dedup by offset, so the slow and the hedge answer count once.
        # A backup fires only after the shard has been in flight longer
        # than hedge_factor x the job's MEDIAN shard latency (and never
        # before any latency has been observed), so healthy tails don't
        # double-compute their last shards.
        self.hedge_tail = bool(hedge_tail)
        self.hedge_factor = 2.0
        # addr -> chip count for ICI-local weighted placement (the north
        # star's "per-host chip topology"); default: every host weight 1
        # (the reference's uniform random pick, services.rs:414-416).
        self.member_weight = member_weight or (lambda addr: 1)
        # Gang scheduling over the global device mesh: a callable returning
        # {member_addr: mesh rank} once the fleet's jax.distributed runtime
        # is fully registered (None before). A job whose assigned members
        # are exactly a registered mesh group dispatches each shard to ALL
        # of them at once — one collective SPMD execution per shard
        # (InferenceEngine.run_batch_global) instead of per-member silos.
        # This is the scheduler DRIVING distributed inference, the
        # reference's whole point (services.rs:407-433) at mesh scale.
        self.mesh_group = mesh_group
        # One gang shard in flight at a time: two concurrent collectives
        # over one mesh would interleave their participants and deadlock.
        self._gang_lock = threading.Lock()
        # Two lazy persistent fan-out pools (not per shard): decode prefetch
        # and collective execution must not share workers — see
        # _ensure_gang_pool.
        self._gang_pool = None
        self._gang_pool_size = 0
        self._gang_exec_pool = None
        self._gang_exec_pool_size = 0
        self._gang_pool_lock = threading.Lock()
        self.gang_max_consec_failures = 8
        self.jobs: dict[str, Job] = {
            name: Job(model_name=name, queries=list(qs)) for name, qs in jobs.items()
        }
        # Set by StandbyLeader on promotion; other candidates read it via
        # leader.status to defer instead of double-leading.
        self.is_leading = False
        # Leadership epoch [counter, claimant] (failover.epoch_key order),
        # set at promotion; candidates compare terms to know who abdicates
        # after a candidate partition heals.
        self.epoch: list = [0, ""]
        # Optional extra leader.status payload supplier (node wires the
        # GenRouter's session/drain summary here) — a plain callable so
        # this module stays ignorant of the generation plane.
        self.extra_status: Callable[[], dict] | None = None
        self._lock = threading.RLock()

    # ---- RPC surface ---------------------------------------------------

    def methods(self) -> dict:
        return traced_methods({
            "job.start": self._start_rpc,
            "job.report": self._report,
            "job.state": self._state,
            "job.assignments": self._assignments,
            "leader.alive": lambda p: {"ok": True},
            "leader.status": lambda p: {
                "leading": self.is_leading,
                "epoch": list(self.epoch),
                "overload": self.overload_status(),
                **({"generate": self.extra_status()}
                   if self.extra_status is not None else {}),
            },
        })

    def overload_status(self) -> dict:
        """The overload-control counters and verdicts this leader holds —
        rides ``leader.status`` so the CLI ``status`` verb (and standbys)
        can show shed/deadline/breaker/demotion state fleet-wide."""
        with self._lock:
            health = {
                m: {"ewma_s": h["ewma"], "demoted": h["demoted"], "reason": h["reason"]}
                for m, h in self._health.items()
                if h["ewma"] is not None or h["demoted"]
            }
            demoted = sorted(self.demoted)
        out: dict = {
            "counters": self.metrics.snapshot(),
            "demoted": demoted,
            "member_health": health,
        }
        if self.retry_policy is not None:
            out["breakers"] = self.retry_policy.snapshot()
        return out

    def _start_rpc(self, p: dict) -> dict:
        """RPC guard: only the active leader accepts `predict` — a deferring
        standby would mark jobs running without ever dispatching them."""
        if not self.is_leading:
            raise RpcError("not the active leader")
        return self._start(p)

    def _start(self, p: dict) -> dict:
        """The `predict` verb: mark every job running (resumes from cursor)."""
        with self._lock:
            for job in self.jobs.values():
                if not job.done:
                    job.running = True
                    # A fresh leadership term resumes from the durable
                    # cursor; in-flight work from a dead term is abandoned
                    # (re-dispatched shards dedup by offset anyway).
                    job.next_offset = max(job.next_offset, job.finished)
                    # Re-arm a job the gang breaker stopped: `predict` is
                    # the operator's explicit retry after fixing the config.
                    job.gang_consec_failures = 0
                    job.last_error = ""
        self.assign_once()
        return {"jobs": sorted(self.jobs)}

    def _report(self, p: dict) -> dict:
        with self._lock:
            return {"jobs": {n: j.report() for n, j in self.jobs.items()}}

    def _state(self, p: dict) -> dict:
        with self._lock:
            return {"jobs": {n: j.to_wire() for n, j in self.jobs.items()}}

    def _assignments(self, p: dict) -> dict:
        with self._lock:
            return {"assigned": {n: list(j.assigned) for n, j in self.jobs.items()}}

    # ---- assignment (services.rs:199-211) ------------------------------

    def assign_once(self) -> None:
        """Split active members evenly across running jobs, round-robin by
        sorted index — the reference's 50/50 split generalized to K jobs.
        Each job's dispatch pool repeats a member by its chip weight,
        interleaved, so shard placement is proportional to capacity.

        With a registered mesh group, every running job is instead assigned
        the WHOLE group: the mesh is one collective serving unit (its
        backends jit over the global mesh and cannot answer per-member
        shards), and jobs share it serially through the gang lock.

        Gray-demoted members are excluded from assignment (the quarantine
        tier: no new shards, canary probes only via next_shard) — unless
        every member is demoted, in which case availability wins and the
        full fleet serves. Gang mode ignores demotion: the collective needs
        every rank."""
        group = self.mesh_group() if self.mesh_group is not None else None
        members = sorted(self.active_members())
        weights = {m: max(1, int(self.member_weight(m))) for m in members}
        with self._lock:
            self._gray_check()
            trigger = self._replan_trigger
            self._replan_trigger = None
            member_set = frozenset(members)
            if member_set != self._last_member_set:
                # Join/leave is a replan trigger in its own right: the
                # advisor must re-solve, budget or not.
                if self._last_member_set:
                    trigger = trigger or "membership"
                self._last_member_set = member_set
            if not group and self.demoted:
                kept = [m for m in members if m not in self.demoted]
                members = kept or members
            running = [n for n, j in self.jobs.items() if j.running and not j.done]
            for name, job in self.jobs.items():
                if name not in running:
                    job.assigned = []
                    job.dispatch_pool = []
                    job.gang_world = 0
            if not running:
                return
            if group:
                for name in running:
                    self.jobs[name].assigned = sorted(group)
                    self.jobs[name].dispatch_pool = []
                    self.jobs[name].gang_world = 0
                return
            if self.advisor is not None and self._assign_from_plan(
                running, members, weights, trigger
            ):
                return
            for i, name in enumerate(running):
                job = self.jobs[name]
                job.gang_world = 0
                job.assigned = [
                    m for k, m in enumerate(members) if k % len(running) == i
                ]
                # Interleave by weight round: [a,b,a,b,a] for weights a=3,b=2.
                pool: list[str] = []
                for r in range(max((weights[m] for m in job.assigned), default=0)):
                    pool.extend(m for m in job.assigned if weights[m] > r)
                job.dispatch_pool = pool

    def _assign_from_plan(
        self, running: list[str], members: list[str],
        weights: dict[str, int], trigger: str | None,
    ) -> bool:
        """Consult the placement advisor (caller holds the lock; the
        advisor is non-blocking and leaf-locked by contract). Applies the
        plan and returns True, or returns False for the round-robin
        fallback when the advisor abstains or the plan is unusable. Every
        applied CHANGE stamps the flight recorder — profile-driven
        placement must never be invisible (lint O2)."""
        plan = self.advisor.advise(
            {n: len(self.jobs[n].queries) - self.jobs[n].finished for n in running},
            members,
            chip_weight=weights,
            trigger=trigger or "periodic",
        )
        if plan is None:
            return False
        member_set = set(members)
        for name in running:
            assigned = plan.assignment.get(name)
            if not assigned or any(m not in member_set for m in assigned):
                return False  # incomplete/stale plan: round-robin this pass
        changed = False
        for name in running:
            job = self.jobs[name]
            assigned = sorted(plan.assignment[name])
            width = int(plan.gangs.get(name, 0))
            if assigned != job.assigned or width != job.gang_world:
                changed = True
            job.assigned = assigned
            job.gang_world = width
            if width:
                # Gang jobs have no dispatch pool: the whole unit takes
                # every shard collectively (rank = sorted-member index).
                job.dispatch_pool = []
                continue
            wmap = plan.weights.get(name) or {}
            w = {m: max(1, int(wmap.get(m, weights.get(m, 1)))) for m in assigned}
            pool: list[str] = []
            for r in range(max(w.values(), default=0)):
                pool.extend(m for m in assigned if w[m] > r)
            job.dispatch_pool = pool
        if changed and self.flight is not None:
            note = dict(
                trigger=trigger or "periodic",
                moves=plan.moves, excluded=",".join(plan.excluded),
            )
            if plan.gangs:
                note["gangs"] = ";".join(
                    f"{j}:{w}" for j, w in sorted(plan.gangs.items())
                )
            self.flight.note("placement_apply", **note)
        return True

    def request_replan(self, reason: str) -> None:
        """Ask the next assignment pass to consult the advisor with an
        explicit trigger (SLO fast-burn, gray transitions, membership).
        Safe from any thread; last reason wins."""
        with self._lock:
            self._replan_trigger = reason

    # ---- gray-failure ejection (docs/OVERLOAD.md) ----------------------

    GRAY_ALPHA = 0.3  # EWMA smoothing for per-member shard latency

    def _observe_member(self, member: str, elapsed: float, failure: bool = False) -> dict:
        """Fold one dispatch's latency into the member's EWMA. Caller holds
        the lock. Success latencies always count; a FAILURE's elapsed time
        counts only when it is evidence of slowness (>= the current EWMA) —
        an instantly-unreachable member must not wash its slow history
        clean (that is the breaker's case, not gray's)."""
        h = self._health.get(member)
        if h is None:
            h = self._health[member] = {
                "ewma": None, "demoted": False, "reason": "",
                "last_probe": 0.0, "opens_mark": 0,
            }
        if failure and (h["ewma"] is None or elapsed < h["ewma"]):
            return h
        if h["ewma"] is None:
            h["ewma"] = float(elapsed)
        else:
            h["ewma"] = (1 - self.GRAY_ALPHA) * h["ewma"] + self.GRAY_ALPHA * elapsed
        return h

    def _demote(self, member: str, reason: str, detail: str) -> None:
        h = self._health[member]
        h["demoted"] = True
        h["reason"] = reason
        h["last_probe"] = self.timer()  # first canary waits one interval
        self.demoted.add(member)
        self.metrics.inc("gray_demotions")
        tracer.record("overload/gray_demote", 0.0, member=member, reason=reason)
        if self.flight is not None:
            self.flight.note("gray_demote", member=member, reason=reason, detail=detail)
        self._replan_trigger = f"gray_demote:{member}"
        log.warning("gray-demoting %s: %s", member, detail)

    def _restore(self, member: str) -> None:
        h = self._health[member]
        h["demoted"] = False
        h["reason"] = ""
        if self.retry_policy is not None:
            h["opens_mark"] = self.retry_policy.open_count(member)
        self.demoted.discard(member)
        self.metrics.inc("gray_restored")
        tracer.record("overload/gray_restore", 0.0, member=member)
        if self.flight is not None:
            self.flight.note("gray_restore", member=member)
        self._replan_trigger = f"gray_restore:{member}"
        log.warning("gray-restoring %s: recovered", member)

    def _gray_check(self) -> None:
        """One demotion/restoration pass (caller holds the lock; runs every
        assignment tick). Latency rule: EWMA > max(gray_factor x fleet
        median, the absolute floor) demotes; recovery below 0.7x that
        threshold restores (hysteresis, so a member hovering at the line
        does not flap). Breaker rule: >= 2 re-opens since the last mark
        demotes; a breaker observed closed again (a half-open canary
        succeeded) restores."""
        if self.gray_factor <= 0:
            return
        if self.retry_policy is not None:
            for m, h in self._health.items():
                opens = self.retry_policy.open_count(m)
                if not h["demoted"] and opens - h["opens_mark"] >= 2:
                    self._demote(m, "breaker", f"breaker re-opened {opens - h['opens_mark']}x")
                elif (
                    h["demoted"]
                    and h["reason"] == "breaker"
                    and self.retry_policy.breaker_state(m) == "closed"
                ):
                    self._restore(m)
        ewmas = {m: h["ewma"] for m, h in self._health.items() if h["ewma"] is not None}
        active = sorted(v for m, v in ewmas.items() if not self._health[m]["demoted"])
        if len(active) < 2:
            return  # no fleet to be an outlier OF
        median = active[len(active) // 2]
        threshold = max(self.gray_factor * median, self.gray_min_latency_s)
        for m, v in ewmas.items():
            h = self._health[m]
            if not h["demoted"] and v > threshold:
                self._demote(m, "slow", f"ewma {v:.3f}s > {threshold:.3f}s "
                                        f"(fleet median {median:.3f}s)")
            elif h["demoted"] and h["reason"] == "slow" and v <= 0.7 * threshold:
                self._restore(m)

    def _gray_probe_candidate(self, excluded: set) -> str | None:
        """A demoted member due for its canary shard, or None. Caller holds
        the lock. The canary is a REAL shard: if the member is still slow
        the shard times out and requeues (exactly-once bookkeeping
        unaffected); if it answers, the latency feeds the EWMA that will
        restore it."""
        if not self.demoted:
            return None
        now = self.timer()
        for m in sorted(self.demoted):
            h = self._health[m]
            if m in excluded or now - h["last_probe"] < self.gray_probe_interval_s:
                continue
            if self.retry_policy is not None and not self.retry_policy.allow(m):
                continue
            h["last_probe"] = now
            return m
        return None

    # ---- dispatch (services.rs:407-433, shard-ized) --------------------

    def _hedgeable_offset(self, job: Job):
        """Oldest outstanding offset eligible for a backup request, or None.
        Eligible: uncompleted, only one copy in flight, and in flight longer
        than hedge_factor x the observed median shard latency (no hedging
        before any latency has been observed — there is no evidence of
        'slow' yet). Caller holds the lock."""
        if not (self.hedge_tail and job.outstanding):
            return None
        if not len(job.shard_stats):
            return None
        if job._median_cache is None:
            job._median_cache = job.shard_stats.percentile(50)
        threshold = self.hedge_factor * job._median_cache
        now = self.timer()
        for o, ms in sorted(job.outstanding.items()):
            if (
                o >= job.finished
                and o not in job.buffered
                and len(ms) < 2
                and now - job.dispatch_t.get(o, now) > threshold
            ):
                return o
        return None

    def next_shard(self, job_name: str):
        """Reserve the next shard (retries first, then fresh work, then —
        with hedge_tail — a backup copy of a slow outstanding shard on a
        different member). Returns (member, offset, queries,
        excluded_members) or None if the job is idle/starved/done. Safe
        under concurrent callers: each reservation hands out a distinct
        offset, and at most 2 copies of an offset are in flight at once."""
        with self._lock:
            job = self.jobs[job_name]
            if not job.running or not job.assigned:
                return None
            excluded: set = set()
            hedge = False
            is_retry = False
            if job.retry_q:
                offset, excluded = job.retry_q.pop(0)
                is_retry = True
            elif job.next_offset < len(job.queries):
                offset = job.next_offset
                job.next_offset += self.shard_size
            else:
                picked = self._hedgeable_offset(job)
                if picked is None:
                    return None
                offset = picked
                # The backup avoids everyone currently running the shard
                # AND everyone who already failed it.
                excluded = set(job.outstanding[offset]) | job.failed.get(offset, set())
                hedge = True
            shard = job.queries[offset : offset + self.shard_size]
            base = job.dispatch_pool or job.assigned
            pool = [m for m in base if m not in excluded]
            if not pool:
                if hedge:
                    return None  # nobody fresh to back it up with
                pool = base
            member = None
            if not hedge:
                # Gray canary FIRST: a demoted member due for its probe takes
                # this shard — the only way quarantined members receive work,
                # and the evidence stream that restores them. Checked before
                # the normal pick so no half-open breaker slot is claimed for
                # a member the canary would then displace (a claimed-but-
                # never-dispatched probe slot wedges that peer shut).
                member = self._gray_probe_candidate(excluded)
            if member is None:
                for _ in range(len(pool)):
                    cand = pool[job._next_member % len(pool)]
                    job._next_member += 1
                    if self._policy_allows(cand, is_retry):
                        member = cand
                        break
            if member is None:
                # Every candidate denied (breaker open / retry budget dry):
                # put the reservation back and let the dispatcher back off —
                # a denied retry fast-fails locally instead of spinning RPCs
                # at a peer that is down or drowning.
                if is_retry:
                    job.retry_q.insert(0, (offset, excluded))
                elif not hedge:
                    job.next_offset = offset
                return None
            job.outstanding.setdefault(offset, set()).add(member)
            job.dispatch_t.setdefault(offset, self.timer())
            return member, offset, shard, excluded

    def _policy_allows(self, member: str, is_retry: bool) -> bool:
        """Breaker gate for every pick; breaker + retry-token for requeued
        work (hedges are already bounded to 2 copies, so they spend no
        tokens). Caller holds the scheduler lock; the policy's own lock is
        a leaf."""
        if self.retry_policy is None:
            return True
        if is_retry:
            return self.retry_policy.allow_retry(member)
        return self.retry_policy.allow(member)

    def _gang_group(self, job: Job):
        """(group, ok): group is {addr: rank} when the global mesh is fully
        registered (else None -> per-member dispatch); ok says this job's
        assignment matches it exactly. While a mesh group is registered,
        per-member dispatch is NEVER a fallback — the mesh's backends jit
        over the global mesh and a solo shard would fail on every member
        (livelock); a mismatched assignment (stale, pre-assign) just waits
        for the next assignment pass."""
        if self.mesh_group is None:
            return None, False
        group = self.mesh_group()
        if not group:
            return None, False
        return dict(group), set(job.assigned) == set(group)

    def _job_gang(self, job: Job):
        """{addr: rank} for an advisor-planned per-job gang (docs/
        SHARDING.md): rank order is sorted-member order, the same order
        ``_assign_from_plan`` stored. None while the job is solo or the
        assignment does not (yet) match the planned width — a torn-down or
        stale gang dispatches NOTHING until the next assignment pass, same
        contract as the registered mesh group. Caller holds the lock."""
        if job.gang_world < 2 or len(job.assigned) != job.gang_world:
            return None
        return {m: i for i, m in enumerate(sorted(job.assigned))}

    def _dispatch_gang(self, job_name: str, group: dict) -> int:
        """One gang shard: reserve an offset, send the SAME shard to every
        mesh process (its rank picks its slice), reassemble rank-ordered
        replies into the shard's predictions, record exactly once. All-or-
        nothing: any member failing fails the shard, which requeues whole —
        there is no partial credit for a collective execution."""
        job = self.jobs[job_name]
        with self._lock:
            if not job.running or not job.assigned:
                return 0
            if job.retry_q:
                offset, _ = job.retry_q.pop(0)
            elif job.next_offset < len(job.queries):
                offset = job.next_offset
                job.next_offset += self.shard_size
            else:
                return 0
            shard = job.queries[offset : offset + self.shard_size]
            job.outstanding.setdefault(offset, set()).update(group)
            job.dispatch_t.setdefault(offset, self.timer())
            if job.first_dispatch_t is None:
                job.first_dispatch_t = self.timer()
        try:
            return self._run_gang_shard(job_name, group, offset, shard)
        except Exception:
            # Safety net: an unexpected failure between reservation and the
            # requeue paths inside _run_gang_shard must not strand the
            # offset in job.outstanding — gang mode has no hedging, so a
            # stranded offset wedges the contiguous cursor forever.
            log.exception("gang shard %s[%d] failed unexpectedly", job_name, offset)
            with self._lock:
                job.outstanding.pop(offset, None)
                job.dispatch_t.pop(offset, None)
                if offset >= job.finished and offset not in job.buffered:
                    job.retry_q.append((offset, set()))
            return 0

    # Phase-1 decode prefetch is an optimization: bound how long it may
    # delay the collective (and how long a hung member can occupy a pool
    # worker) far below shard_timeout_s — a late stage is simply unused
    # and the member decodes inline.
    DECODE_PREFETCH_TIMEOUT_S = 30.0

    def _ensure_gang_pool(self, world: int):
        """Fan-out pools under their own lock so pool management never
        contends with the gang serialization. Returns ``(decode_pool,
        exec_pool)`` — SEPARATE executors, because mixing them lets phase-1
        decode tasks (up to DECODE_PREFETCH_TIMEOUT_S each, several
        dispatcher threads deep) queue ahead of the serialized collective's
        futures and stretch the gang critical path. The exec pool only ever
        carries one shard's collective (submits happen under _gang_lock), so
        ``world`` workers never queue; the decode pool is 2x world for two
        dispatchers prefetching at once.
        A replaced (grown) pool is NOT shut down: another dispatcher thread
        may hold the old reference between _ensure_gang_pool and submit,
        and submit-after-shutdown raises. The abandoned pool's idle workers
        are reclaimed by concurrent.futures' interpreter-exit join; mesh
        growth is rare enough that the leak is a few sleeping threads."""
        import concurrent.futures

        with self._gang_pool_lock:
            need = max(2 * world, 8)
            if self._gang_pool is None or self._gang_pool_size < need:
                self._gang_pool_size = need
                self._gang_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=need, thread_name_prefix="gang-decode"
                )
            need_exec = max(world, 4)
            if self._gang_exec_pool is None or self._gang_exec_pool_size < need_exec:
                self._gang_exec_pool_size = need_exec
                self._gang_exec_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=need_exec, thread_name_prefix="gang-exec"
                )
            return self._gang_pool, self._gang_exec_pool

    def _run_gang_shard(self, job_name: str, group: dict, offset: int, shard) -> int:
        job = self.jobs[job_name]
        synsets = [s for s, _ in shard]
        world = len(group)
        t0 = self.timer()

        def call_one(addr: str, rank: int):
            with tracer.span(
                "scheduler/dispatch_gang", job=job_name, member=addr, rank=rank, n=len(shard)
            ):
                return self.rpc.call(
                    addr,
                    "job.predict_gang",
                    {"model": job.model_name, "synsets": synsets, "rank": rank, "world": world},
                    timeout=self.shard_timeout_s,
                )

        def decode_one(addr: str, rank: int) -> bool:
            try:
                r = self.rpc.call(
                    addr,
                    "job.decode_gang",
                    {"model": job.model_name, "synsets": synsets, "rank": rank, "world": world},
                    timeout=self.DECODE_PREFETCH_TIMEOUT_S,
                )
                return bool(r.get("staged"))
            except Exception:
                return False  # best-effort: the member will decode inline

        pool, exec_pool = self._ensure_gang_pool(world)

        # Phase 1 — prefetch decode on every member, OUTSIDE the gang lock:
        # while the previous gang shard's collective executes (holding
        # _gang_lock from another dispatcher thread), this shard's slices
        # decode host-side on every member, so mesh serving pipelines decode
        # against execution instead of paying decode+execute serially per
        # shard (VERDICT r3 weak #5).
        staged = 0
        decode_futs = [
            pool.submit(decode_one, addr, rank)
            for addr, rank in sorted(group.items(), key=lambda kv: kv[1])
        ]
        # Bounded wait across ALL decode futures: a hung member must not
        # extend the failure-detection critical path (the collective's own
        # shard_timeout_s is the real detector) — a straggler's stage is
        # abandoned and that member decodes inline.
        decode_deadline = monotonic() + self.DECODE_PREFETCH_TIMEOUT_S
        for fut in decode_futs:
            try:
                staged += bool(
                    fut.result(timeout=max(0.0, decode_deadline - monotonic()))
                )
            except Exception:  # dmlc-lint: disable=E1 -- prefetch is best-effort by contract: a timed-out/failed stage means that member decodes inline, which the collective path handles
                pass
        with self._lock:
            job.gang_staged_ranks += staged

        # Phase 2 — serialize gangs: concurrent collectives over one mesh
        # deadlock.
        with self._gang_lock:
            futures = {
                rank: exec_pool.submit(call_one, addr, rank)
                for addr, rank in sorted(group.items(), key=lambda kv: kv[1])
            }
            by_rank: dict[int, list] = {}
            errors: list[str] = []
            method_error = False
            lost_members = False
            for rank, fut in futures.items():
                try:
                    # dmlc-lint: disable=L1 -- _gang_lock exists precisely to hold across this wait: two concurrent collectives over one mesh interleave participants and deadlock
                    by_rank[rank] = list(fut.result()["predictions"])
                except RpcUnreachable as e:
                    lost_members = True
                    errors.append(f"rank {rank}: {e}")
                except Exception as e:
                    # The member EXECUTED and refused (rank mismatch,
                    # batch not divisible, slice > engine cap, ...).
                    method_error = True
                    errors.append(f"rank {rank}: {e}")

        def requeue(why: str, breaker: bool, teardown: bool = False) -> int:
            log.warning("gang shard %s[%d] requeued: %s", job_name, offset, why)
            with self._lock:
                job.outstanding.pop(offset, None)
                job.dispatch_t.pop(offset, None)
                if offset >= job.finished and offset not in job.buffered:
                    # Whole-gang retry: no member exclusion — the collective
                    # needs every process, so exclusions are meaningless.
                    job.retry_q.append((offset, set()))
                if teardown and job.gang_world:
                    # An advisor-planned gang lost a member: the unit is
                    # all-or-nothing, so RELEASE the whole gang (no further
                    # dispatch until reassigned) and force a replan — the
                    # advisor's cached plan is stale the moment a gang
                    # member dies, so hysteresis/budget cannot veto it.
                    released = list(job.assigned)
                    job.assigned = []
                    job.dispatch_pool = []
                    self._replan_trigger = (
                        self._replan_trigger or f"gang_member_lost:{job_name}"
                    )
                    if self.flight is not None:
                        self.flight.note(
                            "gang_teardown", job=job_name,
                            world=job.gang_world,
                            released=",".join(released), why=why[:200],
                        )
                if breaker:
                    # Method-level refusals only: a config incompatibility
                    # (slice > engine batch cap, batch not divisible by
                    # processes, rank mismatch, ...) fails identically every
                    # retry, so past the cap the job stops with the error
                    # surfaced instead of hot-spinning RPCs. Unreachability
                    # is weather (member restarting) and retries forever —
                    # the shard timeout already bounds each attempt.
                    job.gang_consec_failures += 1
                    if job.gang_consec_failures >= self.gang_max_consec_failures:
                        job.running = False
                        job.last_error = f"gang dispatch failing repeatedly: {why}"
                        if self.flight is not None:
                            self.flight.note(
                                "job_stopped", job=job_name, error=job.last_error
                            )
                        log.error("stopping job %s: %s", job_name, job.last_error)
            return 0

        if errors:
            return requeue(
                "; ".join(errors), breaker=method_error, teardown=lost_members
            )
        preds: list = []
        for rank in sorted(by_rank):
            want = gang_slice(len(synsets), rank, world)
            got = by_rank[rank]
            if len(got) != want[1] - want[0]:
                return requeue(
                    f"rank {rank} returned {len(got)} preds for slice {want}",
                    breaker=True,
                )
            preds.extend(got)
        elapsed = self.timer() - t0
        done = self._record_result(job, offset, shard, preds, elapsed)
        with self._lock:
            job.gang_consec_failures = 0
            if done:
                job.gang_shards += 1
        return done

    def dispatch_once(self, job_name: str) -> int:
        """Send one shard, record its result. Returns the #queries this call
        COMPLETED (0 on failure or duplicate) — an out-of-order success
        buffers its result and still counts as completed work; the contiguous
        ``finished`` cursor advances only when the gap fills. Failures
        requeue the shard with the member excluded — nothing is ever lost or
        double-counted. A job whose assigned members form the registered
        mesh group gang-dispatches instead (one collective execution per
        shard across ALL of them)."""
        with self._lock:
            job = self.jobs.get(job_name)
            group, ok = self._gang_group(job) if job is not None else (None, False)
            job_gang = (
                self._job_gang(job)
                if job is not None and group is None and job.gang_world
                else None
            )
        if group is not None:
            if not ok:
                return 0  # mesh registered, assignment stale: next assign pass
            return self._dispatch_gang(job_name, group)
        if job is not None and group is None and job.gang_world:
            # Advisor-planned gang: the collective path or nothing — a solo
            # shard would land a model that does not FIT one member.
            if job_gang is None:
                return 0  # torn down / stale: wait for the next assign pass
            return self._dispatch_gang(job_name, job_gang)
        picked = self.next_shard(job_name)
        if picked is None:
            return 0
        member, offset, shard, excluded = picked
        job = self.jobs[job_name]
        synsets = [s for s, _ in shard]
        t0 = self.timer()
        with self._lock:
            if job.first_dispatch_t is None:
                job.first_dispatch_t = t0
        try:
            with tracer.span("scheduler/dispatch", job=job_name, member=member, n=len(shard)):
                reply = self.rpc.call(
                    member,
                    "job.predict",
                    {"model": job.model_name, "synsets": synsets},
                    # One shard is one batched forward: seconds. A bounded
                    # timeout keeps a wedged member from stalling the
                    # dispatcher for the reference's 1 h deadline
                    # (main.rs:132); on expiry the shard retries on the
                    # next assigned member.
                    timeout=self.shard_timeout_s,
                )
            preds = list(reply["predictions"])
            if len(preds) != len(shard):
                raise RpcError(f"{len(preds)} predictions for {len(shard)} queries")
        except (RpcUnreachable, RpcError) as e:
            if self.retry_policy is not None:
                self.retry_policy.record(member, e)
            if isinstance(e, DeadlineExceeded):
                self.metrics.inc("deadline_exceeded")
                if self.profiler is not None:
                    # A timed-out shard IS cost evidence: the member burned
                    # at least the full budget. Without this, a member slow
                    # enough to blow every deadline never accrues a profile
                    # and placement cannot act on it.
                    self.profiler.record(
                        job.model_name, member, "dispatch",
                        self.timer() - t0, count=len(shard),
                    )
            elif isinstance(e, Overloaded):
                self.metrics.inc("shed_observed")
            with self._lock:
                # A timeout/deadline failure IS slowness evidence for gray
                # ejection (fast unreachable errors are filtered inside).
                self._observe_member(member, self.timer() - t0, failure=True)
            log.warning("shard dispatch %s[%d] -> %s failed: %s", job_name, offset, member, e)
            self._record_failure(job, offset, member, excluded)
            return 0
        if self.retry_policy is not None:
            self.retry_policy.record(member)
        elapsed = self.timer() - t0
        return self._record_result(job, offset, shard, preds, elapsed, member)

    def _record_failure(self, job: Job, offset: int, member: str, excluded: set) -> None:
        """One in-flight copy failed: drop just that member's tracking,
        remember it (and only it — prior failures are already in the
        history) in the shard's failure record, and requeue only when NO
        copy is still in flight (a live hedge or original may yet answer)
        and nothing has landed."""
        with self._lock:
            inflight = job.outstanding.get(offset)
            if inflight is not None:
                inflight.discard(member)
                if not inflight:
                    job.outstanding.pop(offset, None)
                    job.dispatch_t.pop(offset, None)
            if offset < job.finished or offset in job.buffered:
                return  # a losing copy failing AFTER the offset completed
            job.failed.setdefault(offset, set()).add(member)
            if offset not in job.outstanding:
                job.retry_q.append((offset, excluded | job.failed[offset]))

    def _record_result(
        self, job: Job, offset: int, shard, preds, elapsed: float, member: str | None = None
    ) -> int:
        """Buffer one shard result; flush the contiguous prefix. Returns
        #queries completed by this call (len(shard), or 0 for a duplicate)."""
        with self._lock:
            job.outstanding.pop(offset, None)
            job.failed.pop(offset, None)
            job.dispatch_t.pop(offset, None)
            if offset < job.finished or offset in job.buffered:
                return 0  # duplicate (shard raced to two members)
            job.last_result_t = self.timer()
            if member is not None:
                job.member_stats.setdefault(member, LatencyStats()).record(elapsed)
                self._observe_member(member, elapsed)
                if self.profiler is not None:
                    # The live cost lane placement runs on: one shard's
                    # leader-measured dispatch RTT, amortized over its
                    # queries (profiler lock is a leaf; safe held here).
                    self.profiler.record(
                        job.model_name, member, "dispatch", elapsed,
                        count=len(shard),
                    )
            job.buffered[offset] = (preds, elapsed)
            while job.finished in job.buffered:
                p, dt = job.buffered.pop(job.finished)
                s = job.queries[job.finished : job.finished + len(p)]
                job.finished += len(s)
                job.correct += sum(1 for (_, truth), pred in zip(s, p) if int(pred) == truth)
                job.shard_stats.record(dt)
                job._median_cache = None
                job.query_stats.record_many(dt / max(1, len(s)), len(s))
            if job.done:
                job.running = False
                job.reset_inflight()
            return len(shard)

    def dispatch_all_once(self) -> int:
        """One pass over every running job. Returns total queries completed."""
        return sum(self.dispatch_once(name) for name in sorted(self.jobs))

    def has_dispatchable(self) -> bool:
        """Any job with reservable work right now? (Cheap idle check for
        dispatcher threads.) Gang-mode jobs count only when their assignment
        matches the registered mesh group — a stale assignment dispatches
        nothing until the next assign pass, and hedging is unreachable on
        the gang path — so dispatcher threads sleep instead of busy-spinning
        through no-op polls (ADVICE r3)."""
        with self._lock:
            # The mesh group is job-independent: resolve the callback once
            # per poll, not once per job (this runs on the dispatcher idle
            # path every tick).
            group = self.mesh_group() if self.mesh_group is not None else None
            gang = set(group) if group else None
            for j in self.jobs.values():
                if not (j.running and j.assigned):
                    continue
                if gang is not None:
                    if set(j.assigned) == gang and (
                        j.retry_q or j.next_offset < len(j.queries)
                    ):
                        return True
                    continue
                if j.gang_world:
                    # Advisor gang: same no-hedging contract as the mesh
                    # group; a torn-down gang has nothing dispatchable.
                    if len(j.assigned) == j.gang_world and (
                        j.retry_q or j.next_offset < len(j.queries)
                    ):
                        return True
                    continue
                if (
                    j.retry_q
                    or j.next_offset < len(j.queries)
                    or self._hedgeable_offset(j) is not None
                ):
                    return True
            return False

    def run_to_completion(self, max_rounds: int = 100_000) -> None:
        """Drive all running jobs until done (used by tests and the CLI's
        synchronous mode; the node runs dispatch loops in threads)."""
        for _ in range(max_rounds):
            self.assign_once()
            if self.dispatch_all_once() == 0:
                if all(not j.running or j.done for j in self.jobs.values()):
                    return

    # ---- standby replication -------------------------------------------

    def adopt_state(self, wire: dict) -> None:
        """Copy job progress from the current leader (standby loop,
        services.rs:212-240). Never moves a cursor backwards — a stale
        snapshot must not rewind completed work."""
        with self._lock:
            for name, w in wire["jobs"].items():
                job = self.jobs.get(name)
                if job is not None and int(w["finished"]) >= job.finished:
                    job.adopt_wire(w)

    def has_history(self) -> bool:
        with self._lock:
            return any(j.finished > 0 or j.running for j in self.jobs.values())
