"""Dataset distribution through SDFS: corpus shards staged member-to-member.

BASELINE.json's distributed config is "AlexNet ImageNet-1k distributed
inference, 4-node SDFS shard", and the north star stages batches "from the
SDFS get path straight into HBM". The reference sidesteps this by requiring
the full fixture corpus pre-installed on every VM (src/services.rs:485-490);
here the corpus is *published once* into the replicated store and members
pull exactly the class images their shards need, caching them on local disk:

- ``publish_corpus`` — one SDFS file per class image (``data/<synset>``),
  placed rf-ways by the leader like any other file.
- ``SdfsImageSource`` — member-side resolver: local cache hit, else a
  replica pull through the ordinary SDFS ``get`` path, then disk cache. An
  EngineBackend wired with one serves shards on a node with NO local
  corpus; the decode/stream pipeline lifts the cached files host->HBM.
"""

from __future__ import annotations

import logging
import threading
from pathlib import Path

from dmlc_tpu.ops import preprocess as pp

log = logging.getLogger(__name__)


def sdfs_image_name(synset: str) -> str:
    return f"data/{synset}"


def publish_corpus(sdfs_client, data_dir: str | Path, synsets=None) -> int:
    """Put each class's fixture image into SDFS (the reference serves the
    first image per class dir, services.rs:485-490). Returns #published.
    ``synsets`` limits/orders the classes; default = every subdirectory."""
    data_dir = Path(data_dir)
    if synsets is None:
        synsets = sorted(d.name for d in data_dir.iterdir() if d.is_dir())
    n = 0
    for synset in synsets:
        path = pp.class_image_path(data_dir, synset)
        sdfs_client.put_bytes(path.read_bytes(), sdfs_image_name(synset))
        n += 1
    log.info("published %d class images into SDFS", n)
    return n


class SdfsImageSource:
    """Resolve synset ids to LOCAL image paths, pulling misses from SDFS.

    Drop-in for the data_dir lookup in EngineBackend: callable mapping a
    synset list to paths. Pulled bytes are cached under ``cache_dir`` so
    each class image crosses the network once per node, not once per shard.
    """

    def __init__(self, sdfs_client, cache_dir: str | Path):
        self.sdfs = sdfs_client
        self.cache_dir = Path(cache_dir)
        self.cache_dir.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    def path_for(self, synset: str) -> Path:
        local = self.cache_dir / f"{synset}.img"
        if local.exists():
            return local
        with self._lock:
            if local.exists():  # raced another shard for the same class
                return local
            # dmlc-lint: disable=L1 -- cache-fill lock: the pull IS the critical section (one network fetch per class image; racing shards for the same class must wait for the bytes, not re-pull)
            _, data = self.sdfs.get_bytes(sdfs_image_name(synset))
            tmp = local.with_suffix(".tmp")
            tmp.write_bytes(data)
            tmp.rename(local)  # atomic: readers never see torn bytes
        return local

    def __call__(self, synsets) -> list[Path]:
        return [self.path_for(s) for s in synsets]
