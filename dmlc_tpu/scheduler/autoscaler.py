"""Burn-rate-driven elastic actuator: grow what burns, shrink what's idle.

The fleet already *measures* everything the scaling decision needs — the
multiwindow SLO burn rate (scheduler/placement.SloEvaluator), per-lane
dispatch cost (cluster/profile.CostProfiler), and device-plane HBM
occupancy (cluster/devicemon) — but until now a human read those dashboards
and turned the knobs. This module closes the loop. It is deliberately
sans-IO (lint D1): no threads, no clocks of its own beyond the injected
timebase, no RPC. The leader's observability loop calls ``tick`` right
after ``SloEvaluator.evaluate`` with the set of burning lanes, and the
autoscaler actuates registered :class:`ScaleTarget` seams:

- decode-tier fan-out (cluster/decodetier.DecodeTierClient.set_fanout),
- generate slot-table width and page-pool budget
  (generate/slots.SlotScheduler.set_limits),
- per-model replica targets, gangs included
  (scheduler/placement.PlacementAdvisor.set_replica_target).

Control discipline mirrors the PlacementAdvisor's (docs/OPERATIONS.md):

- **Scale up on the burn edge.** A fast-burn lane grows every target whose
  model matches, multiplicatively (x1.5, at least +1) — a 10x flash crowd
  reaches any reachable capacity within a few fast-burn windows instead of
  creeping one unit per tick.
- **Scale down only after quiet.** ``clear_windows`` consecutive clear
  ticks are required before shrinking, and the shrink is a single step —
  asymmetric hysteresis, because a premature shrink re-triggers the burn
  it just cleared (the classic autoscaler flap).
- **Moves budget.** At most ``moves_budget`` actuations per tick; the rest
  wait for the next evaluation.
- **HBM guard.** A memory-bound target never grows while the fleet's worst
  device is above ``hbm_ceiling`` occupancy — growing the slot table on a
  full HBM converts an SLO problem into an OOM.

Every decision — up, down, and the *refusals* (budget spent, HBM guard) —
is flight-recorded with its trigger and the signal values that justified
it (lint O2: this module reads profiles and steers the fleet, so its
reasoning must be reconstructible from the recorder), and kept in a ring
the CLI renders (``dmlc status`` / ``dmlc tenants``).
"""

from __future__ import annotations

from time import monotonic
from typing import Any, Callable, Iterable, Mapping

__all__ = ["Autoscaler", "ScaleTarget"]


class ScaleTarget:
    """One elastic knob: a name, a reader, an actuator, and bounds.

    ``get`` returns the current setting; ``apply`` sets a new one and
    returns what actually took effect (seams clamp — the decision record
    stores the effective value, not the wish). ``models`` restricts which
    burning lanes drive this target (None = any burn in the fleet);
    lanes are matched on their model part, so the per-tenant composite
    ``llm-7b@acme`` drives a target registered for ``llm-7b``.
    ``memory_bound`` targets answer to the HBM guard on the way up.

    ``drain`` gates the way DOWN (ISSUE 19, scale-down-through-drain): a
    shrink that would abandon live work — generation slots mid-decode, a
    member holding resident sessions — first asks ``drain(proposed)``.
    True means the capacity is already clear and the shrink applies; False
    means a drain was *initiated* (sessions finishing or migrating) and
    the shrink holds, visibly, until a later quiet tick finds it clear.
    """

    def __init__(
        self,
        name: str,
        *,
        get: Callable[[], int],
        apply: Callable[[int], int],
        lo: int = 1,
        hi: int = 64,
        models: Iterable[str] | None = None,
        memory_bound: bool = False,
        drain: Callable[[int], bool] | None = None,
    ) -> None:
        self.name = name
        self.get = get
        self.apply = apply
        self.lo = int(lo)
        self.hi = int(hi)
        self.models = frozenset(models) if models is not None else None
        self.memory_bound = bool(memory_bound)
        self.drain = drain

    def matches(self, burning_models: set[str]) -> bool:
        if self.models is None:
            return bool(burning_models)
        return bool(self.models & burning_models)


class Autoscaler:
    """Sans-IO scaling brain: feed it burn verdicts, it turns knobs."""

    GROWTH = 1.5  # multiplicative scale-up factor (at least +1 per move)

    def __init__(
        self,
        *,
        flight: Any = None,
        metrics: Any = None,
        clock: Callable[[], float] = monotonic,
        clear_windows: int = 3,
        moves_budget: int = 2,
        hbm_ceiling: float = 0.9,
        hbm_used: Callable[[], float | None] | None = None,
        history: int = 64,
    ) -> None:
        self.flight = flight
        self.metrics = metrics
        self.clock = clock
        self.clear_windows = max(1, int(clear_windows))
        self.moves_budget = max(1, int(moves_budget))
        self.hbm_ceiling = float(hbm_ceiling)
        # Worst-device HBM occupancy fraction (devicemon scrape), None when
        # the device plane is dark — unknown never blocks, mirroring the
        # PlacementAdvisor's headroom stance.
        self.hbm_used = hbm_used
        self.history = max(1, int(history))
        self.targets: list[ScaleTarget] = []
        self._clear_streak: dict[str, int] = {}
        self._seq = 0
        self.decisions: list[dict[str, Any]] = []
        self.ticks = 0

    def register(self, target: ScaleTarget) -> ScaleTarget:
        self.targets.append(target)
        self._clear_streak[target.name] = 0
        return target

    # ---- decision engine -------------------------------------------------

    def _record(self, **fields: Any) -> dict[str, Any]:
        self._seq += 1
        decision = {"seq": self._seq, "t": round(self.clock(), 3), **fields}
        self.decisions.append(decision)
        del self.decisions[: -self.history]
        if self.flight is not None:
            self.flight.note("autoscale_decision", **{
                k: v for k, v in decision.items() if v is not None
            })
        if self.metrics is not None:
            self.metrics.inc(f"autoscale_{fields.get('direction', 'hold')}")
        return decision

    def _grow(self, cur: int, hi: int) -> int:
        return min(hi, max(cur + 1, int(cur * self.GROWTH)))

    def tick(
        self,
        burning: Iterable[str],
        burn_values: Mapping[str, float] | None = None,
    ) -> list[dict[str, Any]]:
        """One control step. ``burning`` is SloEvaluator.burning_models()
        output — lanes, including per-tenant composites ``model@tenant``.
        Returns the decisions made this tick (also flight-recorded and
        kept in ``self.decisions`` for the status plane)."""
        self.ticks += 1
        lanes = sorted(set(burning))
        burning_models = {lane.split("@", 1)[0] for lane in lanes}
        burn_values = burn_values or {}
        try:
            hbm = self.hbm_used() if self.hbm_used is not None else None
        except Exception:  # noqa: BLE001 - telemetry read; treat as unknown
            hbm = None
        moves = 0
        out: list[dict[str, Any]] = []
        for target in self.targets:
            cur = int(target.get())
            if target.matches(burning_models):
                self._clear_streak[target.name] = 0
                trigger_lane = next(
                    (ln for ln in lanes
                     if target.models is None
                     or ln.split("@", 1)[0] in target.models),
                    lanes[0] if lanes else "",
                )
                trigger = f"slo_fast_burn:{trigger_lane}"
                burn = burn_values.get(trigger_lane)
                if cur >= target.hi:
                    continue  # already at ceiling: nothing to decide
                if moves >= self.moves_budget:
                    out.append(self._record(
                        target=target.name, direction="hold", at=cur,
                        trigger=trigger, reason="moves_budget",
                        burn=burn,
                    ))
                    continue
                if (target.memory_bound and hbm is not None
                        and hbm > self.hbm_ceiling):
                    # Growing a memory-holding knob on a full device trades
                    # an SLO breach for an OOM; refuse, visibly.
                    out.append(self._record(
                        target=target.name, direction="hold", at=cur,
                        trigger=trigger, reason="hbm_guard",
                        hbm_used=round(hbm, 3), burn=burn,
                    ))
                    continue
                effective = int(target.apply(self._grow(cur, target.hi)))
                moves += 1
                out.append(self._record(
                    target=target.name, direction="up",
                    from_=cur, to=effective, trigger=trigger, burn=burn,
                    hbm_used=None if hbm is None else round(hbm, 3),
                ))
            else:
                streak = self._clear_streak[target.name] = (
                    self._clear_streak[target.name] + 1
                )
                if cur <= target.lo or streak < self.clear_windows:
                    continue
                if moves >= self.moves_budget:
                    continue  # quiet shrink can always wait a tick
                proposed = max(target.lo, cur - 1)
                if target.drain is not None and not target.drain(proposed):
                    # Scale-down goes through drain, never through
                    # abandonment: the seam started draining the excess
                    # capacity; the shrink lands once it reports clear.
                    out.append(self._record(
                        target=target.name, direction="hold", at=cur,
                        trigger=f"slo_clear:{streak}w", reason="draining",
                    ))
                    continue
                effective = int(target.apply(proposed))
                moves += 1
                out.append(self._record(
                    target=target.name, direction="down",
                    from_=cur, to=effective,
                    trigger=f"slo_clear:{streak}w",
                ))
        return out

    # ---- status plane ----------------------------------------------------

    def status(self) -> dict[str, Any]:
        """CLI/status shape: per-target setting + clear streak, the last
        decision, and the recent decision ring."""
        return {
            "ticks": self.ticks,
            "clear_windows": self.clear_windows,
            "moves_budget": self.moves_budget,
            "hbm_ceiling": self.hbm_ceiling,
            "targets": {
                t.name: {
                    "current": int(t.get()),
                    "lo": t.lo,
                    "hi": t.hi,
                    "clear_streak": self._clear_streak.get(t.name, 0),
                    "memory_bound": t.memory_bound,
                }
                for t in self.targets
            },
            "last_decision": self.decisions[-1] if self.decisions else None,
            "decisions": list(self.decisions[-8:]),
        }
