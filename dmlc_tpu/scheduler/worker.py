"""Member-side inference worker: answers ``job.predict`` shards.

Capability parity with the reference's member predict path
(src/services.rs:475-497): given a model name and a list of synset ids, look
up one fixture image per synset, preprocess, forward, return top-1 — except
the unit here is a shard (one batched XLA execution for the whole list), not
one image under a model mutex.

The model backend is injectable: the real node wires ``EngineBackend``
(InferenceEngine on the TPU mesh, models loaded eagerly at startup like
services.rs:513-524); hermetic cluster tests wire a fake backend so scheduler
logic is testable with no JAX at all.
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Callable, Protocol, Sequence

from dmlc_tpu.cluster import tenant as tenant_mod
from dmlc_tpu.cluster.rpc import DecodeError, Overloaded, RpcError
from dmlc_tpu.utils.hotpath import hot_path
from dmlc_tpu.utils.metrics import LatencyStats
from dmlc_tpu.utils.tracing import traced_methods, tracer

log = logging.getLogger(__name__)

# (synset_ids) -> list of predicted class indices
PredictFn = Callable[[Sequence[str]], list[int]]


class DynamicBatcher:
    """Dynamic micro-batcher: coalesce concurrent small classify requests
    into device-shaped batches.

    The engine's unit of work is a ``batch_size`` XLA execution; an RPC
    carrying one (or a few) synsets would otherwise pay a whole padded
    device dispatch for itself. This wrapper queues incoming requests and a
    background worker drains them in batches: a batch dispatches the moment
    ``batch_size`` items are queued, or when the OLDEST queued item has
    waited ``max_wait_s`` — so under load N single-image requests ride
    ceil(N / batch_size) device dispatches, while a lone request is delayed
    at most the deadline. Results map back to their callers by queue order
    (the wrapped ``predict`` returns predictions in argument order).

    Wraps any PredictFn-shaped backend: ``__call__`` is the batched predict
    surface, and every other attribute (``warmup``, ``load_variables``,
    ``predict_gang``, ...) passes through to the wrapped backend — gang
    shards are collective SPMD executions whose slicing must not be
    reordered, so they deliberately bypass the batcher.

    Overload control (docs/OVERLOAD.md): with ``max_queue > 0`` the queue is
    BOUNDED — a submit against a full queue is shed immediately with a typed
    ``Overloaded`` (retry-after = the batch deadline) instead of buffering
    toward a guaranteed timeout. And the batch deadline *brownouts*: as the
    queue fills, the coalescing wait shrinks linearly to zero — waiting
    optimizes latency the batcher no longer has, so under pressure it
    degrades to dispatch-as-fast-as-the-device-drains.

    Multi-tenant quotas (docs/OVERLOAD.md §Priority classes): with a
    tenant table, each queued item is charged to its ambient tenant
    (cluster/tenant.py) against share x max_queue. A tenant at quota
    sheds typed (``quota="over_quota"``); a *full* queue first tries to
    displace a queued low-priority-and-over-quota item in favor of a
    high-priority within-quota submit — brownout ordering is
    low-priority-and-over-quota first, never cross-tenant eviction of
    within-quota work.
    """

    def __init__(
        self,
        predict: PredictFn,
        batch_size: int,
        max_wait_s: float = 0.005,
        name: str = "microbatch",
        max_queue: int = 0,
        metrics=None,
        flight=None,
        tenants=None,
    ):
        # _predict is set FIRST: __getattr__ delegates to it, and any
        # attribute probe before it exists would recurse.
        self._predict = predict
        self.flight = flight
        self.batch_size = int(batch_size)
        self.max_wait_s = float(max_wait_s)
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        # Bounded admission: 0 = unbounded (the pre-overload behavior). A
        # bound below one device batch would shed work the very next
        # dispatch could carry, so the floor is 2 full batches.
        self.max_queue = max(2 * self.batch_size, int(max_queue)) if max_queue > 0 else 0
        self.metrics = metrics
        # One Condition owns all batcher state; its internal lock is only
        # ever held for list surgery — the device dispatch runs outside it.
        self._cv = threading.Condition()
        self._queue: list[tuple[str, concurrent.futures.Future, str]] = []
        self._closed = False
        # Per-tenant queue-token quotas (cluster/tenant.py): enforced only
        # when the queue is bounded — an unbounded queue has no capacity to
        # derive shares from (the pre-overload legacy configuration).
        self.ledger = tenant_mod.TenantLedger(
            tenants if self.max_queue > 0 else None, self.max_queue
        )
        self.requests = 0    # items ever submitted
        self.dispatches = 0  # device-shaped batches sent to the backend
        self.sheds = 0       # submits refused at the bounded queue
        self.queue_hw = 0    # queue-depth high-water
        self.fill = LatencyStats()  # per-dispatch batch fill fraction
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    # ---- request side ---------------------------------------------------

    def _count_shed(self, tenant: str, verdict: str) -> None:
        self.sheds += 1
        self.ledger.note_shed(tenant)
        if self.metrics is not None:
            self.metrics.inc("shed")
            self.metrics.inc("shed_microbatch")
            if verdict == "over_quota":
                self.metrics.inc("shed_over_quota_microbatch")
        if self.flight is not None:
            self.flight.note("shed", gate=self._thread.name,
                             active=len(self._queue), tenant=tenant,
                             quota=verdict)

    def _displace_over_quota(self) -> bool:
        """Brownout ordering under a full queue: shed the NEWEST queued
        item whose tenant is low-priority and over quota, freeing its slot
        for a high-priority within-quota submit. Called under the cv.
        Returns False when every queued item is within-quota or
        high-priority (those are never displaced across tenants)."""
        for i in range(len(self._queue) - 1, -1, -1):
            _, vfut, vtenant = self._queue[i]
            if self.ledger.over_quota(vtenant) and \
                    not self.ledger.spec(vtenant).high_priority:
                del self._queue[i]
                self.ledger.release(vtenant)
                self._count_shed(vtenant, "over_quota")
                vfut.set_exception(Overloaded(
                    f"microbatch: displaced by higher-priority work "
                    f"(tenant {vtenant!r} over quota)",
                    retry_after_s=self.max_wait_s,
                    tenant=vtenant, quota="over_quota",
                ))
                return True
        return False

    def submit(self, synset: str) -> "concurrent.futures.Future":
        """Queue one classify request; the future resolves to its predicted
        class index once the batch it rides in completes. Sheds with a
        typed ``Overloaded`` (carrying the tenant + quota verdict) when the
        bounded queue — or the calling tenant's quota — is full."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        tenant = tenant_mod.current()
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is stopped")
            if self.ledger.would_exceed(tenant):
                self._count_shed(tenant, "over_quota")
                raise Overloaded(
                    f"microbatch: tenant {tenant!r} at quota "
                    f"({self.ledger.active(tenant)}/{self.ledger.quota(tenant)})",
                    retry_after_s=self.max_wait_s,
                    tenant=tenant, quota="over_quota",
                )
            if self.max_queue > 0 and len(self._queue) >= self.max_queue:
                displaced = (
                    self.ledger.spec(tenant).high_priority
                    and self._displace_over_quota()
                )
                if not displaced:
                    self._count_shed(tenant, "gate_full")
                    raise Overloaded(
                        f"microbatch queue full ({len(self._queue)}/{self.max_queue})",
                        retry_after_s=self.max_wait_s,
                        tenant=tenant, quota="gate_full",
                    )
            self._queue.append((synset, fut, tenant))
            self.ledger.acquire(tenant)
            self.requests += 1
            if len(self._queue) > self.queue_hw:
                self.queue_hw = len(self._queue)
                if self.metrics is not None:
                    self.metrics.observe_high("queue_hw_microbatch", len(self._queue))
            self._cv.notify_all()
        return fut

    @hot_path
    def __call__(self, synsets: Sequence[str]) -> list[int]:
        """PredictFn surface: queue every synset, wait for all results.
        Items from concurrent callers interleave into shared batches, which
        is the whole point; per-caller order is preserved by the futures."""
        futs = [self.submit(s) for s in synsets]
        return [int(f.result()) for f in futs]

    def __getattr__(self, name: str):
        # Backend capability passthrough (warmup/load_variables/decode_gang/
        # predict_gang/image_source/...). Only called for attributes not
        # found on the batcher itself.
        return getattr(self._predict, name)

    # ---- worker side ----------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue and self._closed:
                    return
                # Deadline semantics: measured from the moment the worker
                # sees the first queued item; the batch goes as soon as it
                # is FULL, else when the deadline lapses (partial batch).
                # Brownout: the wait shrinks linearly with queue depth — a
                # full bounded queue coalesces with ZERO added latency.
                wait = self.max_wait_s
                if self.max_queue > 0:
                    wait *= max(0.0, 1.0 - len(self._queue) / self.max_queue)
                deadline = time.monotonic() + wait
                while len(self._queue) < self.batch_size and not self._closed:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(timeout=left)
                batch = self._queue[: self.batch_size]
                del self._queue[: self.batch_size]
                for _, _, t in batch:
                    self.ledger.release(t)
            self._dispatch(batch)

    def _dispatch(self, batch: list) -> None:
        synsets = [s for s, _, _ in batch]
        try:
            with tracer.span("scheduler/microbatch", n=len(synsets)):
                preds = list(self._predict(synsets))
            if len(preds) != len(synsets):
                raise RpcError(
                    f"backend returned {len(preds)} predictions for "
                    f"{len(synsets)} queries"
                )
        except BaseException as e:  # noqa: BLE001 - every waiter must observe the failure
            for _, fut, _ in batch:
                fut.set_exception(e)
            return
        with self._cv:
            self.dispatches += 1
            self.fill.record(len(batch) / self.batch_size)
        for (_, fut, _), pred in zip(batch, preds):
            fut.set_result(int(pred))

    def stop(self, timeout_s: float = 10.0) -> None:
        """Drain the queue (queued requests still complete), then join the
        worker. Further submits raise."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=timeout_s)

    def summary(self) -> dict:
        """Coalescing counters for reports/bench: requests, device
        dispatches, and the mean batch-fill fraction (1.0 = every dispatch
        rode a full device batch)."""
        with self._cv:
            out: dict = {
                "requests": self.requests,
                "dispatches": self.dispatches,
                "mean_fill": self.fill.mean if len(self.fill) else 0.0,
                "sheds": self.sheds,
                "queue_hw": self.queue_hw,
            }
            tenants = self.ledger.summary()
            if tenants:
                out["tenants"] = tenants
            return out


def _resolve_paths(image_source, data_dir: Path, synsets: Sequence[str]) -> list[Path]:
    """Synsets -> local image paths: through the SDFS-backed source when
    wired, else the local fixture-corpus layout (shared by both backends)."""
    from dmlc_tpu.ops import preprocess as pp

    if image_source is not None:
        return list(image_source(synsets))
    return [pp.class_image_path(data_dir, s) for s in synsets]


class PredictWorker:
    """RPC surface for shard prediction over a registry of models.

    ``gate`` (cluster/admission.AdmissionGate, optional) bounds concurrent
    ``job.predict`` work: past max_inflight + max_queue the shard is shed
    with a typed ``Overloaded`` instead of queuing on the engine lock toward
    a guaranteed deadline miss. Gang verbs are NOT gated — a collective
    execution needs every rank, so shedding one would fail the whole gang
    the leader is about to retry anyway (the scheduler's gang breaker is the
    backpressure there)."""

    def __init__(self, backends: dict[str, PredictFn], gate=None,
                 decode_lanes: int | None = None):
        self.backends = dict(backends)
        self.gate = gate
        # Decode-tier lane accounting (docs/INGEST.md §Decode tier): this
        # host can usefully run ~one JPEG decode per core; idle lanes =
        # lanes minus in-flight ``job.decode`` RPCs. Exported as the
        # per-member ``decode_lane_idle`` gauge so the leader's
        # ingest-aware placement (and `metrics fleet --worst`) can see
        # which members have spare decode capacity.
        self.decode_lanes = int(decode_lanes or min(32, (os.cpu_count() or 4)))
        self._decode_active = 0
        self._decode_lock = threading.Lock()

    def methods(self) -> dict:
        return traced_methods({
            "job.predict": self._predict,
            "job.predict_gang": self._predict_gang,
            "job.decode_gang": self._decode_gang,
            "job.decode": self._decode,
        })

    def decode_lane_idle(self) -> int:
        """Idle decode lanes right now (gauge read; never negative)."""
        with self._decode_lock:
            return max(0, self.decode_lanes - self._decode_active)

    def _decode(self, p: dict) -> dict:
        """Decode-tier member verb: raw encoded-image BYTES in, one
        device-ready uint8 tensor block out (``data`` = C-contiguous
        [n, size, size, 3] bytes). Rides the member's persistent decode
        pool (native when built, the cached PIL pool otherwise), is
        admission-gated by the SAME predict gate (decode work competes
        with shards for this host's CPU), and inherits the caller's
        deadline/trace ambiently like every traced method. Undecodable
        blobs answer a typed ``DecodeError`` naming the poison indices —
        the leader retries those locally, never here."""
        import numpy as np

        from dmlc_tpu.ops import preprocess as pp

        blobs = list(p["blobs"])
        size = int(p["size"])
        if self.gate is not None:
            with self.gate.admit():
                out, status = self._decode_tracked(pp, blobs, size)
        else:
            out, status = self._decode_tracked(pp, blobs, size)
        if status.any():
            bad = [int(i) for i in np.nonzero(status)[0]]
            raise DecodeError(
                f"{len(bad)}/{len(blobs)} blobs undecodable (indices {bad[:16]})"
            )
        return {"n": len(blobs), "size": size, "data": out.tobytes()}

    def _decode_tracked(self, pp, blobs: list, size: int):
        with self._decode_lock:
            self._decode_active += 1
        try:
            return pp.decode_blobs(blobs, size=size)
        finally:
            with self._decode_lock:
                self._decode_active -= 1

    def _decode_gang(self, p: dict) -> dict:
        """Prefetch decode for an upcoming gang shard: the leader calls this
        while the PREVIOUS gang shard's collective is still executing, so
        host-side JPEG decode overlaps device execution on the distributed
        serving path (VERDICT r3 weak #5 — the single-host path already had
        this via run_paths_stream). Best-effort by contract: a backend
        without staging, or any decode failure, answers staged=False and
        predict_gang decodes inline exactly as before."""
        backend = self.backends.get(p["model"])
        if backend is None or not hasattr(backend, "decode_gang"):
            return {"staged": False}
        staged = backend.decode_gang(
            list(p["synsets"]), int(p["rank"]), int(p["world"])
        )
        return {"staged": bool(staged)}

    def _predict(self, p: dict) -> dict:
        model, synsets = p["model"], list(p["synsets"])
        fn = self.backends.get(model)
        if fn is None:
            raise RpcError(f"model {model!r} not loaded here; have {sorted(self.backends)}")
        if self.gate is not None:
            with self.gate.admit():
                preds = fn(synsets)
        else:
            preds = fn(synsets)
        if len(preds) != len(synsets):
            raise RpcError(f"backend returned {len(preds)} predictions for {len(synsets)} queries")
        return {"predictions": [int(x) for x in preds]}

    def _predict_gang(self, p: dict) -> dict:
        """Gang-scheduled shard: the leader sent the SAME shard to every
        process of the global mesh; this process answers only for its rank's
        contiguous slice, computed inside ONE collective SPMD execution with
        its peers (InferenceEngine.run_batch_global). The reply carries this
        rank's predictions; the leader reassembles rank order."""
        model = p["model"]
        synsets = list(p["synsets"])
        rank, world = int(p["rank"]), int(p["world"])
        backend = self.backends.get(model)
        if backend is None:
            raise RpcError(f"model {model!r} not loaded here; have {sorted(self.backends)}")
        if not hasattr(backend, "predict_gang"):
            raise RpcError(f"backend for {model!r} cannot serve gang shards")
        preds = backend.predict_gang(synsets, rank, world)
        return {"predictions": [int(x) for x in preds]}


def gang_slice(n: int, rank: int, world: int) -> tuple[int, int]:
    """The [start, stop) of rank's contiguous share of an n-query gang
    shard. Mirrors run_batch_global's row-ownership: the global batch is
    process 0's rows, then process 1's, ... — so splitting the shard into
    contiguous per-rank runs keeps reply order == shard order. The leader
    and every member MUST agree on this function."""
    share = -(-n // world) if n else 0  # ceil; empty shard -> empty slices
    start = min(n, rank * share)
    return start, min(n, start + share)


class EngineBackend:
    """Real backend: fixture images through an InferenceEngine.

    Loads lazily on first shard (JAX import + compile are heavy; tests that
    never dispatch to a real model shouldn't pay), then serves every shard
    with one batched device execution. A lock serializes shards per engine —
    the device pipeline is already saturated by one batch stream; the
    reference serialized with a model mutex too (services.rs:493).
    """

    def __init__(
        self,
        model_name: str,
        data_dir: str | Path,
        batch_size: int = 256,
        image_source=None,
        mesh=None,
        variables=None,
        dtype=None,
        device_resize_from: int | None = None,
        device_work=None,
    ):
        self.model_name = model_name
        self.data_dir = Path(data_dir)
        self.batch_size = batch_size
        # Device-plane telemetry hook (cluster/devicemon.py): called with
        # (model, items, device_seconds) per device execution; feeds the
        # node's MFU window and compute cost lane.
        self.device_work = device_work
        # Optional synsets -> local paths resolver (e.g. an SdfsImageSource
        # for the BASELINE "SDFS shard" config); None = local fixture dirs.
        self.image_source = image_source
        # Device-side resize (ops/device_resize.py): decode at this RAW
        # size on the host (no host resample) and reach the model's input
        # size on the chip — the decode tier's peers then ship near-raw
        # uint8 and the host CPU sheds the ~35% that resample costs.
        self.device_resize_from = device_resize_from
        # Fleet decode tier client (cluster/decodetier.py), wired by the
        # node when decode_tier_enabled: multi-batch shards source their
        # prefetch decode through it instead of only the local stage pool.
        self.decode_tier = None
        # Optional engine construction overrides: a GLOBAL (multi-process)
        # mesh makes this backend gang-capable — predict_gang answers its
        # rank's slice of a collectively-executed shard. Variables must then
        # be identical on every process (replicated from SDFS, or same seed).
        self.mesh = mesh
        self.variables = variables
        self.dtype = dtype
        self._engine = None
        self._lock = threading.Lock()
        # Gang decode staging: slice-content -> decoded uint8 batch, keyed
        # by the synset tuple itself so a requeued shard's stage is still
        # valid and no leader-coordinated token is needed. Bounded LRU —
        # entries are ~batch/world images each.
        self._staged: "OrderedDict[tuple, object]" = OrderedDict()
        self._stage_lock = threading.Lock()
        self.stage_hits = 0  # predict_gang calls served from a prefetch

    _STAGE_CAP = 4

    def warmup(self) -> None:
        """Build + compile the engine now. Call at node startup, BEFORE the
        membership loops begin: tracing/compiling holds the GIL for seconds
        at a time, which starves the heartbeat threads past the failure
        timeout and gets the node falsely marked FAILED mid-compile (the
        reference loads models eagerly before joining for the same reason,
        services.rs:513-524)."""
        with self._lock:
            self._ensure_engine()

    def _ensure_engine(self):
        if self._engine is None:
            from dmlc_tpu.parallel.inference import InferenceEngine

            kw = {}
            if self.mesh is not None:
                kw["mesh"] = self.mesh
            if self.variables is not None:
                kw["variables"] = self.variables
            if self.dtype is not None:
                kw["dtype"] = self.dtype
            if self.device_resize_from is not None:
                kw["device_resize_from"] = self.device_resize_from
            if self.device_work is not None:
                kw["device_work"] = self.device_work
            self._engine = InferenceEngine(
                self.model_name, batch_size=self.batch_size, **kw
            )
            self._engine.warmup()
        return self._engine

    def __call__(self, synsets: Sequence[str]) -> list[int]:
        # dmlc-lint: disable=A2 -- the engine lock serializes shards per engine BY DESIGN (the reference's model mutex, services.rs:493); the future wait it reaches in run_paths_stream is the decode/execute pipeline INSIDE one shard, not a foreign dependency
        with self._lock:
            engine = self._ensure_engine()
            paths = _resolve_paths(self.image_source, self.data_dir, synsets)
            if len(paths) <= self.batch_size:
                result = engine.run_paths(paths)
            else:
                # Multi-batch shard: decode batch i+1 while the device runs
                # batch i (SURVEY §7 hard part b). With the fleet decode
                # tier wired, that prefetch decode fans out across peers'
                # idle decode lanes instead of only the local stage pool.
                result = engine.run_paths_stream(
                    paths,
                    decode_source=(
                        self.decode_tier.decode_paths
                        if self.decode_tier is not None
                        else None
                    ),
                )
            return [int(x) for x in result.top1_index]

    def decode_gang(self, synsets: Sequence[str], rank: int, world: int) -> bool:
        """Decode this rank's slice of an UPCOMING gang shard into the
        staging buffer, deliberately OUTSIDE the engine lock: the leader
        sends this while the previous shard's collective still holds that
        lock, so decode and device execution overlap across gang shards.
        Best-effort: any failure stages nothing, and predict_gang decodes
        inline with its existing deferred-error symmetry."""
        from dmlc_tpu.ops import preprocess as pp

        try:
            engine = self._engine
            if engine is None:
                # First touch only; afterwards the reference read above is
                # lock-free so a running collective cannot block prefetch.
                with self._lock:
                    engine = self._ensure_engine()
            start, stop = gang_slice(len(synsets), rank, world)
            mine = tuple(synsets[start:stop])
            if not mine:
                return False
            paths = _resolve_paths(self.image_source, self.data_dir, list(mine))
            batch = pp.load_batch(paths, size=engine.input_size)
            with self._stage_lock:
                self._staged[mine] = batch
                while len(self._staged) > self._STAGE_CAP:
                    self._staged.popitem(last=False)
            return True
        except Exception:
            log.warning("gang decode prefetch failed; will decode inline", exc_info=True)
            return False

    def _pop_staged(self, mine: Sequence[str]):
        with self._stage_lock:
            return self._staged.pop(tuple(mine), None)

    def predict_gang(self, synsets: Sequence[str], rank: int, world: int) -> list[int]:
        """This rank's slice of a gang shard, through ONE SPMD execution
        entered by every process of the engine's global mesh.

        Failure symmetry is the load-bearing property: every process must
        enter the collective or the others deadlock inside it holding this
        backend's lock. So any per-rank failure that the OTHER ranks cannot
        see (an unreadable corpus file, a rank mismatch, an over-cap slice
        on just the non-tail ranks) is deferred — this rank still enters
        the collective with an EMPTY batch, then raises to the leader after
        its peers have been released. Only failures that hit every rank
        identically (engine construction, batch/process divisibility) may
        raise before the collective."""
        import jax
        import numpy as np

        from dmlc_tpu.ops import preprocess as pp

        with self._lock:
            engine = self._ensure_engine()
            size = engine.input_size
            deferred: Exception | None = None
            batch = np.zeros((0, size, size, 3), np.uint8)
            try:
                if rank != jax.process_index():
                    # Scheduler rank map and jax runtime MUST agree, or
                    # rows come back permuted across members.
                    raise RpcError(
                        f"gang rank mismatch: scheduler says {rank}, "
                        f"jax.process_index() is {jax.process_index()}"
                    )
                start, stop = gang_slice(len(synsets), rank, world)
                mine = list(synsets[start:stop])
                cap = engine.batch_size // max(1, jax.process_count())
                if len(mine) > cap:
                    raise RpcError(
                        f"gang slice of {len(mine)} exceeds per-process "
                        f"batch cap {cap} (shard too large for the engines)"
                    )
                if mine:
                    batch = self._pop_staged(mine)
                    if batch is not None:
                        self.stage_hits += 1
                    else:
                        paths = _resolve_paths(self.image_source, self.data_dir, mine)
                        batch = pp.load_batch(paths, size=size)
            except Exception as e:
                deferred = e
            result = engine.run_batch_global(batch)
            if deferred is not None:
                raise RpcError(f"{type(deferred).__name__}: {deferred}")
            return [int(x) for x in result.top1_index]

    def load_variables(self, variables) -> None:
        """Swap pretrained weights into the live engine (member side of the
        `train` verb — the reference reloads .ot files, services.rs:513-524)."""
        with self._lock:
            self._ensure_engine().load_variables(variables)


class LmBackend:
    """Gang-sharded causal-LM serving backend (docs/SHARDING.md).

    A "synset" on a ``kind="lm"`` job is a PROMPT ID: the encoding is the
    deterministic arithmetic in ``parallel.sharding.tokens_for_prompt``, so
    the leader, every gang member, and the single-process reference agree on
    the token stream byte-for-byte, and the predicted "class index" is the
    argmax next-token id — the existing job.predict accuracy accounting then
    measures exact TOKEN IDENTITY against reference labels.

    The compiled program comes from the partition-rule engine: one rule
    table, compiled at whatever gang width the PlacementAdvisor chose
    (``plan_axes`` splits the width into dp x tp). Solo ``__call__`` REFUSES
    when the model's resident bytes exceed this chip's HBM budget — the
    refusal the advisor converts into a wide gang instead of a dead job.
    ``predict_gang`` serves a rank's contiguous ``gang_slice`` of the shard
    from a program sharded across the gang's chips, so per-chip residency is
    ``sharded_bytes_per_chip`` — under the budget the solo path refused at.
    """

    def __init__(
        self,
        model_name: str,
        *,
        gang_devices: int = 0,
        prompt_len: int = 16,
        dtype=None,
        hbm_budget_bytes: int = 0,
        device_work=None,
        devices=None,
    ):
        self.model_name = model_name
        self.prompt_len = prompt_len
        # Fixed gang width (config lm_gang_devices); 0 = follow the
        # scheduler's world size, clamped to the local chip count.
        self.gang_devices = gang_devices
        # Per-chip resident-bytes budget enforced on the SOLO path; 0 = no
        # budget (model fits anywhere). The test harness sets this below
        # lm_wide's bytes so the model only serves sharded.
        self.hbm_budget_bytes = hbm_budget_bytes
        self.device_work = device_work
        self._devices = devices
        self._dtype = dtype
        self._programs: dict[int, object] = {}
        self._lock = threading.Lock()

    def _resolve_devices(self) -> list:
        if self._devices is not None:
            return list(self._devices)
        import jax

        return list(jax.devices())

    def _program(self, width: int):
        import jax.numpy as jnp

        from dmlc_tpu.models.registry import get_model
        from dmlc_tpu.parallel import sharding as sharding_lib
        from dmlc_tpu.parallel.mesh import make_mesh

        devs = self._resolve_devices()
        width = max(1, min(width, len(devs)))
        prog = self._programs.get(width)
        if prog is None:
            spec = get_model(self.model_name)
            axes = sharding_lib.plan_axes(width, num_heads=spec.num_heads)
            mesh = make_mesh(axes, devices=devs[:width])
            prog = sharding_lib.ShardedProgram(
                self.model_name, mesh, dtype=self._dtype or jnp.float32
            )
            self._programs[width] = prog
        return prog

    def warmup(self) -> None:
        """Build + compile now, BEFORE the membership loops begin (same
        GIL-starvation rationale as EngineBackend.warmup)."""
        with self._lock:
            self._program(self.gang_devices or 1)

    def _run(self, prog, synsets: Sequence[str]) -> list[int]:
        from dmlc_tpu.parallel import sharding as sharding_lib

        spec = prog.spec  # registry ModelSpec: input_size=max_len, num_outputs=vocab
        tokens = sharding_lib.encode_prompts(
            list(synsets), min(self.prompt_len, spec.input_size), spec.num_outputs
        )
        t0 = time.monotonic()
        out = prog.run(tokens)
        if self.device_work is not None:
            self.device_work(self.model_name, len(synsets), time.monotonic() - t0)
        return [int(x) for x in out]

    def __call__(self, synsets: Sequence[str]) -> list[int]:
        with self._lock:
            if self.hbm_budget_bytes > 0:
                import jax.numpy as jnp

                from dmlc_tpu.models.registry import get_model

                need = get_model(self.model_name).param_bytes(
                    self._dtype or jnp.float32
                )
                if need > self.hbm_budget_bytes:
                    raise RpcError(
                        f"model {self.model_name!r} needs {need} resident bytes, "
                        f"over this chip's {self.hbm_budget_bytes} HBM budget; "
                        f"serve it as a gang (docs/SHARDING.md)"
                    )
            return self._run(self._program(1), synsets)

    def predict_gang(self, synsets: Sequence[str], rank: int, world: int) -> list[int]:
        """This rank's contiguous slice of a gang shard, computed by the
        rule-sharded program at the gang's width. Unlike EngineBackend's
        multi-process SPMD path there is no collective-entry symmetry to
        keep — each rank's slice is an independent device execution over
        chip-sharded weights — so an empty slice just answers []."""
        with self._lock:
            prog = self._program(self.gang_devices or world)
            start, stop = gang_slice(len(synsets), rank, world)
            mine = list(synsets[start:stop])
            if not mine:
                return []
            return self._run(prog, mine)

    def load_variables(self, variables) -> None:
        """Hot-swap weights (the `train` verb): every cached width re-shards
        the same host tree under the model's rule table."""
        with self._lock:
            for prog in self._programs.values():
                prog.load_variables(variables)

    def resident_bytes(self) -> int | None:
        """Per-chip resident weight bytes of the WIDEST built program — the
        number the leader's HBM gauges see, so the advisor's headroom math
        reflects the sharded (post-gang) footprint, not the replicated one.
        None until a program builds (same contract as engine gauges)."""
        from dmlc_tpu.parallel import sharding as sharding_lib

        if not self._programs:
            return None
        prog = self._programs[max(self._programs)]
        return int(
            sharding_lib.sharded_bytes_per_chip(
                self.model_name, prog.mesh, dtype=prog.dtype
            )
        )


class ExportedBackend:
    """Serve shards from the SDFS-distributed StableHLO artifact + weights —
    NO model source code on the serving path. This is the deployed form of
    the native-serving contract (models/export.py): everything a member
    needs to answer ``job.predict`` is two SDFS files, ``executables/<m>``
    and ``models/<m>``. Weights absent from SDFS fall back to the registry's
    random init (exactly EngineBackend's behavior before `train`), and
    `train` hot-swaps them through ``load_variables`` like any backend.
    """

    def __init__(
        self,
        model_name: str,
        data_dir: str | Path,
        sdfs,
        image_source=None,
    ):
        self.model_name = model_name
        self.data_dir = Path(data_dir)
        self.sdfs = sdfs
        self.image_source = image_source
        self._server = None
        self._lock = threading.Lock()
        # Persistent decode-ahead worker for the shard pipeline below —
        # created once here, never per shard (lint H1: no per-call pools on
        # hot paths; the old code built a ThreadPoolExecutor every __call__).
        self._decoder = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="export-decode"
        )

    def warmup(self) -> None:
        # dmlc-lint: disable=A2 -- one-time lazy init: the SDFS artifact/weights fetch MUST happen under the lock so shards arriving before the artifact is resident block instead of double-fetching (same invariant as the in-file L1 suppression inside _ensure_server)
        with self._lock:
            self._ensure_server()

    def _ensure_server(self):
        if self._server is None:
            import jax
            import numpy as np

            from dmlc_tpu.cluster.rpc import RpcUnreachable
            from dmlc_tpu.models import export as export_lib
            from dmlc_tpu.models import weights as weights_lib
            from dmlc_tpu.models.registry import get_model

            spec = get_model(self.model_name)
            version, exported = export_lib.fetch_executable(self.sdfs, self.model_name)
            # The artifact's input shape is FIXED at export: serving batch
            # and input size come from IT, never from node config — an
            # artifact exported at another size must not shape-mismatch.
            u8_avals = [
                a for a in exported.in_avals if str(a.dtype) == "uint8" and len(a.shape) == 4
            ]
            if not u8_avals:
                raise RpcError(
                    f"executable for {self.model_name!r} has no uint8 NHWC "
                    "input — not a serving artifact this backend can drive"
                )
            u8_aval = u8_avals[0]
            artifact_batch = int(u8_aval.shape[0])
            try:
                # dmlc-lint: disable=L1 -- one-time lazy init: shards arriving before the artifact is resident MUST block here; after first load the fetch never runs again
                _, blob = self.sdfs.get_bytes(weights_lib.sdfs_weights_name(self.model_name))
                # Validation errors (corrupt/mismatched blob) PROPAGATE —
                # weights.py's contract is fail-at-load, never serve them.
                _, variables = weights_lib.weights_from_bytes(blob, expect_model=self.model_name)
                log.info("%s: artifact v%d + SDFS weights", self.model_name, version)
            except RpcUnreachable:
                raise  # transient (failover mid-fetch): retry the shard, not random-init
            except RpcError as e:
                if not weights_lib.not_published(e):
                    raise  # any refusal other than not-published is not consent
                _, variables = spec.init_params(jax.random.PRNGKey(0), dtype=jax.numpy.float32)
                variables = jax.tree_util.tree_map(np.asarray, variables)
                log.info("%s: artifact v%d, weights not published yet — random init", self.model_name, version)
            self._server = export_lib.ExportedServer(
                exported, variables, artifact_batch, classifier=spec.classifier
            )
            self._serve_batch = artifact_batch
            self._input_size = int(u8_aval.shape[1])
        return self._server

    @hot_path
    def __call__(self, synsets: Sequence[str]) -> list[int]:
        from dmlc_tpu.ops import preprocess as pp

        if not synsets:
            return []
        # dmlc-lint: disable=A2 -- the backend lock serializes shards per artifact by design (reference's model mutex), and first-shard lazy init must block later shards on the one SDFS fetch (see _ensure_server's L1 justification)
        with self._lock:
            server = self._ensure_server()
            chunk_size = self._serve_batch
            paths = _resolve_paths(self.image_source, self.data_dir, synsets)
            starts = list(range(0, len(paths), chunk_size))
            preds: list[int] = []
            # Decode chunk i+1 while the artifact executes chunk i (the same
            # overlap EngineBackend gets from run_paths_stream), on the
            # PERSISTENT self._decoder — never a per-shard pool (lint H1).
            decode = lambda s: pp.load_batch(
                paths[s : s + chunk_size], size=self._input_size
            )
            fut = self._decoder.submit(decode, starts[0])
            for i, s in enumerate(starts):
                # dmlc-lint: disable=L1 -- the backend lock serializes shards per artifact by design (reference's model mutex); the wait is the decode/execute pipeline inside one shard
                batch = fut.result()
                if i + 1 < len(starts):
                    fut = self._decoder.submit(decode, starts[i + 1])
                idx, _ = server(batch)
                preds.extend(int(x) for x in idx)
            return preds

    def load_variables(self, variables) -> None:
        """The `train` verb's hot-swap: same validated tree the engine path
        takes, handed to the artifact executor."""
        # dmlc-lint: disable=A2 -- hot-swap must not interleave with a running shard, so it takes the same serializing lock; the SDFS fetch it can reach is the one-time lazy init (see _ensure_server)
        with self._lock:
            self._ensure_server().variables = variables


class ModelLoader:
    """Member RPC surface for hot-loading distributed weights.

    After `train` replicates ``models/{model}`` into a member's local SDFS
    store, the leader calls ``model.load`` here: read the blob from the local
    store, deserialize + validate (models/weights.py), and hand the variables
    to the model's backend. Backends without ``load_variables`` (test fakes)
    refuse cleanly.
    """

    def __init__(self, store, backends: dict, extra: dict | None = None):
        self.store = store
        self.backends = backends
        # A second live backend table (e.g. the generation backends): the
        # `train` verb hot-swaps LM weights the same way it swaps image
        # weights. Predict backends win a (never-expected) name collision.
        self.extra = extra if extra is not None else {}

    def methods(self) -> dict:
        return traced_methods({"model.load": self._load})

    def _load(self, p: dict) -> dict:
        from dmlc_tpu.models import weights as weights_lib

        model = p["model"]
        backend = self.backends.get(model, self.extra.get(model))
        if backend is None:
            raise RpcError(f"model {model!r} not served here")
        if not hasattr(backend, "load_variables"):
            raise RpcError(f"backend for {model!r} does not support weight loading")
        name = weights_lib.sdfs_weights_name(model)
        version = int(p["version"])
        try:
            blob = self.store.read(name, version)
        except KeyError as e:
            raise RpcError(str(e))
        try:
            _, variables = weights_lib.weights_from_bytes(blob, expect_model=model)
        except ValueError as e:
            raise RpcError(f"bad weights blob {name} v{version}: {e}")
        backend.load_variables(variables)
        log.info("loaded %s v%d into %s backend", name, version, model)
        return {"model": model, "version": version}
