"""GenerationEngine: jitted autoregressive decode over a paged KV cache.

One engine serves one registry LM (kind="lm") at a FIXED batch shape: every
decode step runs all ``max_slots`` rows whether or not a request occupies
them — that is what makes continuous batching recompile-free (one jit cache
entry across the whole serving lifetime; tests pin ``_cache_size() == 1``)
and what lets slots join/leave between steps without reshaping anything.

Two jitted programs, both built ONCE in ``__init__`` (never per request —
lint J2's regression class):

- ``_prefill``: one slot's padded prompt ([1, max_prefill]) through the
  full causal forward; K/V for real positions are scattered into the
  slot's pages (padding lands on the scratch page), and the last real
  position's logits seed the first sampled token. Exact because padding
  sits at the END under a causal mask: no real position can attend to it.
- ``_step``: one token per slot ([max_slots]) — embed + per-layer
  (write K/V into pages at position ``lengths[s]``, ragged paged attention
  over ``lengths[s]+1`` cached positions, MLP) + head + sampling (greedy
  at temperature 0, categorical otherwise, per-slot temperature). The
  page pools are DONATED through both programs, so exactly one generation
  of the cache exists in device memory.

Sampling is **per-slot position-seeded**: the categorical draw for the
token at sequence position ``p`` of a request seeded ``s`` uses the key
``fold_in(fold_in(PRNGKey(0), s), p)`` — a pure function of (seed,
position), independent of batch composition, step count, or which slot row
the request occupies. That is what makes a migrated stream token-identical
to its unkilled reference (docs/GENERATE.md §Migration): re-prefilling
``prompt + delivered_prefix`` on another member with the same seed resumes
the identical random sequence at the identical position, so the
continuation equals the uninterrupted run token for token.

The forward math mirrors ``parallel.sp_transformer.SPTransformerLM``
parameter-for-parameter (same trees, flax LayerNorm/Dense/gelu semantics,
dense_attention's f32 score discipline), so decode logits match the full-
sequence ``lm.apply`` within float tolerance — the paged-KV correctness
pin. ``cache="contiguous"`` swaps the paged gather for a dense per-slot
cache with identical math: the parity reference for the paged path, and
the baseline the 2x continuous-batching pin measures against.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from dmlc_tpu.generate.kvcache import SCRATCH_PAGE, PagedKVCache


# ---------------------------------------------------------------------------
# flax-parity primitives (pure functions over the module's param tree)
# ---------------------------------------------------------------------------


def _layer_norm(x: Any, p: Any) -> Any:
    # flax.linen.LayerNorm semantics: population moments over the last
    # axis, epsilon 1e-6, learned scale + bias.
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]


def _dense(x: Any, p: Any) -> Any:
    return x @ p["kernel"] + p["bias"]


def _split_heads(x: Any, num_heads: int) -> Any:
    # [..., D] -> [..., H, Dh]
    return x.reshape(*x.shape[:-1], num_heads, x.shape[-1] // num_heads)


class GenerationEngine:
    """Continuous-batching decode driver for one registry LM.

    Host-side state (lengths, active flags, temperatures, the page table)
    is NumPy; device state is the param tree and the KV pools. Mutating
    methods (join/step/release) must be serialized by the caller — the
    SlotScheduler's decode thread is the only writer in production;
    ``reserve``/``release_reservation`` are thread-safe (the allocator has
    its own lock) so admission can run on RPC threads.
    """

    def __init__(
        self,
        model_name: str,
        *,
        variables: Any = None,
        dtype: Any = None,
        max_slots: int = 8,
        page_size: int = 16,
        num_pages: int = 128,
        max_prefill: int = 64,
        cache: str = "paged",
        use_pallas: bool | None = None,
        return_logits: bool = False,
        seed: int = 0,
        device_work: Any = None,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from dmlc_tpu.models.registry import get_model

        # Device-plane telemetry hook (cluster/devicemon.py): called with
        # (model, tokens, seconds) per decode step so the node's
        # DeviceMonitor can track achieved FLOP/s vs roofline. None = off.
        self.device_work = device_work

        if cache not in ("paged", "contiguous"):
            raise ValueError(f"cache must be 'paged' or 'contiguous', got {cache!r}")
        spec = get_model(model_name)
        if spec.kind != "lm":
            raise ValueError(f"{model_name!r} is not a language model (kind={spec.kind})")
        self.spec = spec
        self.model_name = spec.name
        self.dtype = dtype if dtype is not None else jnp.float32
        module = spec.module(dtype=self.dtype)
        if variables is None:
            # Seed init: generation is servable with no published weights,
            # exactly like the predict path before `train`.
            _, variables = spec.init_params(
                jax.random.PRNGKey(0), dtype=self.dtype, batch_size=1
            )
        self._variables = jax.device_put(variables)
        self.vocab = int(module.vocab)
        self.num_layers = int(module.num_layers)
        self.num_heads = int(module.num_heads)
        self.hidden = int(module.hidden)
        self.head_dim = self.hidden // self.num_heads
        self.max_len = int(module.max_len)
        self.max_slots = int(max_slots)
        self.max_prefill = min(int(max_prefill), self.max_len)
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self.use_pallas = bool(use_pallas)
        self.cache_mode = cache
        self.return_logits = bool(return_logits)

        max_pages_per_slot = -(-self.max_len // int(page_size))
        if cache == "paged":
            self.cache = PagedKVCache(
                num_layers=self.num_layers,
                num_pages=num_pages,
                page_size=page_size,
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                max_slots=self.max_slots,
                max_pages_per_slot=max_pages_per_slot,
                dtype=self.dtype,
            )
            self.max_tokens = min(self.max_len, self.cache.max_tokens_per_slot)
            self._k_state = self.cache.k_pages
            self._v_state = self.cache.v_pages
        else:
            self.cache = None
            self.max_tokens = self.max_len
            shape = (
                self.num_layers, self.max_slots, self.max_tokens,
                self.num_heads, self.head_dim,
            )
            self._k_state = jnp.zeros(shape, self.dtype)
            self._v_state = jnp.zeros(shape, self.dtype)

        # Host-side slot registers (fixed batch shape).
        self.lengths = np.zeros(self.max_slots, np.int32)
        self.active = np.zeros(self.max_slots, bool)
        self.temps = np.zeros(self.max_slots, np.float32)
        self.steps = 0
        self.tokens_out = 0
        self.last_tokens = np.zeros(self.max_slots, np.int32)
        self.last_logits: np.ndarray | None = None
        # Per-slot sampling seeds (position-seeded RNG, module docstring).
        # Default seeds derive deterministically from the engine seed and a
        # join counter; a caller-supplied seed (the router's migration path)
        # overrides so a resumed stream replays the same random sequence.
        self.seeds = np.zeros(self.max_slots, np.uint32)
        self._base_seed = int(seed)
        self._joins = 0

        # The two compiled programs — built exactly once (J2/H1 contract),
        # census-wrapped so a steady-state recompile of either is a labeled
        # flight alert (cluster/devicemon.py; the wrapper passes
        # ``_cache_size`` through, so the ==1 invariant pins unchanged).
        from dmlc_tpu.cluster.devicemon import CensusedJit

        self._step = CensusedJit(f"gen/{self.model_name}/step", self._build_step())
        self._prefill = CensusedJit(
            f"gen/{self.model_name}/prefill", self._build_prefill()
        )

    # ---- forward math ---------------------------------------------------

    def _params(self, variables: Any) -> Any:
        return variables["params"]

    def _attend(self, q: Any, k_state: Any, v_state: Any, layer: int,
                page_table: Any, kv_lengths: Any, slots: Any = None) -> Any:
        """Per-layer decode attention: paged gather + ragged mask, or the
        contiguous per-slot view. q: [B, H, Dh] -> [B, H, Dh]."""
        from dmlc_tpu.ops.ragged_decode import (
            gather_kv_pages,
            ragged_decode_attention,
        )

        if self.cache_mode == "paged":
            k = gather_kv_pages(k_state[layer], page_table, use_pallas=self.use_pallas)
            v = gather_kv_pages(v_state[layer], page_table, use_pallas=self.use_pallas)
        else:
            k, v = k_state[layer], v_state[layer]  # [B, S_max, H, Dh]
        return ragged_decode_attention(q, k, v, kv_lengths)

    def _build_step(self) -> Any:
        import jax
        import jax.numpy as jnp

        num_heads = self.num_heads
        page_size = self.cache.page_size if self.cache_mode == "paged" else 0
        num_layers = self.num_layers
        return_logits = self.return_logits

        def step(variables: Any, k_state: Any, v_state: Any, tokens: Any,
                 lengths: Any, active: Any, page_table: Any, seeds: Any,
                 temps: Any) -> Any:
            p = self._params(variables)
            pos = jnp.minimum(lengths, self.max_len - 1)
            x = p["embed"]["embedding"][tokens] + p["pos_embed"]["embedding"][pos]
            x = x.astype(self.dtype)
            if self.cache_mode == "paged":
                # Destination of this step's K/V: the page covering position
                # ``lengths[s]`` — inactive rows write into scratch page 0.
                page_idx = jnp.take_along_axis(
                    page_table, (lengths // page_size)[:, None], axis=1
                )[:, 0]
                dest_page = jnp.where(active, page_idx, SCRATCH_PAGE)
                dest_off = lengths % page_size
            kv_lengths = jnp.maximum(lengths + 1, 1)
            batch = jnp.arange(tokens.shape[0])
            for layer in range(num_layers):
                blk = p[f"block{layer}"]
                h = _layer_norm(x, blk["ln1"])
                q = _split_heads(_dense(h, blk["attn"]["query"]), num_heads)
                k = _split_heads(_dense(h, blk["attn"]["key"]), num_heads)
                v = _split_heads(_dense(h, blk["attn"]["value"]), num_heads)
                if self.cache_mode == "paged":
                    k_state = k_state.at[layer, dest_page, dest_off].set(k)
                    v_state = v_state.at[layer, dest_page, dest_off].set(v)
                else:
                    k_state = k_state.at[layer, batch, lengths].set(k)
                    v_state = v_state.at[layer, batch, lengths].set(v)
                att = self._attend(q, k_state, v_state, layer, page_table, kv_lengths)
                x = x + _dense(att.reshape(att.shape[0], -1), blk["attn"]["out"])
                h2 = _layer_norm(x, blk["ln2"])
                h2 = jax.nn.gelu(_dense(h2, blk["mlp_in"]))
                x = x + _dense(h2, blk["mlp_out"])
            x = _layer_norm(x, p["ln_f"])
            logits = _dense(x, p["head"]).astype(jnp.float32)  # [B, V]
            # The token sampled here lands at sequence position ``lengths``
            # (pre-increment) — the position the key must be folded on.
            nxt = _sample(logits, seeds, lengths, temps)
            if return_logits:
                return k_state, v_state, nxt, logits
            return k_state, v_state, nxt

        return jax.jit(step, donate_argnums=(1, 2))

    def _build_prefill(self) -> Any:
        import jax
        import jax.numpy as jnp

        from dmlc_tpu.parallel.ring_attention import dense_attention

        num_heads = self.num_heads
        num_layers = self.num_layers
        page_size = self.cache.page_size if self.cache_mode == "paged" else 0
        s_pad = self.max_prefill

        def prefill(variables: Any, tokens: Any, length: Any, k_state: Any,
                    v_state: Any, dest: Any, seed: Any, temp: Any) -> Any:
            """tokens: [1, s_pad]; length: [] int32 (real prompt length);
            dest: page row [max_pages_per_slot] (paged) or slot index []
            (contiguous)."""
            p = self._params(variables)
            x = p["embed"]["embedding"][tokens] + p["pos_embed"]["embedding"][
                jnp.arange(s_pad)
            ][None, :]
            x = x.astype(self.dtype)
            seq = jnp.arange(s_pad)
            if self.cache_mode == "paged":
                dest_page = jnp.where(seq < length, dest[seq // page_size], SCRATCH_PAGE)
                dest_off = seq % page_size
            for layer in range(num_layers):
                blk = p[f"block{layer}"]
                h = _layer_norm(x, blk["ln1"])
                q = _split_heads(_dense(h, blk["attn"]["query"]), num_heads)
                k = _split_heads(_dense(h, blk["attn"]["key"]), num_heads)
                v = _split_heads(_dense(h, blk["attn"]["value"]), num_heads)
                if self.cache_mode == "paged":
                    k_state = k_state.at[layer, dest_page, dest_off].set(k[0])
                    v_state = v_state.at[layer, dest_page, dest_off].set(v[0])
                else:
                    # Positions past ``length`` are scratch rows the ragged
                    # mask never exposes; later decode steps overwrite them.
                    k_state = k_state.at[layer, dest, :s_pad].set(k[0])
                    v_state = v_state.at[layer, dest, :s_pad].set(v[0])
                qh = q.transpose(0, 2, 1, 3)  # [1, H, S, Dh]
                att = dense_attention(
                    qh, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), causal=True
                ).transpose(0, 2, 1, 3)
                x = x + _dense(att.reshape(1, s_pad, -1), blk["attn"]["out"])
                h2 = _layer_norm(x, blk["ln2"])
                h2 = jax.nn.gelu(_dense(h2, blk["mlp_in"]))
                x = x + _dense(h2, blk["mlp_out"])
            x = _layer_norm(x, p["ln_f"])
            logits = _dense(x, p["head"]).astype(jnp.float32)  # [1, S, V]
            last = jnp.take(logits[0], length - 1, axis=0)     # [V]
            # First sampled token comes from position ``length - 1`` — the
            # same position a resumed prefill of prompt+prefix re-samples.
            nxt = _sample(
                last[None],
                jnp.reshape(seed, (1,)),
                jnp.reshape(length - 1, (1,)),
                temp[None],
            )[0]
            return k_state, v_state, nxt, last

        return jax.jit(prefill, donate_argnums=(3, 4))

    # ---- admission (thread-safe) ----------------------------------------

    def reserve(self, prompt_len: int) -> list[int]:
        """Reserve pages for a prompt plus its first generated token.
        Raises PagePoolExhausted — the submit-time shed signal. Contiguous
        mode has nothing to reserve (capacity is the slot row itself)."""
        if self.cache_mode != "paged":
            return []
        need = self.cache.allocator.pages_for(int(prompt_len) + 1)
        return self.cache.allocator.alloc(need)

    def release_reservation(self, pages: list[int]) -> None:
        if self.cache_mode == "paged" and pages:
            self.cache.allocator.free(pages)

    # ---- slot lifecycle (decode-thread only) -----------------------------

    def free_slots(self) -> list[int]:
        return [s for s in range(self.max_slots) if not self.active[s]]

    def join(self, slot: int, prompt: Any, *, temperature: float = 0.0,
             pages: list[int] | None = None, seed: int | None = None) -> int:
        """Prefill ``prompt`` into ``slot`` and return the first sampled
        token. ``pages`` is the submit-time reservation (paged mode).
        ``seed`` keys the position-seeded sampling RNG; passing the same
        seed with ``prompt + delivered_prefix`` resumes a migrated stream
        token-identically (module docstring)."""
        import jax.numpy as jnp

        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token sequence")
        if prompt.size > self.max_prefill:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds max_prefill="
                f"{self.max_prefill}"
            )
        if self.active[slot]:
            raise ValueError(f"slot {slot} is already active")
        if self.cache_mode == "paged":
            if pages is None:
                pages = self.reserve(prompt.size)
            self.cache.bind(slot, pages)
            dest = jnp.asarray(self.cache.page_table[slot], jnp.int32)
        else:
            dest = jnp.int32(slot)
        padded = np.zeros(self.max_prefill, np.int32)
        padded[: prompt.size] = prompt
        if seed is None:
            seed = (self._base_seed * 1_000_003 + self._joins) % (1 << 31)
        self._joins += 1
        seed = int(seed) & 0xFFFFFFFF
        k_state, v_state, nxt, last = self._prefill(
            self._variables,
            jnp.asarray(padded[None]),
            jnp.int32(prompt.size),
            self._k_state,
            self._v_state,
            dest,
            jnp.uint32(seed),
            jnp.float32(temperature),
        )
        self._set_state(k_state, v_state)
        first = int(nxt)
        self.lengths[slot] = prompt.size
        self.active[slot] = True
        self.temps[slot] = float(temperature)
        self.seeds[slot] = seed
        self.last_tokens[slot] = first
        self.tokens_out += 1
        return first

    def ensure_capacity(self, slot: int) -> None:
        """Grow the slot's page run if the NEXT step's write would cross a
        page boundary. Raises PagePoolExhausted (eviction policy is the
        scheduler's call, not the engine's)."""
        if self.cache_mode != "paged":
            return
        if not self.cache.capacity_ok(slot, int(self.lengths[slot]) + 1):
            self.cache.grow(slot)

    def step(self) -> np.ndarray:
        """One decode step over every active slot (fixed batch shape).
        Appends the previous sampled token to each slot's cache and samples
        the next; returns the sampled token per slot ([max_slots], only
        active rows meaningful). Host state advances for active slots."""
        import time

        import jax.numpy as jnp

        t0 = time.perf_counter()
        table = (
            jnp.asarray(self.cache.page_table)
            if self.cache_mode == "paged"
            else jnp.zeros((self.max_slots, 1), jnp.int32)
        )
        out = self._step(
            self._variables,
            self._k_state,
            self._v_state,
            jnp.asarray(self.last_tokens),
            jnp.asarray(self.lengths),
            jnp.asarray(self.active),
            table,
            jnp.asarray(self.seeds),
            jnp.asarray(self.temps),
        )
        if self.return_logits:
            k_state, v_state, nxt, logits = out
            self.last_logits = np.asarray(logits)
        else:
            k_state, v_state, nxt = out
        self._set_state(k_state, v_state)
        tokens = np.asarray(nxt)
        n_active = int(self.active.sum())
        self.lengths[self.active] += 1
        self.last_tokens[self.active] = tokens[self.active]
        self.steps += 1
        self.tokens_out += n_active
        if self.device_work is not None and n_active > 0:
            # np.asarray(nxt) above materialized the step's results, so
            # this wall is the step's real device+host latency.
            self.device_work(self.model_name, n_active, time.perf_counter() - t0)
        return tokens

    def release(self, slot: int) -> list[int]:
        """Slot exit: recycle its pages, reset its registers. Returns the
        freed page ids."""
        self.active[slot] = False
        self.lengths[slot] = 0
        self.temps[slot] = 0.0
        self.seeds[slot] = 0
        self.last_tokens[slot] = 0
        if self.cache_mode == "paged":
            return self.cache.release(slot)
        return []

    def _set_state(self, k_state: Any, v_state: Any) -> None:
        self._k_state = k_state
        self._v_state = v_state
        if self.cache_mode == "paged":
            self.cache.k_pages = k_state
            self.cache.v_pages = v_state

    # ---- observability / weights ----------------------------------------

    @property
    def slots_active(self) -> int:
        return int(self.active.sum())

    @property
    def pages_free(self) -> int:
        return self.cache.pages_free if self.cache_mode == "paged" else 0

    def resident_bytes(self) -> int:
        """Analytic device residency of this engine: weights pytree + both
        KV pools (paged or contiguous) — the per-model attribution behind
        the ``resident_bytes_<model>`` gauge (docs/OBSERVABILITY.md §8)."""
        from dmlc_tpu.cluster.devicemon import pytree_nbytes

        return (
            pytree_nbytes(self._variables)
            + pytree_nbytes(self._k_state)
            + pytree_nbytes(self._v_state)
        )

    def jit_cache_sizes(self) -> dict[str, int]:
        """Compiled-entry counts for the two programs — the recompile-free
        invariant's measurement (must stay 1 apiece at any request mix)."""
        return {
            "step": self._step._cache_size(),
            "prefill": self._prefill._cache_size(),
        }

    def load_variables(self, variables: Any) -> None:
        """Hot-swap weights (the `train` verb's member side). Same shapes
        by construction (ModelLoader validated against the registry
        template), so the jit cache entries are reused, not recompiled."""
        import jax

        self._variables = jax.device_put(variables)

    def summary(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "model": self.model_name,
            "cache": self.cache_mode,
            "max_slots": self.max_slots,
            "slots_active": self.slots_active,
            "steps": self.steps,
            "tokens_out": self.tokens_out,
            "jit_entries": self.jit_cache_sizes(),
        }
        if self.cache_mode == "paged":
            out["pages"] = self.cache.allocator.summary()
        return out


def _sample(logits: Any, seeds: Any, positions: Any, temps: Any) -> Any:
    """Greedy at temperature <= 0, position-seeded categorical otherwise —
    per row. logits: [B, V] f32; seeds: [B] u32; positions: [B] i32 (the
    sequence position each row's token lands at); temps: [B] f32. The key
    ``fold_in(fold_in(PRNGKey(0), seed), position)`` depends only on the
    (seed, position) pair, never on batch composition — the property the
    migration token-identity guarantee rests on (module docstring)."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1)
    temp_safe = jnp.maximum(temps, 1e-6)[:, None]
    base = jax.random.PRNGKey(0)
    keys = jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.fold_in(base, s), p)
    )(seeds, positions)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row, axis=-1)
    )(keys, logits / temp_safe)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
