"""Member-side generation worker: the ``job.generate`` RPC surface.

Mirrors ``scheduler/worker.PredictWorker``'s shape — a backend per model,
an RPC method table wired into the member server — but the verb is
autoregressive, so one request produces MANY replies' worth of tokens. The
control-plane fabric is strict request/response (cluster/rpc.py), so
streaming rides a chunk-poll protocol (wire format: docs/GENERATE.md):

- ``job.generate``  {model, prompt:[int], max_new_tokens, temperature?,
  eos_id?, gen_id?, seed?, resume_tokens?} -> {gen_id}. Admission happens
  HERE (slot table + page pool, typed ``Overloaded`` on refusal) and the
  ambient deadline/trace context captured by the slot scheduler ride the
  whole generation. A caller-supplied ``gen_id`` makes the verb IDEMPOTENT:
  re-submitting a live id returns it without a second prefill — the
  property the router's migration retry (leader failover mid-migration)
  leans on for its ≤1-prefill-per-failure bound. ``seed`` keys the
  position-seeded sampling RNG and ``resume_tokens`` re-prefills an
  already-delivered prefix (scheduler/genrouter.py migration entry).
- ``job.generate_poll``  {gen_id, ack:int} -> {chunks: [[seq, [tok,..]],
  ...], done, error?}. Chunks are seq-numbered and retained until covered
  by the CUMULATIVE ack, so a retried poll (lost reply, client crash +
  resume) re-reads identical chunks and the client dedups by seq —
  exactly-once token delivery over an at-least-once fabric.
- ``job.generate_cancel`` {gen_id, reason?} -> {cancelled} releases the
  consumer's interest and cancels the stream cooperatively (the decode
  loop retires the slot between steps, never mid-step).

Sessions for which no poll arrives within ``session_ttl_s`` are swept (an
abandoned client must not pin chunks forever) — but never while the
backend is still stepping the stream or a migration handoff holds it: the
sweep compares the stream's ``step_gen`` against its last observation and
skips held streams, so an in-flight decode step or handoff cannot race a
reap. Every sweep/cancel is flight-recorded (``session_sweep`` with reason
``ttl``/``cancel``/``migrated``). ``generate_stream`` / ``generate`` are
the client helpers the CLI and tests drive.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections.abc import Callable, Iterable
from typing import TYPE_CHECKING, Any, Iterator

if TYPE_CHECKING:
    from dmlc_tpu.generate.slots import GenStream, SlotScheduler

from dmlc_tpu.cluster.rpc import RpcError
from dmlc_tpu.utils.tracing import traced_methods, tracer

log = logging.getLogger(__name__)


class GenerationBackend:
    """One servable LM: engine + slot scheduler, built lazily like
    EngineBackend (JAX import + compile are heavy; nodes that never see a
    generate request shouldn't pay)."""

    def __init__(
        self,
        model_name: str,
        *,
        max_slots: int = 8,
        page_size: int = 16,
        num_pages: int = 128,
        max_prefill: int = 64,
        max_waiting: int = 0,
        use_pallas: bool | None = None,
        metrics: Any = None,
        flight: Any = None,
        registry: Any = None,
        lane: Any = None,
        profile: Callable[[float], None] | None = None,
        device_work: Any = None,
        tenants: Any = None,
    ) -> None:
        self.model_name = model_name
        self.tenants = tenants
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_prefill = int(max_prefill)
        self.max_waiting = int(max_waiting)
        self.use_pallas = use_pallas
        self.metrics = metrics
        self.flight = flight
        self.registry = registry
        self.lane = lane
        self.profile = profile
        # Device-plane telemetry hook (cluster/devicemon.py): called with
        # (model, tokens, device_seconds) per decode step.
        self.device_work = device_work
        self._scheduler: SlotScheduler | None = None
        self._lock = threading.Lock()

    def warmup(self) -> None:
        """Build + compile now (node startup, before membership — same
        GIL-starvation rationale as EngineBackend.warmup)."""
        self._ensure()

    def _ensure(self) -> SlotScheduler:
        # One-time lazy init: requests arriving before the engine exists must
        # block on the single build, not double-build it (EngineBackend's
        # pattern).
        with self._lock:
            if self._scheduler is None:
                from dmlc_tpu.generate.engine import GenerationEngine
                from dmlc_tpu.generate.slots import SlotScheduler

                engine = GenerationEngine(
                    self.model_name,
                    max_slots=self.max_slots,
                    page_size=self.page_size,
                    num_pages=self.num_pages,
                    max_prefill=self.max_prefill,
                    use_pallas=self.use_pallas,
                    device_work=self.device_work,
                )
                self._scheduler = SlotScheduler(
                    engine,
                    max_waiting=self.max_waiting,
                    name=f"generate-{self.model_name}",
                    metrics=self.metrics,
                    flight=self.flight,
                    registry=self.registry,
                    lane=self.lane,
                    profile=self.profile,
                    tenants=self.tenants,
                )
            return self._scheduler

    def slot_limit(self) -> int:
        """Autoscaler read seam: the effective slot-table bound (configured
        width until the lazy engine builds)."""
        with self._lock:
            sched = self._scheduler
        return sched.max_active if sched is not None else self.max_slots

    def set_slot_limit(self, max_active: int) -> int:
        """Autoscaler apply seam: bound the live slot table. A backend that
        hasn't built yet just reports its configured width — there is no
        running decode batch to bound."""
        with self._lock:
            sched = self._scheduler
        if sched is None:
            return self.max_slots
        return int(sched.set_limits(max_active=max_active)["max_active"])

    def slots_resident(self) -> int:
        """Live decode slots right now — the autoscaler's drain seam:
        shrinking the slot limit below this would abandon streams
        mid-decode, so scale-down holds until residency fits."""
        with self._lock:
            sched = self._scheduler
        return int(sched.engine.slots_active) if sched is not None else 0

    def submit(self, prompt: Iterable[int], **kw: Any) -> GenStream:
        return self._ensure().submit(prompt, **kw)

    def load_variables(self, variables: Any) -> None:
        """`train`-verb hot-swap into the live engine."""
        self._ensure().engine.load_variables(variables)

    def summary(self) -> dict[str, Any]:
        with self._lock:
            sched = self._scheduler
        return sched.summary() if sched is not None else {"built": False}

    def stop(self, timeout_s: float = 5.0) -> None:
        with self._lock:
            sched = self._scheduler
        if sched is not None:
            sched.stop(timeout_s=timeout_s)


class _Session:
    __slots__ = ("stream", "last_poll", "step_gen")

    def __init__(self, stream: GenStream, now: float) -> None:
        self.stream = stream
        self.last_poll = now
        # Stream step generation at the last sweep observation: a stream
        # whose backend stepped since then is live regardless of polls.
        self.step_gen = 0


class GenerateWorker:
    """RPC surface over a dict of GenerationBackends."""

    def __init__(self, backends: dict[str, GenerationBackend], *,
                 session_ttl_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic,
                 flight: Any = None) -> None:
        self.backends = dict(backends)
        self.session_ttl_s = float(session_ttl_s)
        self.clock = clock
        self.flight = flight
        self._sessions: dict[str, _Session] = {}
        self._lock = threading.Lock()

    def methods(self) -> dict[str, Any]:
        return traced_methods({
            "job.generate": self._generate,
            "job.generate_poll": self._poll,
            "job.generate_cancel": self._cancel,
        })

    def _backend(self, model: str) -> GenerationBackend:
        backend = self.backends.get(model)
        if backend is None:
            raise RpcError(
                f"model {model!r} not served here; have {sorted(self.backends)}"
            )
        return backend

    def _generate(self, p: dict[str, Any]) -> dict[str, Any]:
        backend = self._backend(p["model"])
        gen_id = str(p.get("gen_id") or os.urandom(8).hex())
        with self._lock:
            if gen_id in self._sessions:
                # Idempotent re-submit (router retry across a leader
                # failover): the live session IS the answer; a second
                # prefill would fork the stream and double-bill the slots.
                return {"gen_id": gen_id, "model": p["model"],
                        "resumed": True}
        try:
            stream = backend.submit(
                [int(t) for t in p["prompt"]],
                max_new_tokens=int(p["max_new_tokens"]),
                temperature=float(p.get("temperature", 0.0)),
                eos_id=int(p["eos_id"]) if p.get("eos_id") is not None else None,
                request_id=gen_id,
                seed=int(p["seed"]) if p.get("seed") is not None else None,
                resume_tokens=p.get("resume_tokens"),
            )
        except ValueError as e:
            raise RpcError(str(e))
        now = self.clock()
        with self._lock:
            self._sweep_locked(now)
            if gen_id in self._sessions:
                dup = stream  # lost a concurrent duplicate-submit race
            else:
                self._sessions[gen_id] = _Session(stream, now)
                dup = None
        if dup is not None:
            dup.cancel()
            return {"gen_id": gen_id, "model": p["model"], "resumed": True}
        return {"gen_id": gen_id, "model": p["model"]}

    def _poll(self, p: dict[str, Any]) -> dict[str, Any]:
        gen_id = p["gen_id"]
        now = self.clock()
        with self._lock:
            session = self._sessions.get(gen_id)
            if session is None:
                raise RpcError(f"unknown generation {gen_id!r} (done+acked, "
                               "cancelled, or expired)")
            session.last_poll = now
        # The session is NOT popped on the final reply: if that reply is
        # lost, the client's retried poll must find the same idempotent
        # done-verdict, not "unknown generation". TTL sweep (and explicit
        # cancel) reap it instead.
        return session.stream.chunks_after(int(p.get("ack", 0)))

    def _cancel(self, p: dict[str, Any]) -> dict[str, Any]:
        reason = str(p.get("reason", "cancel"))
        with self._lock:
            session = self._sessions.pop(p["gen_id"], None)
        if session is not None:
            # Cooperative: the decode loop retires the slot between steps
            # (never mid-step), freeing its pages for the next admit — a
            # migrated-away session must not keep decoding dead tokens.
            session.stream.cancel()
            if self.flight is not None:
                self.flight.note("session_sweep", gen_id=p["gen_id"],
                                 reason=reason)
        return {"cancelled": session is not None}

    def _sweep_locked(self, now: float) -> None:
        for gid, s in list(self._sessions.items()):
            if now - s.last_poll <= self.session_ttl_s:
                continue
            stream = s.stream
            if stream.held():
                continue  # migration handoff mid-read: never reap under it
            gen = int(stream.step_gen)
            if not stream.done and gen != s.step_gen:
                # The backend stepped this stream since the last sweep
                # observation: it is live even with no polls arriving
                # (slow consumer, router mid-failover). Reap only once the
                # decode goes quiet too — the step-generation guard that
                # closes the sweep-vs-in-flight-step race.
                s.step_gen = gen
                continue
            self._sessions.pop(gid, None)
            stream.cancel()
            if self.flight is not None:
                self.flight.note("session_sweep", gen_id=gid, reason="ttl",
                                 idle_s=round(now - s.last_poll, 3))
            log.info("swept abandoned generation session %s", gid)

    def summary(self) -> dict[str, Any]:
        with self._lock:
            open_sessions = len(self._sessions)
        return {
            "open_sessions": open_sessions,
            "models": {name: b.summary() for name, b in self.backends.items()},
        }


# ---------------------------------------------------------------------------
# Client helpers (CLI / tests / tools)
# ---------------------------------------------------------------------------


def generate_stream(
    rpc: Any,
    addr: str,
    model: str,
    prompt: Iterable[int],
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    eos_id: int | None = None,
    seed: int | None = None,
    poll_timeout: float = 10.0,
    poll_interval_s: float = 0.0,
    sleep: Callable[[float], None] = time.sleep,
) -> Iterator[int]:
    """Submit and yield tokens as they stream. Exactly-once: chunks are
    dedup'd by seq and acked cumulatively, so a retried poll after a lost
    reply cannot duplicate or drop tokens. Raises the remote's typed error
    (Overloaded / DeadlineExceeded / RpcError) on failure. ``seed`` pins
    the sampling RNG (temperature > 0) to a reproducible sequence."""
    from dmlc_tpu.cluster.rpc import remote_error

    payload: dict[str, Any] = {
        "model": model, "prompt": [int(t) for t in prompt],
        "max_new_tokens": int(max_new_tokens),
        "temperature": float(temperature), "eos_id": eos_id,
    }
    if seed is not None:
        payload["seed"] = int(seed)
    with tracer.span("cli/generate", model=model):
        reply = rpc.call(addr, "job.generate", payload, timeout=poll_timeout)
        gen_id = reply["gen_id"]
        acked = 0
        while True:
            r = rpc.call(
                addr, "job.generate_poll", {"gen_id": gen_id, "ack": acked},
                timeout=poll_timeout,
            )
            advanced = False
            for seq, toks in sorted(r.get("chunks", [])):
                if seq <= acked:
                    continue  # replayed chunk from a retried poll
                acked = seq
                advanced = True
                for t in toks:
                    yield int(t)
            if r.get("done") and not r.get("chunks"):
                if r.get("error"):
                    raise remote_error(r["error"])
                return
            if not advanced and not r.get("done") and poll_interval_s > 0:
                sleep(poll_interval_s)


def generate(rpc: Any, addr: str, model: str, prompt: Iterable[int],
             **kw: Any) -> list[int]:
    """Blocking convenience: the full generated token list."""
    return list(generate_stream(rpc, addr, model, prompt, **kw))
