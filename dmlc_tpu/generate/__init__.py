"""Generation subsystem: continuous batching + paged KV cache LLM serving.

- ``kvcache``  — page pool, free-list allocator, per-slot page tables
- ``engine``   — jitted fixed-shape decode/prefill over the paged cache
- ``slots``    — step-level slot scheduler (join/leave between steps)
- ``worker``   — ``job.generate`` RPC surface + chunk-poll token streaming

See docs/GENERATE.md for the slot lifecycle, page layout, and wire format.
"""

from dmlc_tpu.generate.kvcache import PageAllocator, PagedKVCache, PagePoolExhausted

__all__ = ["PageAllocator", "PagedKVCache", "PagePoolExhausted"]
