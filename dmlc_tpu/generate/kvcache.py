"""Paged KV cache: fixed-size pages, a free-list allocator, per-slot tables.

The contiguous-cache alternative reserves max_len KV rows per slot up
front, so HBM cost is max_slots * max_len regardless of what is actually
cached — short requests strand most of it, and a long request cannot
borrow a short one's slack. Paging (vLLM's insight, specialized for TPU by
"Ragged Paged Attention", PAPERS.md) carves the pool into fixed-size pages
and binds them to slots on demand through an int32 page table, so capacity
is a FLEET of pages shared by whatever mix of requests is resident.

Layout (docs/GENERATE.md):

- ``k_pages`` / ``v_pages``: [num_layers, num_pages, page_size, H, Dh]
  device arrays. One page id spans EVERY layer — allocating a page grants
  page_size token positions in all layers at once, so there is one
  allocator and one table, not num_layers of each.
- **page 0 is the reserved scratch page**: never allocated, the write/read
  target for inactive batch rows (the decode step runs at a fixed batch
  shape; rows with no request must still index something). Garbage lands
  there and is never attended to.
- ``page_table``: int32 [max_slots, max_pages_per_slot], host-owned
  (NumPy) and shipped to the device per step — it is tiny, and host
  ownership keeps allocation pure Python with no device round trip.
  Released rows are reset to scratch so a stale table can never reach a
  recycled page.

The allocator is a plain LIFO free list under a lock: page exhaustion
raises the typed ``PagePoolExhausted``, which the slot scheduler converts
into a typed ``Overloaded`` shed at admission (docs/OVERLOAD.md) — the
pool being full is an overload condition, not an error.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

#: Page id 0 — the scratch page inactive rows point at; never allocated.
SCRATCH_PAGE = 0


class PagePoolExhausted(Exception):
    """No free pages: the caller must shed, evict, or retry later."""


class PageAllocator:
    """Free-list allocator over the page pool. Thread-safe; LIFO reuse so
    a just-released page is the next one handed out — which is exactly
    what the cross-slot-contamination tests want to stress."""

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved scratch)")
        if page_size <= 0:
            raise ValueError("page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # Ascending pop order (list.pop() takes the tail) keeps allocation
        # deterministic for the seeded tests.
        self._free = list(range(self.num_pages - 1, SCRATCH_PAGE, -1))
        self._held: set[int] = set()
        self.allocs = 0
        self.frees = 0
        self.exhaustions = 0

    @property
    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def pages_total(self) -> int:
        return self.num_pages - 1  # scratch excluded

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` cache positions."""
        return -(-max(0, int(n_tokens)) // self.page_size)

    def alloc(self, n: int) -> list[int]:
        """Take ``n`` pages or none: a partial grant would leave the caller
        holding pages it must immediately free under the same contention."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            if n > len(self._free):
                self.exhaustions += 1
                raise PagePoolExhausted(
                    f"need {n} page(s), {len(self._free)} free "
                    f"of {self.pages_total}"
                )
            pages = [self._free.pop() for _ in range(n)]
            self._held.update(pages)
            self.allocs += n
            return pages

    def free(self, pages: list[int]) -> None:
        with self._lock:
            for p in pages:
                p = int(p)
                if p == SCRATCH_PAGE:
                    raise ValueError("page 0 is the reserved scratch page")
                if p not in self._held:
                    raise ValueError(f"double free (or foreign page): {p}")
                self._held.discard(p)
                self._free.append(p)
                self.frees += 1

    def summary(self) -> dict[str, int]:
        with self._lock:
            return {
                "pages_total": self.pages_total,
                "pages_free": len(self._free),
                "pages_held": len(self._held),
                "allocs": self.allocs,
                "frees": self.frees,
                "exhaustions": self.exhaustions,
            }


class PagedKVCache:
    """Device page pools + the host-side slot table over one allocator.

    Construction is the expensive part (it allocates the whole pool in
    device memory) and happens ONCE per engine — never per request or per
    step; lint rule H1 flags per-hot-path construction of this class the
    same way it flags per-call thread pools.
    """

    def __init__(
        self,
        *,
        num_layers: int,
        num_pages: int,
        page_size: int,
        num_heads: int,
        head_dim: int,
        max_slots: int,
        max_pages_per_slot: int,
        dtype: Any = None,
    ) -> None:
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.dtype = dtype if dtype is not None else jnp.float32
        self.allocator = PageAllocator(num_pages, page_size)
        shape = (num_layers, num_pages, page_size, num_heads, head_dim)
        # The pools live on the engine's device; the jitted step donates
        # and replaces them every call, so exactly one generation of the
        # pool exists at a time.
        self.k_pages = jnp.zeros(shape, self.dtype)
        self.v_pages = jnp.zeros(shape, self.dtype)
        # Host-owned table/lengths; rows default to the scratch page.
        self.page_table = np.full(
            (self.max_slots, self.max_pages_per_slot), SCRATCH_PAGE, np.int32
        )
        self.lengths = np.zeros(self.max_slots, np.int32)
        self._slot_pages: dict[int, list[int]] = {}

    # ---- slot binding ---------------------------------------------------

    @property
    def max_tokens_per_slot(self) -> int:
        return self.max_pages_per_slot * self.page_size

    def bind(self, slot: int, pages: list[int]) -> None:
        """Install an allocated page run as ``slot``'s table row (pages come
        from ``allocator.alloc``, usually via a submit-time reservation)."""
        if slot in self._slot_pages:
            raise ValueError(f"slot {slot} already bound")
        if len(pages) > self.max_pages_per_slot:
            raise ValueError(
                f"{len(pages)} pages exceed max_pages_per_slot="
                f"{self.max_pages_per_slot}"
            )
        self._slot_pages[slot] = list(pages)
        self.page_table[slot, :] = SCRATCH_PAGE
        self.page_table[slot, : len(pages)] = pages
        self.lengths[slot] = 0

    def grow(self, slot: int) -> None:
        """Add one page to ``slot`` (decode crossed a page boundary).
        Raises PagePoolExhausted without disturbing the slot's state."""
        pages = self._slot_pages[slot]
        if len(pages) >= self.max_pages_per_slot:
            raise PagePoolExhausted(
                f"slot {slot} at max_pages_per_slot={self.max_pages_per_slot}"
            )
        (page,) = self.allocator.alloc(1)
        pages.append(page)
        self.page_table[slot, len(pages) - 1] = page
        self.pages_needed_hw = max(getattr(self, "pages_needed_hw", 0), len(pages))

    def capacity_ok(self, slot: int, next_len: int) -> bool:
        """True when the slot's bound pages already cover ``next_len``
        cache positions (no grow needed before the next step)."""
        return len(self._slot_pages[slot]) * self.page_size >= next_len

    def release(self, slot: int) -> list[int]:
        """Recycle the slot's pages into the free list and reset its table
        row to scratch. Returns the freed page ids (tests assert reuse)."""
        pages = self._slot_pages.pop(slot, [])
        if pages:
            self.allocator.free(pages)
        self.page_table[slot, :] = SCRATCH_PAGE
        self.lengths[slot] = 0
        return pages

    def slot_pages(self, slot: int) -> list[int]:
        return list(self._slot_pages.get(slot, []))

    @property
    def pages_free(self) -> int:
        return self.allocator.pages_free
