"""Step-level slot scheduler: continuous batching over the generation engine.

``scheduler/worker.DynamicBatcher`` coalesces ONE-SHOT predict requests into
a batch and disbands it after a single device dispatch. Generation needs the
Orca-style evolution of that idea: the batch is PERSISTENT (one jitted
decode step ticking at a fixed shape) and requests are SLOTS that join and
leave it between steps — a 5-token reply exits after 5 steps while a
500-token neighbor keeps its slot, and the freed slot (plus its recycled KV
pages) admits the next waiting request immediately. Throughput scales with
resident slots at roughly constant step cost, which is the 2x-over-
sequential pin in tests/test_generate_cluster.py.

Admission follows the predict path's overload contract (docs/OVERLOAD.md):

- submit-time shed — no free slot (and the bounded wait queue full) or not
  enough free pages for the prompt+1 reservation raises a typed
  ``Overloaded`` with a retry-after hint; nothing buffers toward a
  guaranteed deadline miss. Flight-recorder ``shed`` events mark each.
- deadline-carrying — a request captures the ambient RPC deadline
  (cluster/deadline.py) at submit; the decode loop exits expired slots
  with a ``deadline:``-typed error between steps, never mid-step.
- mid-decode eviction — a slot whose next token needs a page the pool
  cannot grant is EVICTED with a typed ``Overloaded`` error (flight
  ``slot_evict``): admission only reserved its prompt, so a full pool is
  the overload signal arriving late, and the evicted client retries
  against the retry-after hint like any shed.

Tokens stream out through per-request ``GenStream``s: seq-numbered chunks
retained until the consumer's cumulative ack — the exactly-once delivery
substrate the RPC worker (generate/worker.py) exposes as
``job.generate_poll`` (wire format: docs/GENERATE.md).

Tracing: every decode step runs under a ``gen/step`` span bound to the
OLDEST resident slot's submit-time trace context, so a request's timeline
shows the steps that produced its tokens parented under its
``rpc/job.generate`` span (trace smoke asserts this); ``gen/prefill`` spans
bind the joining request's own context.
"""

from __future__ import annotations

import logging
import os
import threading
from collections.abc import Callable, Iterable
from time import monotonic
from typing import Any, NoReturn

from dmlc_tpu.cluster import deadline as deadline_mod
from dmlc_tpu.cluster import tenant as tenant_mod
from dmlc_tpu.cluster import tracectx
from dmlc_tpu.cluster.rpc import Overloaded
from dmlc_tpu.generate.kvcache import PagePoolExhausted
from dmlc_tpu.utils import tracing
from dmlc_tpu.utils.metrics import LatencyStats
from dmlc_tpu.utils.tracing import tracer

log = logging.getLogger(__name__)


class GenStream:
    """One request's token stream with exactly-once chunk delivery.

    Producer side (the decode loop): ``push`` appends tokens; ``finish``
    seals the stream (optionally with a typed error string). Consumer side:
    ``chunks_after(ack)`` returns every chunk with seq > ack — chunks are
    retained until covered by a later cumulative ack, so a lost/retried
    poll re-reads the same chunks and the consumer dedups by seq.
    ``tokens()``/``wait`` serve in-process consumers (CLI, tests).

    Lifecycle hooks for the session plane (generate/worker.py,
    scheduler/genrouter.py): ``cancel`` requests a cooperative exit — the
    decode loop retires the slot between steps with a ``cancelled:`` error;
    ``hold``/``unhold`` pin the stream against the worker's TTL sweep while
    a migration handoff is reading it; ``step_gen`` is the engine step
    count at the last delivered token, the sweep's liveness witness."""

    def __init__(self, request_id: str) -> None:
        self.request_id = request_id
        self._cv = threading.Condition()
        self._chunks: list[tuple[int, list[int]]] = []
        self._next_seq = 1
        self._all: list[int] = []
        self.done = False
        self.error: str | None = None
        self.acked = 0
        self.cancelled = False
        self.step_gen = 0
        self._holds = 0

    # ---- producer --------------------------------------------------------

    def push(self, tokens: list[int]) -> None:
        if not tokens:
            return
        with self._cv:
            if self.done:
                raise RuntimeError("stream already finished")
            self._chunks.append((self._next_seq, [int(t) for t in tokens]))
            self._next_seq += 1
            self._all.extend(int(t) for t in tokens)
            self._cv.notify_all()

    def finish(self, error: str | None = None) -> None:
        with self._cv:
            if self.done:
                return
            self.done = True
            self.error = error
            self._cv.notify_all()

    # ---- session-plane hooks --------------------------------------------

    def cancel(self) -> None:
        """Request a cooperative exit: the decode loop retires the slot
        between steps (never mid-step). Idempotent; a finished stream is
        left as-is."""
        with self._cv:
            self.cancelled = True
            self._cv.notify_all()

    def hold(self) -> None:
        with self._cv:
            self._holds += 1

    def unhold(self) -> None:
        with self._cv:
            self._holds = max(0, self._holds - 1)

    def held(self) -> bool:
        with self._cv:
            return self._holds > 0

    # ---- consumer --------------------------------------------------------

    def chunks_after(self, ack: int) -> dict[str, Any]:
        """The poll reply body: unacked chunks + completion state. ``ack``
        is cumulative — chunks with seq <= ack are dropped for good."""
        with self._cv:
            if ack > self.acked:
                self.acked = int(ack)
                self._chunks = [c for c in self._chunks if c[0] > self.acked]
            return {
                "chunks": [[seq, list(toks)] for seq, toks in self._chunks],
                "done": self.done,
                "error": self.error,
            }

    def drained(self) -> bool:
        """Finished AND every chunk acked — safe to garbage-collect."""
        with self._cv:
            return self.done and not self._chunks

    def wait(self, timeout: float | None = None) -> bool:
        with self._cv:
            self._cv.wait_for(lambda: self.done, timeout=timeout)
            return self.done

    def tokens(self) -> list[int]:
        with self._cv:
            return list(self._all)

    def result(self, timeout: float | None = None) -> list[int]:
        """Block until done; raise the stream's typed error if it failed."""
        if not self.wait(timeout):
            raise TimeoutError(f"generation {self.request_id} still running")
        with self._cv:
            if self.error is not None:
                from dmlc_tpu.cluster.rpc import remote_error

                raise remote_error(self.error)
            return list(self._all)


class _Slot:
    """Host-side request state riding one engine slot."""

    __slots__ = (
        "stream", "prompt", "max_new_tokens", "temperature", "eos_id",
        "deadline", "trace_ctx", "pages", "emitted", "slot", "submitted_t",
        "tenant", "seed",
    )

    def __init__(self, stream: GenStream, prompt: list[int],
                 max_new_tokens: int, temperature: float, eos_id: int | None,
                 deadline: Any, trace_ctx: Any, pages: list[int],
                 submitted_t: float, tenant: str,
                 seed: int | None = None) -> None:
        self.stream = stream
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos_id = eos_id
        self.deadline = deadline
        self.trace_ctx = trace_ctx
        self.pages = pages
        self.emitted = 0
        self.slot = -1
        self.submitted_t = submitted_t
        self.tenant = tenant
        self.seed = seed


class SlotScheduler:
    """Continuous-batching loop: admit between steps, step while anyone is
    resident, shed at the door when the slot table / page pool is full."""

    def __init__(
        self,
        engine: Any,
        *,
        max_waiting: int = 0,
        name: str = "generate",
        metrics: Any = None,
        flight: Any = None,
        registry: Any = None,
        retry_after_s: float = 0.25,
        clock: Callable[[], float] = monotonic,
        autostart: bool = True,
        lane: Any = None,
        profile: Callable[[float], None] | None = None,
        tenants: Any = None,
    ) -> None:
        self.engine = engine
        self.name = name
        self.metrics = metrics
        self.flight = flight
        self.retry_after_s = float(retry_after_s)
        self.clock = clock
        # Cost-profile feed (cluster/profile.py): called with each decode
        # step's wall seconds so the node's profiler grows a gen/step lane.
        self.profile = profile
        # Node identity for span attribution (utils/tracing.lane): the
        # decode thread does not inherit the RPC server's ambient lane, so
        # it binds its own. A callable defers resolution to thread start
        # (the node's lane can still change while ports resolve).
        self.lane = lane
        # Bounded join queue beyond the slot table itself: 0 = no waiting,
        # a submit either takes a slot-table place or sheds.
        self.max_waiting = max(0, int(max_waiting))
        # Per-tenant quotas over the in-flight bound (cluster/tenant.py):
        # a tenant's share of (slot table + wait queue), enforced at
        # submit; eviction ordering below prefers low-priority-and-over-
        # quota residents. No tenants declared = legacy behavior.
        self.ledger = tenant_mod.TenantLedger(
            tenants, int(engine.max_slots) + self.max_waiting
        )
        # Autoscaler-adjustable soft bounds (scheduler/autoscaler.py):
        # max_active caps ADMITTED slots at <= the compiled slot table;
        # page_budget caps pages-in-use at <= the allocated pool (0 = the
        # pool itself). Both resize live — the compiled step shape and the
        # HBM pool never change, only how much of them admission hands out.
        self.max_active = int(engine.max_slots)
        self.page_budget = 0
        self._page_total = int(getattr(engine, "pages_free", 0))
        self._cv = threading.Condition()
        self._pending: list[_Slot] = []
        self._closed = False
        # Owned exclusively by the decode thread after admission.
        self._resident: list[_Slot] = []
        self.requests = 0
        self.sheds = 0
        self.evictions = 0
        self.completions = 0
        self.step_stats = LatencyStats()
        self.tokens_streamed = 0
        self._t_first_token: float | None = None
        self._t_last_token: float | None = None
        if registry is not None:
            registry.gauge(f"{name}_slots_active", lambda: self.engine.slots_active)
            registry.gauge(f"{name}_pages_free", lambda: self.engine.pages_free)
            registry.gauge(f"{name}_tok_s", self.tok_s)
        self._thread = threading.Thread(
            target=self._loop, name=f"gen-{name}", daemon=True
        )
        # ``autostart=False`` defers the decode thread so a test can stage
        # several submissions and observe a DETERMINISTIC admission order;
        # production always autostarts.
        if autostart:
            self._thread.start()

    def start(self) -> None:
        if not self._thread.is_alive():
            self._thread.start()

    # ---- request side ----------------------------------------------------

    def submit(
        self,
        prompt: Iterable[int],
        *,
        max_new_tokens: int,
        temperature: float = 0.0,
        eos_id: int | None = None,
        request_id: str | None = None,
        deadline: Any = None,
        seed: int | None = None,
        resume_tokens: Iterable[int] | None = None,
    ) -> GenStream:
        """Admit one generation request; returns its stream immediately.
        Sheds with a typed ``Overloaded`` when the slot table (plus the
        bounded wait queue) or the page pool cannot take it. Captures the
        ambient RPC deadline and trace context (the decode loop carries
        both forward).

        ``seed`` keys the engine's position-seeded sampling RNG.
        ``resume_tokens`` is the migration entry (docs/GENERATE.md
        §Migration): tokens already delivered to the client elsewhere are
        prefilled along with the prompt (same seed → the continuation is
        token-identical to the uninterrupted run), and the stream emits
        only the ``max_new_tokens`` NEW tokens from the resume point on."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if resume_tokens is not None:
            prompt = prompt + [int(t) for t in resume_tokens]
        if len(prompt) > self.engine.max_prefill:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds max_prefill="
                f"{self.engine.max_prefill}"
            )
        total = len(prompt) + int(max_new_tokens)
        if total > self.engine.max_tokens:
            raise ValueError(
                f"prompt+max_new_tokens={total} exceeds the engine's "
                f"max_tokens={self.engine.max_tokens}"
            )
        if deadline is None:
            deadline = deadline_mod.current()
        tenant = tenant_mod.current()
        stream = GenStream(request_id or os.urandom(6).hex())
        with self._cv:
            if self._closed:
                raise RuntimeError("slot scheduler is stopped")
            if self.ledger.would_exceed(tenant):
                self._shed(
                    f"tenant {tenant!r} at quota "
                    f"({self.ledger.active(tenant)}/{self.ledger.quota(tenant)})",
                    tenant=tenant, verdict="over_quota",
                )
            in_flight = len(self._resident) + len(self._pending)
            limit = min(int(self.engine.max_slots), self.max_active) + self.max_waiting
            if in_flight >= limit:
                self._shed(f"slot table full ({in_flight} in flight)",
                           tenant=tenant)
            if self.page_budget > 0 and \
                    self._page_total - self.engine.pages_free >= self.page_budget:
                self._shed(
                    f"page budget exhausted "
                    f"({self._page_total - self.engine.pages_free}/"
                    f"{self.page_budget} pages in use)",
                    tenant=tenant,
                )
            try:
                pages = self.engine.reserve(len(prompt))
            except PagePoolExhausted as e:
                self._shed(f"page pool exhausted: {e}", tenant=tenant)
            self.requests += 1
            if self.metrics is not None:
                self.metrics.inc("gen_requests")
            slot = _Slot(
                stream, prompt, int(max_new_tokens), float(temperature),
                eos_id, deadline, tracectx.current(), pages, self.clock(),
                tenant, seed,
            )
            self._pending.append(slot)
            self.ledger.acquire(tenant)
            self._cv.notify_all()
        return stream

    def _shed(self, why: str, tenant: str | None = None,
              verdict: str = "gate_full") -> NoReturn:
        self.sheds += 1
        if tenant is not None:
            self.ledger.note_shed(tenant)
        if self.metrics is not None:
            self.metrics.inc("shed")
            self.metrics.inc(f"shed_{self.name}")
            if verdict == "over_quota":
                self.metrics.inc(f"shed_over_quota_{self.name}")
        tracer.record(f"overload/shed_{self.name}", 0.0)
        if self.flight is not None:
            self.flight.note("shed", gate=self.name,
                             active=len(self._resident), tenant=tenant,
                             quota=verdict)
        raise Overloaded(f"{self.name}: {why}",
                         retry_after_s=self.retry_after_s,
                         tenant=tenant, quota=verdict)

    def set_limits(self, max_active: int | None = None,
                   page_budget: int | None = None) -> dict[str, int]:
        """Autoscaler actuation seam: resize the admitted share of the
        slot table / page pool. Clamped to the compiled/allocated sizes —
        the engine itself never reshapes. Returns the effective limits."""
        with self._cv:
            if max_active is not None:
                self.max_active = max(1, min(int(max_active),
                                             int(self.engine.max_slots)))
            if page_budget is not None:
                pb = int(page_budget)
                if pb <= 0 or (self._page_total and pb >= self._page_total):
                    self.page_budget = 0
                else:
                    self.page_budget = max(1, pb)
            return {"max_active": self.max_active,
                    "page_budget": self.page_budget}

    # ---- decode loop -----------------------------------------------------

    def _loop(self) -> None:
        lane_name = self.lane() if callable(self.lane) else self.lane
        with tracing.lane(lane_name):
            self._loop_body()

    def _loop_body(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._resident and not self._closed:
                    self._cv.wait()
                if self._closed:
                    drained = self._pending
                    self._pending = []
                else:
                    drained = None
            if drained is not None:
                for s in drained:
                    self.engine.release_reservation(s.pages)
                    self._ledger_release(s)
                    s.stream.finish("overloaded: scheduler stopped")
                for s in self._resident:
                    self.engine.release(s.slot)
                    self._ledger_release(s)
                    s.stream.finish("overloaded: scheduler stopped")
                self._resident = []
                return
            try:
                self._admit_pending()
                self._retire_and_step()
            except Exception:
                # A crashed decode loop must fail every resident request
                # visibly, not hang their streams forever.
                log.exception("decode loop error; failing resident slots")
                for s in self._resident:
                    try:
                        self.engine.release(s.slot)
                    except Exception:  # dmlc-lint: disable=E1 -- best-effort cleanup mid-failure; the stream error below is the observable verdict
                        pass
                    s.stream.finish("RpcError: generation engine failed")
                self._resident = []

    def _admit_pending(self) -> None:
        """Move waiting requests into free engine slots (between steps).

        The head request stays IN ``_pending`` until it lands in
        ``_resident``: submit-time admission counts both lists, and a
        request invisible to that count during its prefill would let a
        third request slip past a full slot table."""
        while True:
            free = self.engine.free_slots()
            with self._cv:
                if not self._pending or not free:
                    return
                req = self._pending[0]
            if req.deadline is not None and req.deadline.expired():
                # Expired while waiting: a prefill now would be dead work.
                self._unpend(req)
                self.engine.release_reservation(req.pages)
                self._ledger_release(req)
                req.stream.finish("deadline: expired before a slot freed")
                continue
            if req.stream.cancelled:
                # Cancelled while waiting (router migrated it away, or the
                # client gave up): a prefill now would be dead work.
                self._unpend(req)
                self.engine.release_reservation(req.pages)
                self._ledger_release(req)
                req.stream.finish("cancelled: before a slot freed")
                continue
            req.slot = free[0]
            try:
                with tracectx.bind(req.trace_ctx):
                    with tracer.span("gen/prefill", slot=req.slot,
                                     prompt=len(req.prompt)):
                        first = self.engine.join(
                            req.slot, req.prompt,
                            temperature=req.temperature, pages=req.pages,
                            seed=req.seed,
                        )
            except Exception as e:
                # A bad request (or a prefill failure) fails ITS stream,
                # never the resident batch. Pages go back wherever they
                # are: bound to the slot (join got past bind) or still the
                # submit-time reservation.
                log.exception("prefill failed for %s", req.stream.request_id)
                self._unpend(req)
                if (self.engine.cache_mode == "paged"
                        and not self.engine.cache.slot_pages(req.slot)):
                    self.engine.release_reservation(req.pages)
                self.engine.release(req.slot)
                self._ledger_release(req)
                req.stream.finish(f"{type(e).__name__}: {e}")
                continue
            req.pages = []  # ownership moved to the cache's slot binding
            with self._cv:
                self._pending.remove(req)
                self._resident.append(req)
            if self.flight is not None:
                # ``step`` stamps WHEN in the batch's life the slot joined:
                # admits at step > 0 are the continuous-batching evidence
                # (a request entered a batch already mid-decode).
                self.flight.note(
                    "slot_admit", slot=req.slot, prompt=len(req.prompt),
                    step=self.engine.steps, request=req.stream.request_id,
                    pages=len(self.engine.cache.slot_pages(req.slot))
                    if self.engine.cache_mode == "paged" else 0,
                )
            self._deliver(req, first)
            if req.eos_id is not None and first == req.eos_id:
                self._exit(req, "eos")

    def _unpend(self, req: _Slot) -> None:
        with self._cv:
            if req in self._pending:
                self._pending.remove(req)

    def _ledger_release(self, req: _Slot) -> None:
        with self._cv:
            self.ledger.release(req.tenant)

    def _eviction_victim(self, req: _Slot) -> _Slot:
        """Eviction ordering (docs/OVERLOAD.md §Priority classes): when
        ``req`` needs a page the pool cannot grant, the slot that dies is
        the newest LOW-PRIORITY-AND-OVER-QUOTA resident — the workload
        holding more than its share pays for the pressure it created.
        With no such victim (everyone within quota, or ``req`` itself is
        the over-quota low-priority one) the requester is evicted, as
        before: within-quota work of another tenant is NEVER the victim."""
        with self._cv:
            spec = self.ledger.spec(req.tenant)
            if spec.high_priority and not self.ledger.over_quota(req.tenant):
                for other in reversed(self._resident):
                    if other is req:
                        continue
                    if self.ledger.over_quota(other.tenant) and \
                            not self.ledger.spec(other.tenant).high_priority:
                        return other
            return req

    def _evict(self, victim: _Slot, why: Exception) -> None:
        self.evictions += 1
        if self.metrics is not None:
            self.metrics.inc("gen_evictions")
        if self.flight is not None:
            self.flight.note("slot_evict", slot=victim.slot,
                             emitted=victim.emitted, tenant=victim.tenant)
        self._exit(victim, "evicted",
                   error=f"overloaded: evicted mid-decode ({why})",
                   counted=False)

    def _retire_and_step(self) -> None:
        # Between-step housekeeping: expired deadlines out, page growth
        # secured, THEN one fixed-shape step for whoever remains.
        for req in list(self._resident):
            if req not in self._resident:
                continue  # already evicted as another slot's page victim
            if req.stream.cancelled:
                self._exit(req, "cancel",
                           error="cancelled: stream cancelled",
                           counted=False)
                continue
            if req.deadline is not None and req.deadline.expired():
                self._exit(req, "deadline",
                           error="deadline: generation exceeded its budget")
                continue
            if req.emitted >= req.max_new_tokens:
                self._exit(req, "max_tokens")
                continue
            try:
                self.engine.ensure_capacity(req.slot)
            except PagePoolExhausted as e:
                victim = self._eviction_victim(req)
                self._evict(victim, e)
                if victim is not req:
                    # The freed pages may now cover the requester; if the
                    # pool STILL cannot grant, the requester exits too.
                    try:
                        self.engine.ensure_capacity(req.slot)
                    except PagePoolExhausted as e2:
                        self._evict(req, e2)
        if not self._resident:
            return
        oldest = min(self._resident, key=lambda r: r.submitted_t)
        t0 = self.clock()
        with tracectx.bind(oldest.trace_ctx):
            with tracer.span("gen/step", slots=len(self._resident)):
                tokens = self.engine.step()
        elapsed = max(0.0, self.clock() - t0)
        self.step_stats.record(elapsed)
        if self.profile is not None:
            self.profile(elapsed)
        for req in list(self._resident):
            tok = int(tokens[req.slot])
            self._deliver(req, tok)
            if req.eos_id is not None and tok == req.eos_id:
                self._exit(req, "eos")

    def _deliver(self, req: _Slot, token: int) -> None:
        req.emitted += 1
        req.stream.step_gen = self.engine.steps
        req.stream.push([token])
        self.tokens_streamed += 1
        if self.metrics is not None:
            self.metrics.inc("gen_tokens")
        now = self.clock()
        if self._t_first_token is None:
            self._t_first_token = now
        self._t_last_token = now

    def _exit(self, req: _Slot, reason: str, error: str | None = None,
              counted: bool = True) -> None:
        freed = self.engine.release(req.slot)
        with self._cv:  # submit reads len(_resident) for admission
            self._resident.remove(req)
            self.ledger.release(req.tenant)
        if counted:
            self.completions += 1
        if self.flight is not None:
            self.flight.note("slot_exit", slot=req.slot, reason=reason,
                             step=self.engine.steps, emitted=req.emitted,
                             pages_freed=len(freed))
        req.stream.finish(error)

    # ---- observability / lifecycle ---------------------------------------

    def tok_s(self) -> float:
        """Streamed-token rate over the window tokens actually flowed."""
        if self._t_first_token is None or self._t_last_token is None:
            return 0.0
        dt = self._t_last_token - self._t_first_token
        if dt <= 0:
            return 0.0
        return self.tokens_streamed / dt

    def summary(self) -> dict[str, Any]:
        with self._cv:
            tenants = self.ledger.summary()
        return {
            "requests": self.requests,
            "sheds": self.sheds,
            "evictions": self.evictions,
            "completions": self.completions,
            "tokens_streamed": self.tokens_streamed,
            "tok_s": round(self.tok_s(), 2),
            "slots_active": self.engine.slots_active,
            "pages_free": self.engine.pages_free,
            "max_active": self.max_active,
            "page_budget": self.page_budget,
            **({"tenants": tenants} if tenants else {}),
            "steps": self.engine.steps,
            "step_ms_p50": round(self.step_stats.percentile(50) * 1e3, 3)
            if len(self.step_stats) else None,
            "step_ms_p99": round(self.step_stats.percentile(99) * 1e3, 3)
            if len(self.step_stats) else None,
        }

    def stop(self, timeout_s: float = 10.0) -> None:
        """Fail-fast shutdown: waiting and resident requests finish with a
        typed error (node stop must be bounded, not generation-length)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=timeout_s)
