"""ResNet-18 / ResNet-50 in Flax, TPU-first.

Replaces the reference's ``tch::vision::resnet`` graph + ``.ot`` VarStore load
(reference: src/services.rs:513-518) with a JAX/Flax definition that XLA can
tile onto the MXU: NHWC layout (TPU-native conv layout), bf16 compute with
fp32 params/batch-stats, static shapes, no data-dependent control flow.

Structure follows the standard torchvision ResNet-v1 topology (BasicBlock for
18, Bottleneck for 50) so that weights are interchangeable with common
checkpoints; the implementation is written from scratch.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides), padding=[(1, 1), (1, 1)])(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), (self.strides, self.strides), name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """1x1 reduce → 3x3 → 1x1 expand(4x) residual block (ResNet-50/101/152)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides), padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), (self.strides, self.strides), name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet v1. Input NHWC float images; output [N, num_classes] logits."""

    stage_sizes: Sequence[int]
    block_cls: Callable[..., nn.Module]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype, param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def resnet18(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock, num_classes=num_classes, dtype=dtype)


def resnet34(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock, num_classes=num_classes, dtype=dtype)


def resnet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck, num_classes=num_classes, dtype=dtype)
