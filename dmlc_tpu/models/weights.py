"""Pretrained-weight distribution: serialize, validate, publish, import.

The reference's entire ML story is pretrained weights: ``.ot`` VarStore files
loaded at member startup (src/services.rs:513-524) and re-broadcast by the
`train` verb (src/services.rs:139-144, README.md:21). Here the equivalent
pipeline is:

1. import an external checkpoint into our Flax layout
   (``import_external`` -> models/convert.py per family),
2. ``weights_to_bytes`` -> one self-describing blob (magic + model name +
   flax msgpack),
3. ``sdfs put`` the blob as ``models/{model_name}`` (versioned, replicated),
4. the `train` verb fans the blob to every member, whose ModelLoader
   (scheduler/worker.py) deserializes and hot-swaps it into the running
   InferenceEngine — predictions change without a restart.

Every deserialized tree is validated against the registry model's abstract
init (structure + shapes) before it can reach an engine, so a corrupt or
mismatched blob fails at load, not mid-forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from dmlc_tpu.models import convert
from dmlc_tpu.models.registry import get_model

MAGIC = b"DMLCWTS1"


def not_published(err: Exception) -> bool:
    """True when an SDFS error means the blob was never published (vs a
    corrupt blob or transient replica failure, which callers must surface).
    The one place the leader's not-found message text is interpreted —
    RPC errors travel as message strings, so cli.py and worker.py share
    this predicate instead of each matching the magic substring."""
    return "not in SDFS" in str(err)


def sdfs_weights_name(model_name: str) -> str:
    """Canonical SDFS name for a model's weights blob (the `train` payload)."""
    return f"models/{model_name}"


@functools.lru_cache(maxsize=None)
def variables_template(model_name: str):
    """Abstract (ShapeDtypeStruct) variables tree for a registry model —
    no compilation, and cached: every model.load RPC validates against it."""
    spec = get_model(model_name)
    model = spec.module(dtype=jnp.float32)
    if spec.kind == "lm":
        dummy_tokens = jnp.zeros((1, 8), jnp.int32)
        return jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), dummy_tokens))
    dummy = jnp.zeros((1, spec.input_size, spec.input_size, 3), jnp.float32)
    return jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), dummy, train=False))


def check_variables(model_name: str, variables) -> None:
    """Raise ValueError unless ``variables`` matches the model's tree
    structure and leaf shapes."""
    template = variables_template(model_name)
    t_paths = jax.tree_util.tree_flatten_with_path(template)[0]
    v_paths = jax.tree_util.tree_flatten_with_path(variables)[0]
    t_map = {jax.tree_util.keystr(p): leaf.shape for p, leaf in t_paths}
    v_map = {jax.tree_util.keystr(p): np.shape(leaf) for p, leaf in v_paths}
    if t_map.keys() != v_map.keys():
        missing = sorted(t_map.keys() - v_map.keys())[:3]
        extra = sorted(v_map.keys() - t_map.keys())[:3]
        raise ValueError(
            f"variables tree mismatch for {model_name!r}: missing={missing} extra={extra}"
        )
    for key, shape in t_map.items():
        if tuple(v_map[key]) != tuple(shape):
            raise ValueError(
                f"shape mismatch for {model_name!r} at {key}: "
                f"got {tuple(v_map[key])}, want {tuple(shape)}"
            )


def weights_to_bytes(model_name: str, variables) -> bytes:
    """Serialize a validated variables tree into the distribution blob."""
    check_variables(model_name, variables)
    name_b = model_name.encode()
    payload = serialization.msgpack_serialize(
        jax.tree_util.tree_map(np.asarray, variables)
    )
    return MAGIC + len(name_b).to_bytes(2, "big") + name_b + payload


def weights_from_bytes(data: bytes, expect_model: str | None = None):
    """-> (model_name, variables), validated against the registry model."""
    if data[: len(MAGIC)] != MAGIC:
        raise ValueError("not a dmlc weights blob (bad magic)")
    off = len(MAGIC)
    n = int.from_bytes(data[off : off + 2], "big")
    model_name = data[off + 2 : off + 2 + n].decode()
    if expect_model is not None and model_name != expect_model:
        raise ValueError(f"weights are for {model_name!r}, expected {expect_model!r}")
    variables = serialization.msgpack_restore(data[off + 2 + n :])
    check_variables(model_name, variables)
    return model_name, variables


def publish_weights(sdfs_client, model_name: str, variables) -> int:
    """Put a new weights version into SDFS; returns the version number."""
    blob = weights_to_bytes(model_name, variables)
    return sdfs_client.put_bytes(blob, sdfs_weights_name(model_name))["version"]


# ---------------------------------------------------------------------------
# External checkpoint import (dispatch over models/convert.py)
# ---------------------------------------------------------------------------

_RESNET_STAGES = {
    "resnet18": ([2, 2, 2, 2], False),
    "resnet34": ([3, 4, 6, 3], False),
    "resnet50": ([3, 4, 6, 3], True),
}
_VIT_LAYERS = {"vit_b16": 12, "vit_l14": 24}
_CLIP_LAYERS = {"clip_vit_l14": 24, "clip_vit_b32": 12}


def import_external(model_name: str, state_dict) -> dict:
    """External state dict (numpy values) -> validated variables tree.

    torchvision layouts for resnet/alexnet, HuggingFace layouts for
    vit/clip — the layouts the ecosystem's pretrained checkpoints ship in
    (the reference's `.ot` files played this role, services.rs:513-524).
    """
    if model_name in _RESNET_STAGES:
        sizes, bottleneck = _RESNET_STAGES[model_name]
        variables = convert.resnet_params_from_torch(state_dict, sizes, bottleneck)
    elif model_name == "alexnet":
        variables = convert.alexnet_params_from_torch(state_dict)
    elif model_name in _VIT_LAYERS:
        variables = convert.vit_params_from_hf(state_dict, _VIT_LAYERS[model_name])
    elif model_name in _CLIP_LAYERS:
        variables = convert.clip_params_from_hf(state_dict, _CLIP_LAYERS[model_name])
    else:
        raise KeyError(f"no external-checkpoint importer for {model_name!r}")
    check_variables(model_name, variables)
    return variables
