"""Vision Transformer (ViT-B/16 class) in Flax, TPU-first.

The reference has no attention models at all (fixed 224x224 CNNs,
src/services.rs:492); BASELINE.json adds ViT-B/16 classification and CLIP
ViT-L/14 embedding as required configs. This is a from-scratch ViT whose
parameter layout maps 1:1 onto HuggingFace ``ViTModel`` weights (q/k/v/out
projections as separate [D, D] matrices) so parity can be tested against
``transformers`` without any network access.

TPU notes: attention and MLP are plain einsum/matmul chains — XLA fuses the
softmax chain and tiles the matmuls onto the MXU; sequence length is static
(197 for 224/16). Long-sequence variants run through
``dmlc_tpu.parallel.ring_attention`` instead of this dense path.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def quick_gelu(x):
    return x * nn.sigmoid(1.702 * x)


def gelu_exact(x):
    # erf-based GELU (what torch/HF "gelu" means); flax's default is the tanh
    # approximation, which breaks bitwise parity with reference checkpoints.
    return nn.gelu(x, approximate=False)


ACTIVATIONS: dict[str, Callable] = {"gelu": gelu_exact, "quick_gelu": quick_gelu}


class MultiHeadAttention(nn.Module):
    """Standard MHA with separate q/k/v/out projections (HF-compatible layout)."""

    num_heads: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        assert d % self.num_heads == 0
        head_dim = d // self.num_heads
        dense = lambda name: nn.Dense(d, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)

        def split(t):  # [B, S, D] -> [B, H, S, hd]
            return t.reshape(t.shape[0], t.shape[1], self.num_heads, head_dim).transpose(0, 2, 1, 3)

        q, k, v = split(q), split(k), split(v)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(head_dim).astype(np.float32)
        probs = nn.softmax(scores.astype(jnp.float32), axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        out = out.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1], d)
        return dense("out")(out)


class TransformerBlock(nn.Module):
    """Pre-LN transformer block: LN→MHA→res, LN→MLP→res."""

    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    layer_norm_eps: float = 1e-12
    activation: str = "gelu"

    @nn.compact
    def __call__(self, x):
        ln = lambda name: nn.LayerNorm(epsilon=self.layer_norm_eps, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        y = ln("ln1")(x)
        y = MultiHeadAttention(self.num_heads, dtype=self.dtype, name="attn")(y)
        x = x + y
        y = ln("ln2")(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32, name="mlp_in")(y)
        y = ACTIVATIONS[self.activation](y)
        y = nn.Dense(x.shape[-1], dtype=self.dtype, param_dtype=jnp.float32, name="mlp_out")(y)
        return x + y


class ViT(nn.Module):
    """ViT encoder for classification. Input NHWC images, output logits."""

    num_classes: int = 1000
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    layer_norm_eps: float = 1e-12
    activation: str = "gelu"

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = x.shape[0]
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.hidden_size,
            (self.patch_size, self.patch_size),
            (self.patch_size, self.patch_size),
            padding="VALID",
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(x)
        x = x.reshape(b, -1, self.hidden_size)  # [B, S, D]
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, self.hidden_size), jnp.float32)
        x = jnp.concatenate([jnp.tile(cls.astype(self.dtype), (b, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], self.hidden_size), jnp.float32
        )
        x = x + pos.astype(self.dtype)
        for i in range(self.num_layers):
            x = TransformerBlock(
                self.num_heads,
                self.mlp_dim,
                dtype=self.dtype,
                layer_norm_eps=self.layer_norm_eps,
                activation=self.activation,
                name=f"block{i}",
            )(x)
        x = nn.LayerNorm(epsilon=self.layer_norm_eps, dtype=self.dtype, param_dtype=jnp.float32, name="ln_final")(x)
        cls_out = x[:, 0]
        logits = nn.Dense(self.num_classes, dtype=self.dtype, param_dtype=jnp.float32, name="head")(cls_out)
        return logits.astype(jnp.float32)


def vit_b16(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ViT:
    return ViT(num_classes=num_classes, dtype=dtype)


def vit_l14(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> ViT:
    return ViT(
        num_classes=num_classes,
        patch_size=14,
        hidden_size=1024,
        num_layers=24,
        num_heads=16,
        mlp_dim=4096,
        dtype=dtype,
    )
