"""Export a serving model as a native PJRT host bundle.

Produces the directory ``native/pjrt_host run`` consumes — the fully
Python-free serving deployment (the program compiles and executes through
the PJRT C ABI; reference analog: the Rust+libtorch native serving host,
services.rs:513-524):

    bundle/
      program.mlir         StableHLO of the serving forward (uint8 NHWC ->
                           top-1 index + prob), weights as PARAMETERS
      compile_options.pb   serialized default xla CompileOptionsProto
      args.txt             manifest: one "dtype:d0,d1,...[=file]" line per
                           executable input, in the exported flatten order
      arg<N>.raw           raw bytes for each weight leaf (row-major)
      client_options.txt   plugin client-create options (axon tunnel shape,
                           mirrored from the environment's jax registration)

Weights ship as raw files SEPARATE from the program, so a weight update
(the `train` verb's SDFS republish) never recompiles — same split the
Python-side ExportedBackend uses.

Entry points: the cluster CLI's `export-bundle` verb and
`python tools/export_pjrt_bundle.py --model resnet18 --batch 8 --out /tmp/bundle`.
"""

from __future__ import annotations

import os
import uuid
from pathlib import Path


_DTYPE_NAMES = {"uint8": "u8", "float32": "f32", "int32": "i32", "bfloat16": "bf16"}


def axon_client_options() -> str:
    """The client-create options the axon tunnel plugin needs — the same set
    jax's registration passes (axon/register/pjrt.py in this image), pool
    mode with a fresh session. Harmless for plugins that ignore options."""
    topology = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e") + ":1x1x1"
    return (
        "remote_compile=i:1\n"
        "local_only=i:0\n"
        "priority=i:0\n"
        f"topology=s:{topology}\n"
        "n_slices=i:1\n"
        f"session_id=s:pjrt-host-{uuid.uuid4()}\n"
        "rank=i:4294967295\n"
    )


def export_bundle(
    model_name: str,
    batch_size: int,
    out_dir: Path,
    seed: int = 0,
    image_paths: list[str] | None = None,
    variables=None,
) -> dict:
    import jax
    import numpy as np

    from dmlc_tpu.models import export as export_lib
    from dmlc_tpu.models.registry import get_model

    out_dir.mkdir(parents=True, exist_ok=True)
    blob = export_lib.export_serving(model_name, batch_size=batch_size)
    _, exported = export_lib.load_serving(blob, expect_model=model_name)
    (out_dir / "program.mlir").write_text(exported.mlir_module())

    from jax._src.lib import xla_client

    (out_dir / "compile_options.pb").write_bytes(
        xla_client.CompileOptions().SerializeAsString()
    )

    # Weights in the exported flatten order, dumped as raw row-major bytes
    # next to their manifest lines. ``variables`` lets callers bundle LIVE
    # weights (the CLI verb passes the cluster's published SDFS weights);
    # default is a fixed-seed init for smoke bundles.
    spec = get_model(model_name)
    if variables is None:
        _, variables = spec.init_params(jax.random.PRNGKey(seed), dtype=jax.numpy.bfloat16)
    flat_vars = jax.tree_util.tree_leaves(variables)
    lines = []
    n_weight_args = 0
    for aval in exported.in_avals:
        dt = _DTYPE_NAMES.get(str(aval.dtype))
        if dt is None:
            raise ValueError(f"unsupported exported input dtype {aval.dtype}")
        shape = ",".join(str(d) for d in aval.shape)
        if str(aval.dtype) == "uint8" and len(aval.shape) == 4:
            if image_paths:
                # Stage REAL decoded pixels so the native host classifies
                # actual JPEG data, not zeros; pad the batch by repeating.
                from dmlc_tpu.ops import preprocess as pp

                if len(image_paths) > batch_size:
                    raise ValueError(
                        f"{len(image_paths)} images but batch size "
                        f"{batch_size}: the extras would be silently "
                        "dropped — raise --batch or trim --image"
                    )
                size = int(aval.shape[1])
                batch = pp.load_batch(image_paths, size=size)
                reps = -(-batch_size // batch.shape[0])
                batch = np.tile(batch, (reps, 1, 1, 1))[:batch_size]
                if tuple(batch.shape) != tuple(aval.shape):
                    # Mirrors the weight-leaf guard: fail at export time,
                    # not at the host's deploy-time byte-size check.
                    raise ValueError(
                        f"staged image batch {batch.shape} != exported "
                        f"input aval {tuple(aval.shape)}"
                    )
                (out_dir / "image.raw").write_bytes(batch.tobytes())
                lines.append(f"{dt}:{shape}=image.raw")
            else:
                lines.append(f"{dt}:{shape}")  # the image batch: zeros
        else:
            leaf = np.asarray(flat_vars[n_weight_args])
            if tuple(leaf.shape) != tuple(aval.shape):
                raise ValueError(
                    f"weight leaf {n_weight_args} shape {leaf.shape} != "
                    f"exported aval {aval.shape} — flatten order drifted"
                )
            if str(leaf.dtype) != str(aval.dtype):
                # Same-itemsize mismatches (i32 vs f32) would otherwise write
                # silently-wrong raw bytes the host stages verbatim. Pure
                # precision differences (an f32-trained checkpoint feeding a
                # bf16 program) are cast; anything kind-crossing is a real
                # flatten drift and fails here, not at host load.
                import jax.numpy as jnp

                if jnp.issubdtype(leaf.dtype, np.floating) and jnp.issubdtype(
                    aval.dtype, np.floating
                ):
                    leaf = np.asarray(leaf, dtype=aval.dtype)
                else:
                    raise ValueError(
                        f"weight leaf {n_weight_args} dtype {leaf.dtype} != "
                        f"exported aval dtype {aval.dtype} — flatten order drifted"
                    )
            fname = f"arg{n_weight_args}.raw"
            (out_dir / fname).write_bytes(leaf.tobytes())
            lines.append(f"{dt}:{shape}={fname}")
            n_weight_args += 1
    if n_weight_args != len(flat_vars):
        raise ValueError(
            f"exported {n_weight_args} weight inputs but the tree has "
            f"{len(flat_vars)} leaves"
        )
    (out_dir / "args.txt").write_text("\n".join(lines) + "\n")
    (out_dir / "client_options.txt").write_text(axon_client_options())
    return {
        "model": model_name,
        "batch": batch_size,
        "inputs": len(lines),
        "weight_args": n_weight_args,
        "program_bytes": (out_dir / "program.mlir").stat().st_size,
    }
