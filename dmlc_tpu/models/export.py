"""StableHLO export toolchain (SURVEY §7 L0): models as portable executables.

The reference distributes models as tch ``.ot`` weight files interpreted by a
libtorch runtime baked into every binary (src/services.rs:513-524). The
TPU-native equivalent distributes two artifacts through SDFS:

- **weights** (models/weights.py) — the variables tree, hot-swappable;
- **executables** (this module) — the whole serving program (device-side
  normalize -> forward -> softmax -> top-1) exported with ``jax.export`` to a
  versioned StableHLO artifact. The artifact is weight-agnostic (variables
  are an argument), hardware-portable within jax's compatibility guarantees,
  and re-executable WITHOUT the model's Python source: ``deserialize`` +
  ``call`` is the whole loader.

This is the credible core of "native serving": the artifact is compiler IR
(VHLO/StableHLO bytes, inspectable via ``stablehlo_text``), not pickled
Python. Executing it outside a Python process additionally needs a PJRT
C-API host — see SURVEY.md §7 for why that loader is deferred and what the
boundary is.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from dmlc_tpu.models import weights as weights_lib
from dmlc_tpu.models.registry import get_model
from dmlc_tpu.ops import preprocess as pp

MAGIC = b"DMLCHLO1"


def sdfs_executable_name(model_name: str) -> str:
    """Canonical SDFS name for a model's serving executable."""
    return f"executables/{model_name}"


def build_serving_forward(model_name: str, dtype=jnp.bfloat16):
    """The serving program: uint8 NHWC -> (top1_index, top1_prob) for
    classifiers, or the embedding matrix for encoders. Mirrors
    InferenceEngine's XLA path (parallel/inference.py) — the export parity
    test pins the two together."""
    spec = get_model(model_name)
    model = spec.module(dtype=dtype)
    mean_np, std_np = pp.stats_for_model(model_name)
    mean, std = jnp.asarray(mean_np), jnp.asarray(std_np)

    def forward(variables, u8):
        x = u8.astype(jnp.float32) / 255.0
        x = (x - mean) / std
        out = model.apply(variables, x, train=False)
        if spec.classifier:
            probs = jax.nn.softmax(out, axis=-1)
            return jnp.argmax(probs, -1).astype(jnp.int32), jnp.max(probs, -1)
        return out

    return forward


def export_serving(model_name: str, batch_size: int = 256, dtype=jnp.bfloat16) -> bytes:
    """Trace + export the serving program on abstract shapes -> one blob
    (magic + model name + serialized StableHLO artifact)."""
    spec = get_model(model_name)
    forward = build_serving_forward(model_name, dtype=dtype)
    template = weights_lib.variables_template(model_name)
    u8 = jax.ShapeDtypeStruct((batch_size, spec.input_size, spec.input_size, 3), jnp.uint8)
    exported = jax_export.export(jax.jit(forward))(template, u8)
    name_b = model_name.encode()
    return MAGIC + len(name_b).to_bytes(2, "big") + name_b + bytes(exported.serialize())


def load_serving(data: bytes, expect_model: str | None = None):
    """-> (model_name, exported): the deserialized artifact. ``exported.call``
    executes it — no model source code involved."""
    if data[: len(MAGIC)] != MAGIC:
        raise ValueError("not a dmlc executable blob (bad magic)")
    off = len(MAGIC)
    n = int.from_bytes(data[off : off + 2], "big")
    model_name = data[off + 2 : off + 2 + n].decode()
    if expect_model is not None and model_name != expect_model:
        raise ValueError(f"executable is for {model_name!r}, expected {expect_model!r}")
    exported = jax_export.deserialize(bytearray(data[off + 2 + n :]))
    return model_name, exported


def stablehlo_text(data: bytes) -> str:
    """Human-readable StableHLO of a serialized executable blob."""
    _, exported = load_serving(data)
    return exported.mlir_module()


def publish_executable(
    sdfs_client, model_name: str, batch_size: int = 256, dtype=jnp.bfloat16
) -> int:
    """Export and put a new executable version into SDFS; returns version."""
    blob = export_serving(model_name, batch_size=batch_size, dtype=dtype)
    return sdfs_client.put_bytes(blob, sdfs_executable_name(model_name))["version"]


def fetch_executable(sdfs_client, model_name: str, version: int | None = None):
    """Pull + deserialize a model's executable from SDFS ->
    (version, exported)."""
    v, blob = sdfs_client.get_bytes(sdfs_executable_name(model_name), version=version)
    _, exported = load_serving(blob, expect_model=model_name)
    return v, exported


class ExportedServer:
    """Serve batches straight from a deserialized artifact: the minimal
    'loader' — everything the member needs to answer predict shards is the
    blob + the weights, no model source."""

    def __init__(self, exported, variables, batch_size: int, classifier: bool = True):
        self.exported = exported
        self.variables = variables
        self.batch_size = int(batch_size)
        self.classifier = classifier

    def __call__(self, batch_u8: np.ndarray):
        n = batch_u8.shape[0]
        if n < self.batch_size:
            pad = np.zeros((self.batch_size - n, *batch_u8.shape[1:]), batch_u8.dtype)
            batch_u8 = np.concatenate([batch_u8, pad])
        out = self.exported.call(self.variables, batch_u8)
        if self.classifier:
            idx, top = (np.asarray(o)[:n] for o in out)
            return idx, top
        return np.asarray(out)[:n]
