"""StableHLO export toolchain (SURVEY §7 L0): models as portable executables.

The reference distributes models as tch ``.ot`` weight files interpreted by a
libtorch runtime baked into every binary (src/services.rs:513-524). The
TPU-native equivalent distributes two artifacts through SDFS:

- **weights** (models/weights.py) — the variables tree, hot-swappable;
- **executables** (this module) — the whole serving program (device-side
  normalize -> forward -> softmax -> top-1) exported with ``jax.export`` to a
  versioned StableHLO artifact. The artifact is weight-agnostic (variables
  are an argument), hardware-portable within jax's compatibility guarantees,
  and re-executable WITHOUT the model's Python source: ``deserialize`` +
  ``call`` is the whole loader.

This is the credible core of "native serving": the artifact is compiler IR
(VHLO/StableHLO bytes, inspectable via ``stablehlo_text``), not pickled
Python. Executing it outside a Python process additionally needs a PJRT
C-API host — see SURVEY.md §7 for why that loader is deferred and what the
boundary is.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from dmlc_tpu.models import weights as weights_lib
from dmlc_tpu.models.registry import get_model
from dmlc_tpu.ops import preprocess as pp

MAGIC = b"DMLCHLO1"
# Gang-sharded executables (docs/SHARDING.md): same artifact discipline, but
# the program was traced under a rule-derived mesh, so the blob additionally
# records the mesh axes it must be re-instantiated on.
SHARDED_MAGIC = b"DMLCHLO2"


def sdfs_executable_name(model_name: str) -> str:
    """Canonical SDFS name for a model's serving executable."""
    return f"executables/{model_name}"


def sdfs_sharded_executable_name(model_name: str, n_devices: int) -> str:
    """Canonical SDFS name for a gang's sharded executable: one artifact per
    (model, gang width) — the same model ganged at a different width is a
    different compiled program."""
    return f"executables/{model_name}@{int(n_devices)}"


def build_serving_forward(model_name: str, dtype=jnp.bfloat16):
    """The serving program: uint8 NHWC -> (top1_index, top1_prob) for
    classifiers, or the embedding matrix for encoders. Mirrors
    InferenceEngine's XLA path (parallel/inference.py) — the export parity
    test pins the two together."""
    spec = get_model(model_name)
    model = spec.module(dtype=dtype)
    mean_np, std_np = pp.stats_for_model(model_name)
    mean, std = jnp.asarray(mean_np), jnp.asarray(std_np)

    def forward(variables, u8):
        x = u8.astype(jnp.float32) / 255.0
        x = (x - mean) / std
        out = model.apply(variables, x, train=False)
        if spec.classifier:
            probs = jax.nn.softmax(out, axis=-1)
            return jnp.argmax(probs, -1).astype(jnp.int32), jnp.max(probs, -1)
        return out

    return forward


def export_serving(model_name: str, batch_size: int = 256, dtype=jnp.bfloat16) -> bytes:
    """Trace + export the serving program on abstract shapes -> one blob
    (magic + model name + serialized StableHLO artifact)."""
    spec = get_model(model_name)
    forward = build_serving_forward(model_name, dtype=dtype)
    template = weights_lib.variables_template(model_name)
    u8 = jax.ShapeDtypeStruct((batch_size, spec.input_size, spec.input_size, 3), jnp.uint8)
    exported = jax_export.export(jax.jit(forward))(template, u8)
    name_b = model_name.encode()
    return MAGIC + len(name_b).to_bytes(2, "big") + name_b + bytes(exported.serialize())


def export_sharded_serving(
    model_name: str,
    mesh,
    *,
    batch_size: int = 8,
    seq_len: int = 16,
    dtype=jnp.float32,
) -> bytes:
    """Export the partition-rule-sharded serving program at a mesh shape —
    the gang's executable (docs/SHARDING.md). The jit carries the rule
    engine's in/out shardings, so the artifact bakes in the collective
    layout; the blob records the mesh axes it was traced under, because a
    deserialized sharded program only runs on a mesh of the same shape."""
    import json

    from dmlc_tpu.parallel.sharding import ShardedProgram

    spec = get_model(model_name)
    prog = ShardedProgram(model_name, mesh, dtype=dtype)
    forward = prog._build_forward()
    template = jax.tree_util.tree_map(
        lambda leaf: jax.ShapeDtypeStruct(jnp.shape(leaf), leaf.dtype),
        prog.variables,
    )
    if spec.kind == "lm":
        data = jax.ShapeDtypeStruct((batch_size, seq_len), jnp.int32)
    else:
        data = jax.ShapeDtypeStruct(
            (batch_size, spec.input_size, spec.input_size, 3), jnp.uint8
        )
    exported = jax_export.export(forward)(template, data)
    axes_b = json.dumps(
        dict(zip(mesh.axis_names, (int(s) for s in mesh.devices.shape)))
    ).encode()
    name_b = model_name.encode()
    return (
        SHARDED_MAGIC
        + len(name_b).to_bytes(2, "big")
        + name_b
        + len(axes_b).to_bytes(2, "big")
        + axes_b
        + bytes(exported.serialize())
    )


def load_sharded_serving(data: bytes, expect_model: str | None = None):
    """-> (model_name, mesh_axes, exported) for a gang executable blob. The
    caller re-creates a mesh of exactly ``mesh_axes`` (parallel.mesh.
    make_mesh) before ``exported.call`` — jax refuses an artifact whose
    device count disagrees with the runtime mesh, by design."""
    import json

    if data[: len(SHARDED_MAGIC)] != SHARDED_MAGIC:
        raise ValueError("not a dmlc sharded executable blob (bad magic)")
    off = len(SHARDED_MAGIC)
    n = int.from_bytes(data[off : off + 2], "big")
    model_name = data[off + 2 : off + 2 + n].decode()
    off = off + 2 + n
    m = int.from_bytes(data[off : off + 2], "big")
    mesh_axes = {k: int(v) for k, v in json.loads(data[off + 2 : off + 2 + m]).items()}
    if expect_model is not None and model_name != expect_model:
        raise ValueError(f"executable is for {model_name!r}, expected {expect_model!r}")
    exported = jax_export.deserialize(bytearray(data[off + 2 + m :]))
    return model_name, mesh_axes, exported


def load_serving(data: bytes, expect_model: str | None = None):
    """-> (model_name, exported): the deserialized artifact. ``exported.call``
    executes it — no model source code involved."""
    if data[: len(MAGIC)] != MAGIC:
        raise ValueError("not a dmlc executable blob (bad magic)")
    off = len(MAGIC)
    n = int.from_bytes(data[off : off + 2], "big")
    model_name = data[off + 2 : off + 2 + n].decode()
    if expect_model is not None and model_name != expect_model:
        raise ValueError(f"executable is for {model_name!r}, expected {expect_model!r}")
    exported = jax_export.deserialize(bytearray(data[off + 2 + n :]))
    return model_name, exported


def stablehlo_text(data: bytes) -> str:
    """Human-readable StableHLO of a serialized executable blob."""
    _, exported = load_serving(data)
    return exported.mlir_module()


def publish_executable(
    sdfs_client, model_name: str, batch_size: int = 256, dtype=jnp.bfloat16
) -> int:
    """Export and put a new executable version into SDFS; returns version."""
    blob = export_serving(model_name, batch_size=batch_size, dtype=dtype)
    return sdfs_client.put_bytes(blob, sdfs_executable_name(model_name))["version"]


def fetch_executable(sdfs_client, model_name: str, version: int | None = None):
    """Pull + deserialize a model's executable from SDFS ->
    (version, exported)."""
    v, blob = sdfs_client.get_bytes(sdfs_executable_name(model_name), version=version)
    _, exported = load_serving(blob, expect_model=model_name)
    return v, exported


class ExportedServer:
    """Serve batches straight from a deserialized artifact: the minimal
    'loader' — everything the member needs to answer predict shards is the
    blob + the weights, no model source."""

    def __init__(self, exported, variables, batch_size: int, classifier: bool = True):
        self.exported = exported
        self.variables = variables
        self.batch_size = int(batch_size)
        self.classifier = classifier

    def __call__(self, batch_u8: np.ndarray):
        n = batch_u8.shape[0]
        if n < self.batch_size:
            pad = np.zeros((self.batch_size - n, *batch_u8.shape[1:]), batch_u8.dtype)
            batch_u8 = np.concatenate([batch_u8, pad])
        out = self.exported.call(self.variables, batch_u8)
        if self.classifier:
            idx, top = (np.asarray(o)[:n] for o in out)
            return idx, top
        return np.asarray(out)[:n]
