"""Servable language models for the generation engine.

The registry's image models map name -> flax module + input geometry; this
module does the same for causal LMs built on ``parallel.sp_transformer.
SPTransformerLM`` — the architecture the lm_flash_train bench leg already
trains at 130k tok/s (BENCH_r05). Registering here makes an LM a first-class
registry citizen: the generation worker builds it by name, weights publish/
hot-swap through the existing SDFS blob path (``models/<name>``), and
``weights.variables_template`` validates blobs against the same abstract
init every other model uses.

``lm_small`` is deliberately tiny (2 layers, 128 hidden): it initializes
from seed in well under a second on the CPU test mesh, so generation has a
servable model with no new checkpoints (ISSUE 7 satellite). Production-
scale entries should follow the bench geometry — heads sized so head_dim
is 128, the MXU lane width (see ops/pallas_kernels.flash_attention).
"""

from __future__ import annotations

import jax.numpy as jnp


def lm_wide(dtype=jnp.float32):
    """The gang-serving proof model (ISSUE 17): head_dim 128 (the MXU lane
    width the bench geometry calls for), sized so its resident weights
    overflow the single-chip HBM budget in the test harness — it only serves
    sharded, across a chip gang the PlacementAdvisor picks from HBM headroom.
    Geometry: 4 heads x 128 head_dim = 512 hidden, 2 layers, vocab 2048
    (~6M params: seed-init stays sub-second on the CPU test mesh)."""
    from dmlc_tpu.parallel.sp_transformer import SPTransformerLM

    return SPTransformerLM(
        vocab=LM_WIDE_VOCAB,
        num_layers=2,
        num_heads=4,
        hidden=512,
        mlp_dim=1024,
        max_len=LM_WIDE_MAX_LEN,
        schedule="dense",
        dtype=dtype,
    )


LM_WIDE_VOCAB = 2048
LM_WIDE_MAX_LEN = 128
LM_WIDE_NUM_HEADS = 4


def lm_small(dtype=jnp.float32):
    """A seed-initialized small causal LM (dense attention schedule: the
    single-device regime; the generation engine supplies its own paged
    decode attention, so the schedule only governs training/prefill)."""
    from dmlc_tpu.parallel.sp_transformer import SPTransformerLM

    return SPTransformerLM(
        vocab=LM_SMALL_VOCAB,
        num_layers=2,
        num_heads=2,
        hidden=128,
        mlp_dim=256,
        max_len=LM_SMALL_MAX_LEN,
        schedule="dense",
        dtype=dtype,
    )


LM_SMALL_VOCAB = 1024
LM_SMALL_MAX_LEN = 256
