"""Model registry.

The reference statically defines exactly two jobs, "resnet18" and "alexnet"
(src/services.rs:168-169), with models loaded eagerly at member startup
(src/services.rs:513-524). Here models are looked up by name from a registry
that also carries the input geometry, so the scheduler, CLI, and bench all
agree on model identity by string name — including the BASELINE.json extras
(resnet50, vit_b16, clip_vit_l14).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp

from dmlc_tpu.models.alexnet import alexnet
from dmlc_tpu.models.clip import clip_vit_b32, clip_vit_l14
from dmlc_tpu.models.lm import (
    LM_SMALL_MAX_LEN,
    LM_SMALL_VOCAB,
    LM_WIDE_MAX_LEN,
    LM_WIDE_NUM_HEADS,
    LM_WIDE_VOCAB,
    lm_small,
    lm_wide,
)
from dmlc_tpu.models.resnet import resnet18, resnet34, resnet50
from dmlc_tpu.models.vit import vit_b16, vit_l14
from dmlc_tpu.parallel.sharding import (
    REPLICATED_PARTITION_RULES,
    TRANSFORMER_PARTITION_RULES,
)


@dataclass(frozen=True)
class ModelSpec:
    name: str
    build: Callable[..., Any]          # (dtype=...) -> nn.Module
    input_size: int                    # square image side; max_len for kind="lm"
    num_outputs: int                   # classes / embedding dim; vocab for "lm"
    classifier: bool = True            # False => embedding model (no top-1/accuracy)
    kind: str = "image"                # "image" | "lm" (autoregressive decode)
    # Ordered (regex, PartitionSpec) table consumed by parallel/sharding.py:
    # declared ONCE here, compiled into sharded programs at any mesh shape.
    # None => fully replicated (the CNN families). num_heads bounds tp.
    partition_rules: tuple[tuple[str, Any], ...] | None = None
    num_heads: int | None = None

    def module(self, dtype=jnp.bfloat16):
        if self.kind == "lm":
            return self.build(dtype=dtype)
        if self.classifier:
            return self.build(num_classes=self.num_outputs, dtype=dtype)
        return self.build(dtype=dtype)

    def init_params(self, rng, dtype=jnp.bfloat16, batch_size: int = 1):
        model = self.module(dtype=dtype)
        if self.kind == "lm":
            # Any token length yields the full parameter tree (the embed
            # tables are sized by the module's vocab/max_len, not the
            # example), so init with a short dummy sequence.
            dummy = jnp.zeros((batch_size, 8), jnp.int32)
            return model, model.init(rng, dummy)
        dummy = jnp.zeros((batch_size, self.input_size, self.input_size, 3), jnp.float32)
        return model, model.init(rng, dummy, train=False)

    # ---- analytic model accounting (devicemon + placement headroom) -----

    def param_count(self) -> int:
        """Total parameter/statistic scalars across every variable
        collection (params + batch_stats), computed ABSTRACTLY via
        ``jax.eval_shape`` — no device allocation, no compile. Pinned
        against the real init pytree in tests/test_model_analytics.py."""
        return sum(math.prod(leaf.shape) for leaf in _abstract_leaves(self.name))

    def param_bytes(self, dtype: Any = None) -> int:
        """Resident bytes of the variables pytree: each leaf's element
        count times its init dtype's width (or ``dtype``'s, when the
        serving engine casts — e.g. bfloat16). This is the analytic
        weights-residency figure the placement headroom constraint and the
        ``resident_bytes_<model>`` gauges build on (docs/OBSERVABILITY.md
        §8)."""
        itemsize = None if dtype is None else jnp.dtype(dtype).itemsize
        total = 0
        for leaf in _abstract_leaves(self.name):
            width = itemsize if itemsize is not None else jnp.dtype(leaf.dtype).itemsize
            total += math.prod(leaf.shape) * width
        return total

    def flops_per_item(self) -> float | None:
        """Analytic forward FLOPs for ONE item — an image for ``kind=
        "image"`` models, one generated token (decode step at max_len
        context, the roofline-relevant upper bound) for ``kind="lm"``.
        Multiply-accumulates count 2 FLOPs, matching XLA's
        ``cost_analysis()['flops']`` convention (validated against it in
        tests/test_model_analytics.py); elementwise/norm/pool terms are
        omitted as sub-percent noise. None for models without a formula."""
        fn = _FLOPS_PER_ITEM.get(self.name)
        return float(fn()) if fn is not None else None


@functools.lru_cache(maxsize=None)
def _abstract_leaves(name: str) -> tuple[Any, ...]:
    """Abstract (shape/dtype-only) leaves of a model's full variables
    pytree: ``eval_shape`` runs the real flax init without touching the
    device, so counts/bytes match the served tree exactly."""
    spec = get_model(name)

    def init() -> Any:
        _, variables = spec.init_params(jax.random.PRNGKey(0), dtype=jnp.float32)
        return variables

    return tuple(jax.tree_util.tree_leaves(jax.eval_shape(init)))


def _conv_out(size: int, kernel: int, stride: int, pad: int) -> int:
    """Output side of a square conv/pool with explicit symmetric padding."""
    return (size + 2 * pad - kernel) // stride + 1


def _resnet_flops(blocks: tuple[int, ...], bottleneck: bool,
                  num_classes: int = 1000, image: int = 224) -> float:
    """Conv-walk of models/resnet.py: stem 7x7/2 -> maxpool 3x3/2 -> four
    stages (filters 64*2^i, first block of stages 2-4 strides 2), basic
    blocks (two 3x3) or bottlenecks (1x1 -> 3x3 stride s -> 1x1 expand x4),
    1x1 projection downsample exactly when the residual shape changes."""
    size = _conv_out(image, 7, 2, 3)
    fl = 2.0 * size * size * 64 * 3 * 49           # stem conv, bias-free
    size = _conv_out(size, 3, 2, 1)                # maxpool
    cin = 64
    for i, n in enumerate(blocks):
        f = 64 * 2 ** i
        for b in range(n):
            s = 2 if (i > 0 and b == 0) else 1
            out = _conv_out(size, 3, s, 1)
            if bottleneck:
                fl += 2.0 * size * size * f * cin           # 1x1 reduce
                fl += 2.0 * out * out * f * f * 9           # 3x3, stride s
                fl += 2.0 * out * out * (4 * f) * f         # 1x1 expand
                if s != 1 or cin != 4 * f:
                    fl += 2.0 * out * out * (4 * f) * cin   # projection shortcut
                cin = 4 * f
            else:
                fl += 2.0 * out * out * f * cin * 9
                fl += 2.0 * out * out * f * f * 9
                if s != 1 or cin != f:
                    fl += 2.0 * out * out * f * cin
                cin = f
            size = out
    return fl + 2.0 * cin * num_classes            # pooled head


def _alexnet_flops(num_classes: int = 1000, image: int = 224) -> float:
    """Conv/fc walk of models/alexnet.py (all convs/denses carry bias —
    bias adds are sub-percent and omitted like every elementwise term)."""
    s1 = _conv_out(image, 11, 4, 2)                # 55
    fl = 2.0 * s1 * s1 * 64 * 3 * 121
    s2 = _conv_out(s1, 3, 2, 0)                    # 27
    fl += 2.0 * s2 * s2 * 192 * 64 * 25
    s3 = _conv_out(s2, 3, 2, 0)                    # 13
    fl += 2.0 * s3 * s3 * 384 * 192 * 9
    fl += 2.0 * s3 * s3 * 256 * 384 * 9
    fl += 2.0 * s3 * s3 * 256 * 256 * 9
    s4 = _conv_out(s3, 3, 2, 0)                    # 6
    flat = 256 * s4 * s4
    return fl + 2.0 * (flat * 4096 + 4096 * 4096 + 4096 * num_classes)


def _vit_flops(patch: int, hidden: int, layers: int, mlp: int,
               out_dim: int, image: int = 224, cls_tokens: int = 1) -> float:
    """Transformer walk shared by models/vit.py and the CLIP vision trunk:
    patch-embed conv + per-block (q/k/v/out projections, score+mix
    attention, MLP) + head/projection read off the cls token."""
    grid = image // patch
    seq = grid * grid + cls_tokens
    fl = 2.0 * grid * grid * hidden * 3 * patch * patch
    per_block = (
        8.0 * seq * hidden * hidden        # q, k, v, out projections
        + 4.0 * seq * seq * hidden         # QK^T scores + attention-weighted V
        + 4.0 * seq * hidden * mlp         # MLP in + out
    )
    return fl + layers * per_block + 2.0 * hidden * out_dim


def _lm_decode_flops(vocab: int, layers: int, hidden: int, mlp: int,
                     context: int) -> float:
    """One decode step (one generated token) at ``context`` resident
    tokens: per-layer q/k/v/out projections + paged-KV attention + MLP,
    plus the vocab head. The embedding lookup is a gather (no MACs)."""
    per_layer = (
        8.0 * hidden * hidden              # q, k, v, out projections
        + 4.0 * context * hidden           # scores + mix against the KV pages
        + 4.0 * hidden * mlp               # MLP in + out
    )
    return layers * per_layer + 2.0 * hidden * vocab


_FLOPS_PER_ITEM: dict[str, Callable[[], float]] = {
    "resnet18": lambda: _resnet_flops((2, 2, 2, 2), False),
    "resnet34": lambda: _resnet_flops((3, 4, 6, 3), False),
    "resnet50": lambda: _resnet_flops((3, 4, 6, 3), True),
    "alexnet": lambda: _alexnet_flops(),
    "vit_b16": lambda: _vit_flops(16, 768, 12, 3072, 1000),
    "vit_l14": lambda: _vit_flops(14, 1024, 24, 4096, 1000),
    "clip_vit_l14": lambda: _vit_flops(14, 1024, 24, 4096, 768),
    "clip_vit_b32": lambda: _vit_flops(32, 768, 12, 3072, 512),
    "lm_small": lambda: _lm_decode_flops(
        LM_SMALL_VOCAB, 2, 128, 256, LM_SMALL_MAX_LEN
    ),
    "lm_wide": lambda: _lm_decode_flops(
        LM_WIDE_VOCAB, 2, 512, 1024, LM_WIDE_MAX_LEN
    ),
}


_REGISTRY: dict[str, ModelSpec] = {}


def register(spec: ModelSpec) -> None:
    _REGISTRY[spec.name] = spec


def get_model(name: str) -> ModelSpec:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_models() -> list[str]:
    return sorted(_REGISTRY)


for _spec in [
    ModelSpec("resnet18", resnet18, 224, 1000,
              partition_rules=REPLICATED_PARTITION_RULES),
    ModelSpec("resnet34", resnet34, 224, 1000,
              partition_rules=REPLICATED_PARTITION_RULES),
    ModelSpec("resnet50", resnet50, 224, 1000,
              partition_rules=REPLICATED_PARTITION_RULES),
    ModelSpec("alexnet", alexnet, 224, 1000,
              partition_rules=REPLICATED_PARTITION_RULES),
    ModelSpec("vit_b16", vit_b16, 224, 1000,
              partition_rules=TRANSFORMER_PARTITION_RULES, num_heads=12),
    ModelSpec("vit_l14", vit_l14, 224, 1000,
              partition_rules=TRANSFORMER_PARTITION_RULES, num_heads=16),
    ModelSpec("clip_vit_l14", clip_vit_l14, 224, 768, classifier=False,
              partition_rules=TRANSFORMER_PARTITION_RULES, num_heads=16),
    ModelSpec("clip_vit_b32", clip_vit_b32, 224, 512, classifier=False,
              partition_rules=TRANSFORMER_PARTITION_RULES, num_heads=12),
    # Servable causal LM for the generation engine (dmlc_tpu/generate/):
    # init from seed, weights hot-swapped via the SDFS models/<name> blob
    # path like every other entry. input_size carries max_len, num_outputs
    # the vocab.
    ModelSpec(
        "lm_small", lm_small, LM_SMALL_MAX_LEN, LM_SMALL_VOCAB,
        classifier=False, kind="lm",
        partition_rules=TRANSFORMER_PARTITION_RULES, num_heads=2,
    ),
    # Gang-serving proof model (ISSUE 17): over the single-chip HBM budget
    # in the test harness, serves only as a >=2 chip gang (docs/SHARDING.md).
    ModelSpec(
        "lm_wide", lm_wide, LM_WIDE_MAX_LEN, LM_WIDE_VOCAB,
        classifier=False, kind="lm",
        partition_rules=TRANSFORMER_PARTITION_RULES, num_heads=LM_WIDE_NUM_HEADS,
    ),
]:
    register(_spec)
