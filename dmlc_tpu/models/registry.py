"""Model registry.

The reference statically defines exactly two jobs, "resnet18" and "alexnet"
(src/services.rs:168-169), with models loaded eagerly at member startup
(src/services.rs:513-524). Here models are looked up by name from a registry
that also carries the input geometry, so the scheduler, CLI, and bench all
agree on model identity by string name — including the BASELINE.json extras
(resnet50, vit_b16, clip_vit_l14).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp

from dmlc_tpu.models.alexnet import alexnet
from dmlc_tpu.models.clip import clip_vit_b32, clip_vit_l14
from dmlc_tpu.models.lm import LM_SMALL_MAX_LEN, LM_SMALL_VOCAB, lm_small
from dmlc_tpu.models.resnet import resnet18, resnet34, resnet50
from dmlc_tpu.models.vit import vit_b16, vit_l14


@dataclass(frozen=True)
class ModelSpec:
    name: str
    build: Callable[..., Any]          # (dtype=...) -> nn.Module
    input_size: int                    # square image side; max_len for kind="lm"
    num_outputs: int                   # classes / embedding dim; vocab for "lm"
    classifier: bool = True            # False => embedding model (no top-1/accuracy)
    kind: str = "image"                # "image" | "lm" (autoregressive decode)

    def module(self, dtype=jnp.bfloat16):
        if self.kind == "lm":
            return self.build(dtype=dtype)
        if self.classifier:
            return self.build(num_classes=self.num_outputs, dtype=dtype)
        return self.build(dtype=dtype)

    def init_params(self, rng, dtype=jnp.bfloat16, batch_size: int = 1):
        model = self.module(dtype=dtype)
        if self.kind == "lm":
            # Any token length yields the full parameter tree (the embed
            # tables are sized by the module's vocab/max_len, not the
            # example), so init with a short dummy sequence.
            dummy = jnp.zeros((batch_size, 8), jnp.int32)
            return model, model.init(rng, dummy)
        dummy = jnp.zeros((batch_size, self.input_size, self.input_size, 3), jnp.float32)
        return model, model.init(rng, dummy, train=False)


_REGISTRY: dict[str, ModelSpec] = {}


def register(spec: ModelSpec) -> None:
    _REGISTRY[spec.name] = spec


def get_model(name: str) -> ModelSpec:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_models() -> list[str]:
    return sorted(_REGISTRY)


for _spec in [
    ModelSpec("resnet18", resnet18, 224, 1000),
    ModelSpec("resnet34", resnet34, 224, 1000),
    ModelSpec("resnet50", resnet50, 224, 1000),
    ModelSpec("alexnet", alexnet, 224, 1000),
    ModelSpec("vit_b16", vit_b16, 224, 1000),
    ModelSpec("vit_l14", vit_l14, 224, 1000),
    ModelSpec("clip_vit_l14", clip_vit_l14, 224, 768, classifier=False),
    ModelSpec("clip_vit_b32", clip_vit_b32, 224, 512, classifier=False),
    # Servable causal LM for the generation engine (dmlc_tpu/generate/):
    # init from seed, weights hot-swapped via the SDFS models/<name> blob
    # path like every other entry. input_size carries max_len, num_outputs
    # the vocab.
    ModelSpec(
        "lm_small", lm_small, LM_SMALL_MAX_LEN, LM_SMALL_VOCAB,
        classifier=False, kind="lm",
    ),
]:
    register(_spec)
