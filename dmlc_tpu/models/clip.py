"""CLIP image encoder (ViT-L/14 class) in Flax for batch embedding.

Required by BASELINE.json config "CLIP ViT-L/14 image-encoder batch embedding
(bf16)". From-scratch implementation whose parameter layout maps onto
HuggingFace ``CLIPVisionModelWithProjection`` so parity is testable offline.

Differences from the classification ViT (models/vit.py):
- a pre-encoder LayerNorm after the embeddings (``pre_layrnorm`` in HF),
- quick-GELU activation, eps 1e-5,
- pooled output = post-LN of the CLS token, then a bias-free projection to the
  shared embedding space.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from dmlc_tpu.models.vit import TransformerBlock


class CLIPVisionEncoder(nn.Module):
    projection_dim: int = 768
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    mlp_dim: int = 4096
    dtype: Any = jnp.bfloat16
    layer_norm_eps: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = x.shape[0]
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.hidden_size,
            (self.patch_size, self.patch_size),
            (self.patch_size, self.patch_size),
            padding="VALID",
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(x)
        x = x.reshape(b, -1, self.hidden_size)
        cls = self.param("cls_token", nn.initializers.normal(0.02), (1, 1, self.hidden_size), jnp.float32)
        x = jnp.concatenate([jnp.tile(cls.astype(self.dtype), (b, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], self.hidden_size), jnp.float32
        )
        x = x + pos.astype(self.dtype)
        ln = lambda name: nn.LayerNorm(epsilon=self.layer_norm_eps, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        x = ln("pre_ln")(x)
        for i in range(self.num_layers):
            x = TransformerBlock(
                self.num_heads,
                self.mlp_dim,
                dtype=self.dtype,
                layer_norm_eps=self.layer_norm_eps,
                activation="quick_gelu",
                name=f"block{i}",
            )(x)
        pooled = ln("post_ln")(x[:, 0])
        embeds = nn.Dense(
            self.projection_dim, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32, name="projection"
        )(pooled)
        return embeds.astype(jnp.float32)


def clip_vit_l14(dtype: Any = jnp.bfloat16) -> CLIPVisionEncoder:
    return CLIPVisionEncoder(dtype=dtype)


def clip_vit_b32(dtype: Any = jnp.bfloat16) -> CLIPVisionEncoder:
    return CLIPVisionEncoder(
        projection_dim=512, patch_size=32, hidden_size=768, num_layers=12, num_heads=12, mlp_dim=3072, dtype=dtype
    )
