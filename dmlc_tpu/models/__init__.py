from dmlc_tpu.models.alexnet import AlexNet, alexnet
from dmlc_tpu.models.clip import CLIPVisionEncoder, clip_vit_b32, clip_vit_l14
from dmlc_tpu.models.registry import ModelSpec, get_model, list_models, register
from dmlc_tpu.models.resnet import ResNet, resnet18, resnet34, resnet50
from dmlc_tpu.models.vit import ViT, vit_b16, vit_l14

__all__ = [
    "AlexNet", "alexnet",
    "CLIPVisionEncoder", "clip_vit_b32", "clip_vit_l14",
    "ModelSpec", "get_model", "list_models", "register",
    "ResNet", "resnet18", "resnet34", "resnet50",
    "ViT", "vit_b16", "vit_l14",
]
