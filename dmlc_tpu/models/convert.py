"""Weight import: external checkpoint layouts -> this zoo's Flax params.

The reference ships pretrained weights as tch ``VarStore`` ``.ot`` files
loaded at member startup (src/services.rs:513-524). Here the equivalent is a
converter per model family from the ecosystem's canonical layouts:

- ``vit_params_from_hf`` / ``clip_params_from_hf`` — HuggingFace
  ``ViTForImageClassification`` / ``CLIPVisionModelWithProjection`` state
  dicts (separate q/k/v/out projections; our modules mirror that layout
  1:1, models/vit.py).
- ``resnet_params_from_torch`` / ``alexnet_params_from_torch`` —
  torchvision-style state dicts (OIHW convs -> HWIO, fc.weight -> kernel.T,
  BatchNorm running stats -> flax batch_stats).

All functions take a ``dict[str, np.ndarray]`` (call ``.numpy()`` on torch
tensors first — torch itself is not required here), and return the
``{"params": ...}`` / ``{"params": ..., "batch_stats": ...}`` variables tree
that ``model.apply`` expects. Converted trees round-trip through
utils/checkpoint.py for SDFS distribution (the `train` verb's payload).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np


def _conv(w: np.ndarray) -> np.ndarray:
    """torch OIHW conv weight -> flax HWIO kernel."""
    return np.transpose(w, (2, 3, 1, 0))


def _dense(w: np.ndarray) -> np.ndarray:
    """torch [out, in] linear weight -> flax [in, out] kernel."""
    return np.transpose(w)


# ---------------------------------------------------------------------------
# ViT / CLIP (HuggingFace layouts)
# ---------------------------------------------------------------------------


def vit_params_from_hf(sd: Mapping[str, np.ndarray], num_layers: int) -> dict:
    """HF ViTForImageClassification state dict -> models.vit.ViT variables."""
    p = {
        "patch_embed": {
            "kernel": _conv(sd["vit.embeddings.patch_embeddings.projection.weight"]),
            "bias": sd["vit.embeddings.patch_embeddings.projection.bias"],
        },
        "cls_token": sd["vit.embeddings.cls_token"],
        "pos_embed": sd["vit.embeddings.position_embeddings"],
        "ln_final": {
            "scale": sd["vit.layernorm.weight"],
            "bias": sd["vit.layernorm.bias"],
        },
        "head": {"kernel": _dense(sd["classifier.weight"]), "bias": sd["classifier.bias"]},
    }
    for i in range(num_layers):
        h = f"vit.encoder.layer.{i}"
        p[f"block{i}"] = {
            "ln1": {"scale": sd[f"{h}.layernorm_before.weight"], "bias": sd[f"{h}.layernorm_before.bias"]},
            "ln2": {"scale": sd[f"{h}.layernorm_after.weight"], "bias": sd[f"{h}.layernorm_after.bias"]},
            "attn": {
                name: {
                    "kernel": _dense(sd[f"{h}.attention.attention.{name}.weight"]),
                    "bias": sd[f"{h}.attention.attention.{name}.bias"],
                }
                for name in ("query", "key", "value")
            }
            | {
                "out": {
                    "kernel": _dense(sd[f"{h}.attention.output.dense.weight"]),
                    "bias": sd[f"{h}.attention.output.dense.bias"],
                }
            },
            "mlp_in": {"kernel": _dense(sd[f"{h}.intermediate.dense.weight"]), "bias": sd[f"{h}.intermediate.dense.bias"]},
            "mlp_out": {"kernel": _dense(sd[f"{h}.output.dense.weight"]), "bias": sd[f"{h}.output.dense.bias"]},
        }
    return {"params": p}


def clip_params_from_hf(sd: Mapping[str, np.ndarray], num_layers: int) -> dict:
    """HF CLIPVisionModelWithProjection state dict -> CLIPVisionEncoder vars."""
    v = "vision_model"
    p = {
        "patch_embed": {"kernel": _conv(sd[f"{v}.embeddings.patch_embedding.weight"])},
        "cls_token": sd[f"{v}.embeddings.class_embedding"].reshape(1, 1, -1),
        "pos_embed": sd[f"{v}.embeddings.position_embedding.weight"][None],
        "pre_ln": {"scale": sd[f"{v}.pre_layrnorm.weight"], "bias": sd[f"{v}.pre_layrnorm.bias"]},
        "post_ln": {"scale": sd[f"{v}.post_layernorm.weight"], "bias": sd[f"{v}.post_layernorm.bias"]},
        "projection": {"kernel": _dense(sd["visual_projection.weight"])},
    }
    for i in range(num_layers):
        h = f"{v}.encoder.layers.{i}"
        p[f"block{i}"] = {
            "ln1": {"scale": sd[f"{h}.layer_norm1.weight"], "bias": sd[f"{h}.layer_norm1.bias"]},
            "ln2": {"scale": sd[f"{h}.layer_norm2.weight"], "bias": sd[f"{h}.layer_norm2.bias"]},
            "attn": {
                ours: {
                    "kernel": _dense(sd[f"{h}.self_attn.{theirs}.weight"]),
                    "bias": sd[f"{h}.self_attn.{theirs}.bias"],
                }
                for ours, theirs in (
                    ("query", "q_proj"),
                    ("key", "k_proj"),
                    ("value", "v_proj"),
                    ("out", "out_proj"),
                )
            },
            "mlp_in": {"kernel": _dense(sd[f"{h}.mlp.fc1.weight"]), "bias": sd[f"{h}.mlp.fc1.bias"]},
            "mlp_out": {"kernel": _dense(sd[f"{h}.mlp.fc2.weight"]), "bias": sd[f"{h}.mlp.fc2.bias"]},
        }
    return {"params": p}


# ---------------------------------------------------------------------------
# ResNet / AlexNet (torchvision layouts)
# ---------------------------------------------------------------------------


def _bn(sd: Mapping[str, np.ndarray], prefix: str) -> tuple[dict, dict]:
    params = {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]}
    stats = {"mean": sd[f"{prefix}.running_mean"], "var": sd[f"{prefix}.running_var"]}
    return params, stats


def resnet_params_from_torch(
    sd: Mapping[str, np.ndarray], stage_sizes: list[int], bottleneck: bool
) -> dict:
    """torchvision ResNet state dict -> models.resnet.ResNet variables
    (params + batch_stats). stage_sizes e.g. [2,2,2,2] for resnet18,
    bottleneck=True for resnet50-style blocks."""
    params: dict = {}
    stats: dict = {}

    params["conv_init"] = {"kernel": _conv(sd["conv1.weight"])}
    params["bn_init"], stats["bn_init"] = _bn(sd, "bn1")
    n_convs = 3 if bottleneck else 2
    for i, count in enumerate(stage_sizes):
        for j in range(count):
            ours = f"stage{i + 1}_block{j + 1}"
            theirs = f"layer{i + 1}.{j}"
            bp: dict = {}
            bs: dict = {}
            for c in range(n_convs):
                bp[f"Conv_{c}"] = {"kernel": _conv(sd[f"{theirs}.conv{c + 1}.weight"])}
                bp[f"BatchNorm_{c}"], bs[f"BatchNorm_{c}"] = _bn(sd, f"{theirs}.bn{c + 1}")
            if f"{theirs}.downsample.0.weight" in sd:
                bp["downsample_conv"] = {"kernel": _conv(sd[f"{theirs}.downsample.0.weight"])}
                bp["downsample_bn"], bs["downsample_bn"] = _bn(sd, f"{theirs}.downsample.1")
            params[ours] = bp
            stats[ours] = bs
    params["head"] = {"kernel": _dense(sd["fc.weight"]), "bias": sd["fc.bias"]}
    return {"params": params, "batch_stats": stats}


_ALEXNET_CONVS = {"conv1": 0, "conv2": 3, "conv3": 6, "conv4": 8, "conv5": 10}
_ALEXNET_DENSE = {"fc1": 1, "fc2": 4, "head": 6}


def alexnet_params_from_torch(sd: Mapping[str, np.ndarray]) -> dict:
    """torchvision AlexNet state dict -> models.alexnet.AlexNet variables."""
    p: dict = {}
    for ours, idx in _ALEXNET_CONVS.items():
        p[ours] = {
            "kernel": _conv(sd[f"features.{idx}.weight"]),
            "bias": sd[f"features.{idx}.bias"],
        }
    for ours, idx in _ALEXNET_DENSE.items():
        p[ours] = {
            "kernel": _dense(sd[f"classifier.{idx}.weight"]),
            "bias": sd[f"classifier.{idx}.bias"],
        }
    return {"params": p}
