"""AlexNet in Flax.

Replaces the reference's ``tch::vision::alexnet`` graph + ``alexnet.ot`` load
(reference: src/services.rs:520-524). Topology matches the canonical
(torchvision-style) AlexNet so common checkpoints map 1:1; written from
scratch in NHWC with bf16 compute / fp32 params.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        def conv(x, features, kernel, stride=1, pad=0, name=None):
            return nn.Conv(
                features,
                (kernel, kernel),
                (stride, stride),
                padding=[(pad, pad), (pad, pad)],
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name=name,
            )(x)

        x = x.astype(self.dtype)
        x = nn.relu(conv(x, 64, 11, stride=4, pad=2, name="conv1"))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(x, 192, 5, pad=2, name="conv2"))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(x, 384, 3, pad=1, name="conv3"))
        x = nn.relu(conv(x, 256, 3, pad=1, name="conv4"))
        x = nn.relu(conv(x, 256, 3, pad=1, name="conv5"))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # torchvision flattens CHW; we transpose NHWC→NCHW before flattening so
        # the classifier weight layout matches torchvision checkpoints.
        x = jnp.transpose(x, (0, 3, 1, 2)).reshape((x.shape[0], -1))
        dense = lambda f, name: nn.Dense(f, dtype=self.dtype, param_dtype=jnp.float32, name=name)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096, "fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(4096, "fc2")(x))
        x = dense(self.num_classes, "head")(x)
        return x.astype(jnp.float32)


def alexnet(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> AlexNet:
    return AlexNet(num_classes=num_classes, dtype=dtype)
