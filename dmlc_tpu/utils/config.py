"""Cluster configuration.

The reference compiles every constant in: leader candidates
(src/services.rs:26-30), ports (src/membership.rs:64, src/services.rs:31-32),
storage dirs + ssh user (src/services.rs:34-36), replication factor 4
(src/services.rs:328,359), heartbeat 1 s / failure timeout 3 s
(src/membership.rs:230,273), maintenance loop periods 3 s
(src/services.rs:188,201,213,529), query interval 0.5 s (src/services.rs:408).

Here all of that is a config object loadable from JSON and overridable per
field, so fleet topology is data, not code. Defaults mirror the reference's
constants so behavior is comparable out of the box.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class ClusterConfig:
    # --- identity / topology ---
    host: str = "127.0.0.1"
    gossip_port: int = 8850          # reference: src/membership.rs:64
    leader_port: int = 8851          # reference: src/services.rs:31
    member_port: int = 8852          # reference: src/services.rs:32
    leader_candidates: list[str] = field(default_factory=list)  # was LEADER_HOSTNAMES, src/services.rs:26-30

    # --- membership / failure detection ---
    heartbeat_interval_s: float = 1.0   # src/membership.rs:230
    failure_timeout_s: float = 3.0      # src/membership.rs:273
    ring_k: int = 2                     # k=2 symmetric ring neighbors, src/membership.rs:242
    # Max membership entries per gossip datagram. The reference ships the
    # FULL list every ping (membership.rs:242-257), O(N) per heartbeat; a
    # bounded random sample (self always included) keeps datagrams under the
    # UDP limit at any fleet size while anti-entropy still converges.
    gossip_max_entries: int = 64
    # SWIM-style indirect probes: a neighbor silent past HALF the failure
    # timeout gets ping-req'd through this many other members, whose relayed
    # acks keep a node with a merely-lossy direct link from being falsely
    # FAILED. 0 restores the reference's direct-only detector.
    indirect_probes: int = 2

    # --- SDFS ---
    storage_dir: str = "storage"        # src/services.rs:34
    replication_factor: int = 4         # src/services.rs:328,359
    rereplication_interval_s: float = 3.0  # src/services.rs:188
    # Bulk-transfer frame size: blobs larger than this stream disk-to-disk
    # as bounded range-read RPCs (the reference streamed via scp from disk,
    # services.rs:244-262); every hop holds O(chunk) memory.
    transfer_chunk_bytes: int = 8 * 1024 * 1024
    # Concurrent replica copies per placement (reference: 10-way scp fanout,
    # services.rs:367-373).
    replicate_fanout: int = 4
    # Anti-entropy scrub: every node re-hashes its stored blobs against
    # their committed sha256 sidecars on this cadence, quarantining and
    # reporting rot so healing re-places from verified copies (docs/SDFS.md).
    # 0 disables the loop (sdfs.scrub / the CLI verb still work on demand).
    scrub_interval_s: float = 30.0
    # Blobs re-hashed per scrub pass (round-robin cursor): bounds the I/O a
    # single pass can burn on a store full of multi-GB checkpoints.
    scrub_batch: int = 4

    # --- scheduler ---
    assignment_interval_s: float = 3.0  # src/services.rs:201
    leader_probe_interval_s: float = 3.0  # src/services.rs:529
    # The reference throttles to 1 query / 0.5 s per job (src/services.rs:408).
    # TPU-native dispatch is shard-based; this is the *shard* size per dispatch.
    dispatch_shard_size: int = 64
    rpc_concurrency: int = 10           # src/main.rs:61,79
    # Dispatcher threads per leader: max shards in flight across all jobs
    # (the reference dispatched fire-and-forget, services.rs:418-421; here
    # in-flight work is bounded and tracked per shard offset).
    dispatch_workers: int = 8
    # Backup-request the oldest outstanding shard on a second member once
    # fresh work runs out (tail hedging; dedup makes it exactly-once).
    hedge_tail: bool = True

    # --- overload control (docs/OVERLOAD.md) ---
    # Per-class deadline defaults, propagated in every RPC frame and
    # inherited by nested calls (cluster/deadline.py). rpc: small control
    # verbs (directory lookups, status, job.start); predict: one shard's
    # batched forward (also the scheduler's shard timeout); transfer: a
    # whole-blob SDFS replicate/pull (many chunk RPCs under one budget).
    rpc_deadline_s: float = 60.0
    predict_deadline_s: float = 120.0
    transfer_deadline_s: float = 300.0
    # Admission control: per-member bounded work queues. Up to max_inflight
    # requests execute while max_queue more wait; past that the request is
    # shed IMMEDIATELY with a typed Overloaded reply + retry-after hint
    # instead of queuing toward a guaranteed timeout. 0 disables a gate.
    predict_max_inflight: int = 32
    predict_max_queue: int = 128
    transfer_max_inflight: int = 16
    transfer_max_queue: int = 64
    shed_retry_after_s: float = 0.25
    # Retry budgets + circuit breakers (cluster/retrypolicy.py), shared by
    # scheduler dispatch, SDFS pulls, failover probes, and the announce
    # loop: retries to one destination spend a token bucket (rate/burst),
    # and breaker_threshold consecutive unreachable/deadline/overloaded
    # failures open a per-peer breaker that admits one half-open probe per
    # cooldown window.
    retry_rate_per_s: float = 1.0
    retry_burst: float = 5.0
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 5.0
    # Gray-failure ejection (scheduler/jobs.py): a member whose EWMA shard
    # latency exceeds gray_factor x the fleet median (and the absolute
    # floor, so microsecond-scale jitter on a fast fleet never ejects
    # anyone), or whose breaker keeps reopening, is demoted to a quarantine
    # tier — no new shards, one canary shard per probe interval — and
    # restored automatically when its latency recovers. 0 disables.
    gray_factor: float = 3.0
    gray_min_latency_s: float = 0.25
    gray_probe_interval_s: float = 5.0
    # Tenant declarations (cluster/tenant.py, docs/OVERLOAD.md §Priority
    # classes): {name: {"priority": "high"|"low", "share": 0..1}}. Each
    # bounded surface (admission gates, microbatcher queue, generate slot
    # table) derives per-tenant token quotas as share x its capacity, and
    # shed/brownout/evict ordering is low-priority-and-over-quota first.
    # Empty = single-tenant fleet, no quota enforcement (requests without
    # a tenant ride as tenant "default" either way).
    tenants: dict = field(default_factory=dict)
    # Bound on distinct tenant labels the metrics plane will track
    # (utils/metrics.TenantLabelGuard): past this, per-tenant series fold
    # into tenant="other" and metrics_label_overflow counts the folds — a
    # tenant-id flood cannot OOM the registry or the scrape tree.
    metrics_max_tenants: int = 16

    # --- elastic autoscaler (scheduler/autoscaler.py) -------------------
    # Burn-rate-driven actuator on the leader: grows/shrinks decode-tier
    # fan-out, generate slot/page budgets, and per-model replica targets
    # from SLO burn + cost lanes + HBM headroom. Decisions are hysteretic
    # (scale up on fast burn, down only after a sustained clear), bounded
    # by a per-window moves budget, and every one is flight-recorded with
    # its trigger + signal values.
    autoscaler_enabled: bool = False
    # Consecutive clear evaluations required before any scale-down (the
    # down-hysteresis; scale-up reacts on the first fast-burn edge).
    autoscaler_clear_windows: int = 3
    # Max actuation moves per evaluate() call across all targets.
    autoscaler_moves_budget: int = 2
    # Seconds between autoscaler evaluations (rides the obs scrape loop;
    # 0 = every scrape cycle).
    autoscaler_interval_s: float = 0.0
    # Refuse scale-ups that would push device HBM usage above this
    # fraction of the limit (headroom guard; 0 disables the check).
    autoscaler_hbm_ceiling: float = 0.9
    # Replica bounds for per-model replica targets.
    autoscaler_min_replicas: int = 1
    autoscaler_max_replicas: int = 8

    # --- live cost profiles / SLO / placement (docs/OBSERVABILITY.md §5) ---
    # Rolling profile windows (cluster/profile.py): per-(model x member x
    # stage) cost lanes the leader folds dispatch latencies and fleet
    # scrapes into. window_s x windows bounds the history; decay weights
    # each window by decay**age in every query.
    profile_window_s: float = 30.0
    profile_windows: int = 16
    profile_decay: float = 0.7
    # Persist the profile (diskio.atomic_write, sibling of storage_dir) so
    # a restarted leader warm-starts placement instead of re-learning the
    # fleet from zero. False disables both save and load.
    profile_persist: bool = True
    # Per-model serving objectives (scheduler/placement.SloEvaluator):
    # {model: {"latency_s": shard dispatch latency bound,
    #          "availability": target fraction under it (default 0.99)}}.
    # Empty = no SLO evaluation.
    slo_objectives: dict = field(default_factory=dict)
    # Multi-window burn-rate alerting: burn = frac-over-objective / error
    # budget. The fast window catches cliffs (pages in minutes), the slow
    # window catches smolder; thresholds follow the SRE-workbook shape.
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    slo_fast_burn: float = 14.0
    slo_slow_burn: float = 2.0
    # Profile-driven placement (scheduler/placement.PlacementAdvisor):
    # greedy cost-balanced assignment consulted by every assign pass, with
    # outlier exclusion past exclude_factor x the fleet median cost, a
    # relative-improvement hysteresis, and a bounded number of member
    # moves per window (rebalancing is itself a disturbance). False keeps
    # the round-robin assignment.
    placement_enabled: bool = True
    placement_max_moves: int = 2
    placement_window_s: float = 60.0
    placement_hysteresis: float = 0.15
    placement_exclude_factor: float = 3.0
    # Fleet-trace clock alignment decay alarm (cluster/observe.py): when
    # child-before-parent clamping in a merged trace exceeds this residual
    # skew on any node, a flight event fires (0 disables the alarm).
    trace_skew_alert_s: float = 0.05

    # --- fleet-scale observability (docs/OBSERVABILITY.md §6-7) ---------
    # Per-member scrape deadline + leader/delegate concurrency pool: one
    # wedged member costs one pool slot for one timeout, never the cycle.
    scrape_timeout_s: float = 2.0
    scrape_concurrency: int = 8
    # Delegated scrape tree (cluster/scrapetree.py): past min_members the
    # leader partitions the ring into spans of scrape_span_size members
    # (0 = ceil(sqrt(N))) and folds delegate partials — ~O(sqrt(N)) leader
    # RPCs per cycle instead of O(N). Below the threshold the direct
    # concurrent scrape is simpler and just as cheap.
    scrape_tree_enabled: bool = True
    scrape_tree_min_members: int = 16
    scrape_span_size: int = 0
    # Head-based trace sampling (utils/tracing): probability a fresh root
    # trace is kept (the bit rides the `t` frame field fleet-wide), and an
    # optional spans/s storage budget the adaptive controller steers the
    # effective rate toward (0 = controller off). Error/deadline-exceeded
    # spans are always recorded regardless of the rate.
    trace_sample_rate: float = 1.0
    trace_spans_per_s_budget: float = 0.0
    # On an SLO fast-burn edge, force-sample every trace fleet-wide for
    # this window (seconds; 0 disables) — burn investigations need whole
    # traces, not a 1% lottery.
    trace_burn_force_sample_s: float = 0.0

    # --- root-cause plane (cluster/critpath.py + sentinel.py, §9) -------
    # Per-request critical-path attribution: every node drains its sampled
    # span DAGs into per-(model, stage, member) critical-path seconds on
    # the scrape cadence; the leader folds the fleet table, names burn
    # culprits, and feeds the drift sentinel.
    critpath_enabled: bool = True
    # Rolling aggregation: windows of critpath_window_s seconds, the last
    # critpath_windows kept, older windows decayed by critpath_decay**age.
    critpath_window_s: float = 30.0
    critpath_windows: int = 16
    critpath_decay: float = 0.7
    # Latency drift sentinel (leader-side, scrape cadence): alert when a
    # lane's recent qNN self-time exceeds drift_factor x its decay-learned
    # baseline for confirm_windows consecutive ticks (clears below
    # clear_factor after the same streak); lanes with fewer than
    # min_samples recent requests are never judged.
    sentinel_enabled: bool = True
    sentinel_quantile: float = 90.0
    sentinel_drift_factor: float = 2.0
    sentinel_clear_factor: float = 1.3
    sentinel_min_samples: int = 20
    sentinel_confirm_windows: int = 3
    sentinel_baseline_decay: float = 0.8
    # On a drift alert, force-sample every trace fleet-wide this long
    # (seconds; 0 disables) so the drift window is densely traced.
    sentinel_force_sample_s: float = 30.0

    # --- device-plane telemetry (cluster/devicemon.py, OBSERVABILITY §8) ---
    # HBM watermark/alert poll cadence (0 disables the poll loop; gauges
    # still read live on every scrape).
    devicemon_poll_interval_s: float = 5.0
    # Compile-census warmup window: a program label compiling again this
    # long after its FIRST compile is a steady-state recompile (flight
    # event `recompile_steady_state` — runtime counterpart of rule A6).
    devicemon_warmup_s: float = 60.0
    # hbm_high_watermark flight event fires when bytes_in_use crosses this
    # fraction of bytes_limit (re-arms below 0.9x the line).
    devicemon_hbm_alert_fraction: float = 0.9
    # Per-chip peak FLOP/s override for MFU (0 = the per-platform table in
    # devicemon.PEAK_FLOPS: v5e bf16 for tpu, nominal 1 TF for cpu).
    devicemon_peak_flops: float = 0.0

    # --- dynamic request micro-batching (scheduler/worker.DynamicBatcher) ---
    # Coalesce concurrent small `job.predict` requests into device-shaped
    # batches: a request waits at most this long for peers before its batch
    # dispatches (batch fills dispatch immediately). 0 disables — each RPC
    # keeps its own engine call, the pre-batcher behavior. Gang (collective)
    # shards always bypass the batcher.
    microbatch_wait_s: float = 0.0

    # --- inference engine ---
    # Chips on this host, for the leader's capacity-weighted shard
    # placement (north star: "per-host chip topology ... ICI-local
    # placement"). 0 = autodetect from jax when it is already loaded.
    chips_per_host: int = 0
    batch_size: int = 256
    model_dtype: str = "bfloat16"
    data_dir: str = "test_files/imagenet_1k/train"
    synset_path: str = "synset_words.txt"
    # Resolve class images through SDFS (published via
    # scheduler/dataset.publish_corpus) instead of a pre-installed local
    # corpus — the BASELINE "4-node SDFS shard" configuration.
    data_from_sdfs: bool = False
    # The reference's two static jobs (src/services.rs:168-169); any registry
    # model name works here. kind="lm" registry entries (lm_small, lm_wide)
    # serve through the gang-aware LmBackend (docs/SHARDING.md).
    job_models: list[str] = field(default_factory=lambda: ["resnet18", "alexnet"])
    # --- gang-sharded LM serving (parallel/sharding.py, docs/SHARDING.md) -
    # lm_gang_devices pins the tensor/data mesh width an LM job uses
    # when dispatched as a gang (0 = the advisor-planned gang world size).
    # lm_hbm_budget_bytes is the per-chip resident budget the solo path
    # enforces: an LM whose replicated weights exceed it refuses solo
    # service with a typed error, steering the PlacementAdvisor toward a
    # gang (0 = no budget, solo always allowed). lm_prompt_len bounds the
    # synthetic prompt length encoded per query id.
    lm_gang_devices: int = 0
    lm_prompt_len: int = 16
    lm_hbm_budget_bytes: int = 0
    # Compile engines at node startup, before membership begins (the
    # reference's eager model load, src/services.rs:513-524). Lazy loading
    # risks compile-time GIL holds starving the heartbeat threads.
    eager_load: bool = True
    # Serve shards from the SDFS-distributed StableHLO artifact
    # (executables/<model>, published with the `export` verb) instead of
    # building the model from source — the native-serving deployment shape
    # (models/export.py): members need only the artifact + weights blobs.
    serve_from_executable: bool = False
    # --- fleet decode tier (cluster/decodetier.py, docs/INGEST.md) ---
    # Ship raw JPEG bytes to peers' job.decode verbs so ingest decode
    # scales with membership instead of capping at one host's cores.
    # min_batch: batches below this many images decode locally (the RPC
    # round-trip would cost more than the decode). max_bytes_per_rpc:
    # per-chunk wire bound — one oversized batch must never wedge a
    # control frame.
    decode_tier_enabled: bool = False
    decode_tier_min_batch: int = 16
    decode_tier_max_bytes_per_rpc: int = 4 * 1024 * 1024

    # --- generation serving (dmlc_tpu/generate/, docs/GENERATE.md) ---
    # Registry LMs (kind="lm", e.g. "lm_small") this node serves through
    # the continuous-batching generation worker. Empty = no generation
    # surface (the default; image-only nodes pay nothing).
    generate_models: list[str] = field(default_factory=list)
    # Slot table size: the decode step's FIXED batch shape — requests join/
    # leave between steps, the compiled program never reshapes.
    gen_max_slots: int = 8
    # Paged KV cache geometry: tokens per page, pages in the pool (page 0
    # is reserved scratch), and the padded prefill length (prompts longer
    # than gen_max_prefill are refused).
    gen_page_size: int = 16
    gen_num_pages: int = 128
    gen_max_prefill: int = 64
    # Requests allowed to WAIT for a slot beyond the table itself before
    # submits shed with a typed Overloaded (0 = shed at a full table).
    gen_max_waiting: int = 8
    # Streamed-chunk retention for a client that stopped polling.
    gen_session_ttl_s: float = 120.0
    # Leader-routed sessions (scheduler/genrouter.py): ledger capacity and
    # the default drain deadline — residents of a draining member get this
    # long to finish before the tick loop migrates them.
    gen_router_max_sessions: int = 256
    gen_drain_deadline_s: float = 30.0

    # --- control-plane authentication (cluster/auth.py) ---
    # Shared fleet key: every RPC frame and gossip datagram carries an
    # HMAC-SHA256 tag, and unauthenticated frames are dropped — reaching a
    # port no longer grants sdfs.delete / job.start (the reference leaned on
    # fleet ssh trust instead, services.rs:244-272). "" disables.
    auth_key: str = ""

    # --- multi-host global device mesh (parallel/multihost.py) ---
    # >1 enables leader-coordinated jax.distributed bootstrap: members call
    # node.join_global_mesh() and the process fleet forms ONE device mesh
    # spanning hosts (collectives ride ICI/DCN). 1 = single-process meshes.
    mesh_processes: int = 1
    mesh_coordinator_port: int = 8853

    def with_updates(self, **kw) -> "ClusterConfig":
        return dataclasses.replace(self, **kw)

    @classmethod
    def from_json(cls, path: str | Path) -> "ClusterConfig":
        raw = json.loads(Path(path).read_text())
        names = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - names
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        return cls(**raw)

    def to_json(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps(dataclasses.asdict(self), indent=2))
