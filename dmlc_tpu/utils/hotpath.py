"""Hot-path marker for the ingest/serving data plane.

``@hot_path`` declares that a function sits on a per-batch (or per-request)
serving path and must not pay per-call setup costs. It is a no-op at
runtime — the value is static: dmlc-lint rule H1 (tools/lint/rules/hotpath.py)
forbids constructing ``ThreadPoolExecutor``/``threading.Thread`` inside any
marked function, which is the regression class the PR-2 ingest overhaul
removed (a fresh pool spawned and joined on every ``load_batch`` /
``run_paths_stream`` call). Build pools once at module or object scope
(``ops/preprocess._host_pool``, ``parallel/inference._stage_pool``) and
submit to them from the hot path instead. The naming convention ``*_hot``
marks a function the same way for code that cannot take a decorator.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)


def hot_path(fn: F) -> F:
    """Mark ``fn`` as a serving hot path (see module docstring)."""
    fn.__dmlc_hot_path__ = True  # type: ignore[attr-defined]
    return fn
