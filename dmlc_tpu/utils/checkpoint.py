"""Checkpoint / resume: train state serialization + versioned SDFS storage.

The reference has no model/optimizer checkpointing (inference-only); its two
resume mechanisms are the replicated job cursor and SDFS's keep-every-version
store (SURVEY.md §5 "Checkpoint / resume"). This module completes the
capability for real training: a TrainState serializes with flax.serialization
(msgpack bytes), and the versioned SDFS is the natural checkpoint store —
every save is a new replicated version of one well-known file, restore pulls
any version, and leader failover cannot lose checkpoints because the
directory is mirrored to standbys.

Local-directory save/restore is also provided for single-host use. Device
placement on restore is the caller's concern (make_train_step re-shards)."""

from __future__ import annotations

import logging
from pathlib import Path

from flax import serialization

log = logging.getLogger(__name__)


def state_to_bytes(state) -> bytes:
    """Serialize any flax-style pytree state (TrainState included)."""
    return serialization.to_bytes(state)


def state_from_bytes(template, data: bytes):
    """Restore into the shape of ``template`` (same pytree structure)."""
    return serialization.from_bytes(template, data)


# ---------------------------------------------------------------------------
# Local directory checkpoints
# ---------------------------------------------------------------------------


def save_local(state, directory: str | Path, step: int) -> Path:
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"checkpoint_{step:08d}.msgpack"
    tmp = path.with_suffix(".tmp")
    tmp.write_bytes(state_to_bytes(state))
    tmp.rename(path)  # atomic publish: a crash never leaves a torn file
    return path


def latest_local(directory: str | Path) -> tuple[int, Path] | None:
    d = Path(directory)
    if not d.exists():
        return None
    ckpts = sorted(d.glob("checkpoint_*.msgpack"))
    if not ckpts:
        return None
    path = ckpts[-1]
    step = int(path.stem.split("_")[1])
    return step, path


def restore_local(template, directory: str | Path):
    """-> (state, step) from the newest checkpoint, or (template, 0)."""
    found = latest_local(directory)
    if found is None:
        return template, 0
    step, path = found
    return state_from_bytes(template, path.read_bytes()), step


# ---------------------------------------------------------------------------
# SDFS-backed checkpoints (replicated + versioned)
# ---------------------------------------------------------------------------


class SdfsCheckpointer:
    """Checkpoints as versions of one SDFS file.

    save() puts a new version (replicated rf-ways by the leader); restore()
    pulls the latest — or any explicit — version. The step number rides in a
    small header so restore can report where training resumes."""

    MAGIC = b"DMLCCKPT"

    def __init__(self, sdfs_client, name: str = "checkpoints/train_state"):
        self.sdfs = sdfs_client
        self.name = name

    def save(self, state, step: int) -> int:
        payload = self.MAGIC + int(step).to_bytes(8, "big") + state_to_bytes(state)
        reply = self.sdfs.put_bytes(payload, self.name)
        log.info("checkpoint step %d -> %s v%d", step, self.name, reply["version"])
        return reply["version"]

    def restore(self, template, version: int | None = None):
        """-> (state, step). Raises RpcError if no checkpoint exists."""
        _, payload = self.sdfs.get_bytes(self.name, version=version)
        if payload[: len(self.MAGIC)] != self.MAGIC:
            raise ValueError(f"{self.name} is not a dmlc checkpoint")
        off = len(self.MAGIC)
        step = int.from_bytes(payload[off : off + 8], "big")
        state = state_from_bytes(template, payload[off + 8 :])
        return state, step
