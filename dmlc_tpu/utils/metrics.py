"""Latency/accuracy metrics with percentile reporting, bounded memory.

Capability parity with the reference's ``jobs`` report, which aggregates
per-query wall-clock durations into mean/std/median/p90/p95/p99 via the
``histogram`` crate (reference: src/main.rs:282-309) and tracks
correct/finished counts per job (src/services.rs:74-80).

Unlike the reference's grow-forever Vec of durations (services.rs:78), this
collector is O(1) memory at any query volume: count/mean/std come from exact
Welford moments, percentiles from a fixed-size reservoir (Algorithm R with a
deterministic PRNG so simulator runs reproduce). That also bounds the wire
payload standby leaders mirror every probe interval — at the >10k img/s
target an exact sample list would cross the RPC frame limit within hours.
"""

from __future__ import annotations

import math
import random
import threading


class Counters:
    """Thread-safe named counters + high-water gauges for overload
    observability (docs/OVERLOAD.md): shed, deadline_exceeded,
    breaker_open, gray_demotions, queue-depth high-waters, ... One instance
    per node, shared by the admission gates, the retry policy, and the
    scheduler, surfaced through ``leader.status`` and the CLI ``status``
    verb. O(1) per update; the snapshot is a plain dict for the wire."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._high: dict[str, float] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def observe_high(self, name: str, value: float) -> None:
        """Record a high-water mark: keeps the max ever observed."""
        with self._lock:
            if value > self._high.get(name, float("-inf")):
                self._high[name] = value

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counts)
            out.update({f"{k}_high": v for k, v in self._high.items()})
            return out


class LatencyStats:
    """Streaming duration collector (seconds) with percentile summary."""

    RESERVOIR_SIZE = 4096

    def __init__(self, samples: list[float] | None = None):
        self.n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.reservoir: list[float] = []
        self._offers = 0  # reservoir offers seen (Algorithm R denominator)
        self._rng = random.Random(0xD31C)
        if samples:
            self.extend(samples)

    # ---- recording -----------------------------------------------------

    def record(self, seconds: float) -> None:
        self._moments_add(float(seconds), 1)
        self._reservoir_offer(float(seconds))

    def record_many(self, seconds: float, count: int) -> None:
        """Record ``count`` queries that shared one measured duration (a
        shard's amortized per-query latency). Moments are exact; the
        reservoir takes one representative offer per call, which keeps
        every shard equally weighted in the percentile sketch."""
        if count <= 0:
            return
        self._moments_add(float(seconds), int(count))
        self._reservoir_offer(float(seconds))

    def extend(self, seconds: list[float]) -> None:
        for s in seconds:
            self.record(float(s))

    def _moments_add(self, value: float, count: int) -> None:
        # Chan et al. parallel update: fold `count` copies of `value` in.
        n2 = self.n + count
        delta = value - self._mean
        self._mean += delta * count / n2
        self._m2 += delta * delta * count * self.n / n2
        self.n = n2

    def _reservoir_offer(self, value: float) -> None:
        # Algorithm R: the i-th offer is kept with probability K/i, so the
        # reservoir stays a uniform sample of ALL offers, not a recency
        # window. The denominator is offers-so-far, not reservoir size.
        self._offers += 1
        if len(self.reservoir) < self.RESERVOIR_SIZE:
            self.reservoir.append(value)
            return
        j = self._rng.randrange(self._offers)
        if j < self.RESERVOIR_SIZE:
            self.reservoir[j] = value

    # ---- queries -------------------------------------------------------

    def __len__(self) -> int:
        return self.n

    @property
    def mean(self) -> float:
        return self._mean if self.n else float("nan")

    @property
    def std(self) -> float:
        if self.n == 0:
            return float("nan")
        if self.n < 2:
            return 0.0
        return math.sqrt(self._m2 / (self.n - 1))

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the reservoir, p in [0, 100]."""
        if not self.reservoir:
            return float("nan")
        xs = sorted(self.reservoir)
        rank = max(1, math.ceil(p / 100.0 * len(xs)))
        return xs[min(rank, len(xs)) - 1]

    def summary(self) -> dict[str, float]:
        """The reference's report shape: mean/std/median/p90/p95/p99."""
        return {
            "count": float(self.n),
            "mean": self.mean,
            "std": self.std,
            "median": self.percentile(50),
            "p90": self.percentile(90),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }

    def merge(self, other: "LatencyStats") -> None:
        if other.n == 0:
            return
        n2 = self.n + other.n
        delta = other._mean - self._mean
        self._mean += delta * other.n / n2
        self._m2 += other._m2 + delta * delta * self.n * other.n / n2
        self.n = n2
        for v in other.reservoir:
            self._reservoir_offer(v)

    # ---- wire ----------------------------------------------------------

    def to_wire(self) -> dict:
        return {
            "n": self.n,
            "mean": self._mean,
            "m2": self._m2,
            "offers": self._offers,
            "reservoir": list(self.reservoir),
        }

    @classmethod
    def from_wire(cls, w) -> "LatencyStats":
        if isinstance(w, list):  # legacy raw-sample form
            return cls(samples=w)
        out = cls()
        out.n = int(w["n"])
        out._mean = float(w["mean"])
        out._m2 = float(w["m2"])
        out.reservoir = [float(x) for x in w["reservoir"]][: cls.RESERVOIR_SIZE]
        out._offers = int(w.get("offers", len(out.reservoir)))
        return out
