"""Latency/accuracy metrics with percentile reporting.

Capability parity with the reference's ``jobs`` report, which aggregates
per-query wall-clock durations into mean/std/median/p90/p95/p99 via the
``histogram`` crate (reference: src/main.rs:282-309) and tracks
correct/finished counts per job (src/services.rs:74-80).

Here durations are recorded per *batch* as well as per *query* — on TPU the
unit of execution is a sharded batch, so we keep both: per-batch device
latency (what the chip did) and per-query amortized latency (what the
reference reported).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass
class LatencyStats:
    """Streaming collection of durations (seconds) with percentile summary."""

    samples: list[float] = field(default_factory=list)

    def record(self, seconds: float) -> None:
        self.samples.append(float(seconds))

    def extend(self, seconds: list[float]) -> None:
        self.samples.extend(float(s) for s in seconds)

    def __len__(self) -> int:
        return len(self.samples)

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile, p in [0, 100]."""
        if not self.samples:
            return float("nan")
        xs = sorted(self.samples)
        rank = max(1, math.ceil(p / 100.0 * len(xs)))
        return xs[min(rank, len(xs)) - 1]

    @property
    def mean(self) -> float:
        return sum(self.samples) / len(self.samples) if self.samples else float("nan")

    @property
    def std(self) -> float:
        if len(self.samples) < 2:
            return 0.0 if self.samples else float("nan")
        m = self.mean
        return math.sqrt(sum((x - m) ** 2 for x in self.samples) / (len(self.samples) - 1))

    def summary(self) -> dict[str, float]:
        """The reference's report shape: mean/std/median/p90/p95/p99."""
        return {
            "count": float(len(self.samples)),
            "mean": self.mean,
            "std": self.std,
            "median": self.percentile(50),
            "p90": self.percentile(90),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }

    def merge(self, other: "LatencyStats") -> None:
        self.samples.extend(other.samples)

    def to_wire(self) -> list[float]:
        return list(self.samples)

    @classmethod
    def from_wire(cls, samples: list[float]) -> "LatencyStats":
        return cls(samples=list(samples))
