"""Latency/accuracy metrics with percentile reporting, bounded memory.

Capability parity with the reference's ``jobs`` report, which aggregates
per-query wall-clock durations into mean/std/median/p90/p95/p99 via the
``histogram`` crate (reference: src/main.rs:282-309) and tracks
correct/finished counts per job (src/services.rs:74-80).

Unlike the reference's grow-forever Vec of durations (services.rs:78), this
collector is O(1) memory at any query volume: count/mean/std come from exact
Welford moments, percentiles from a fixed-size reservoir (Algorithm R with a
deterministic PRNG so simulator runs reproduce). That also bounds the wire
payload standby leaders mirror every probe interval — at the >10k img/s
target an exact sample list would cross the RPC frame limit within hours.
"""

from __future__ import annotations

import bisect
import math
import random
import re
import threading
from typing import Callable


class Counters:
    """Thread-safe named counters + high-water gauges for overload
    observability (docs/OVERLOAD.md): shed, deadline_exceeded,
    breaker_open, gray_demotions, queue-depth high-waters, ... One instance
    per node, shared by the admission gates, the retry policy, and the
    scheduler, surfaced through ``leader.status`` and the CLI ``status``
    verb. O(1) per update; the snapshot is a plain dict for the wire."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._high: dict[str, float] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def observe_high(self, name: str, value: float) -> None:
        """Record a high-water mark: keeps the max ever observed."""
        with self._lock:
            if value > self._high.get(name, float("-inf")):
                self._high[name] = value

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = dict(self._counts)
            out.update({f"{k}_high": v for k, v in self._high.items()})
            return out


class TenantLabelGuard:
    """Label-cardinality bound for per-tenant metric series.

    Every per-tenant gauge/counter/lane name passes its tenant through
    ``label()`` first: the first ``max_tenants`` distinct tenants keep
    their own label, everything after folds into ``tenant="other"`` and
    increments the ``metrics_label_overflow`` counter — so a caller
    flooding the fleet with fresh tenant ids can inflate ONE bucket, not
    the registry, the scrape-tree payloads, or the Prometheus exposition
    (docs/OBSERVABILITY.md). Admission *quota* accounting deliberately
    does NOT ride this guard (cluster/tenant.TenantLedger keys on the
    real name — quotas must bind to the actual tenant); only the metrics
    plane folds. ``max_tenants <= 0`` disables the bound."""

    OTHER = "other"

    def __init__(self, max_tenants: int = 16, counters: Counters | None = None):
        self.max_tenants = int(max_tenants)
        self.counters = counters
        self._lock = threading.Lock()
        self._seen: set[str] = set()
        self.overflows = 0

    def label(self, tenant: str) -> str:
        """The bounded metrics label for ``tenant`` (sticky: a tenant that
        ever passed keeps passing; one that ever folded keeps folding)."""
        with self._lock:
            if tenant in self._seen or self.max_tenants <= 0:
                self._seen.add(tenant)
                return tenant
            if len(self._seen) < self.max_tenants:
                self._seen.add(tenant)
                return tenant
            self.overflows += 1
            if self.counters is not None:
                self.counters.inc("metrics_label_overflow")
            return self.OTHER

    def tracked(self) -> list[str]:
        with self._lock:
            return sorted(self._seen)


class LatencyStats:
    """Streaming duration collector (seconds) with percentile summary."""

    RESERVOIR_SIZE = 4096
    # Fixed log-spaced histogram bounds (seconds). Exact per-bucket counts
    # complement the reservoir quantiles: buckets aggregate losslessly
    # across nodes and ship as a proper Prometheus histogram family, so
    # fleet-wide p99 can be computed server-side (histogram_quantile) even
    # where a merged reservoir would be an approximation of approximations.
    BUCKET_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

    def __init__(self, samples: list[float] | None = None):
        self.n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.reservoir: list[float] = []
        self._offers = 0  # reservoir offers seen (Algorithm R denominator)
        self._rng = random.Random(0xD31C)
        # Per-bucket (non-cumulative) counts; the last slot is +Inf overflow.
        self.buckets = [0] * (len(self.BUCKET_BOUNDS) + 1)
        if samples:
            self.extend(samples)

    # ---- recording -----------------------------------------------------

    def record(self, seconds: float) -> None:
        self._moments_add(float(seconds), 1)
        self._reservoir_offer(float(seconds))

    def record_many(self, seconds: float, count: int) -> None:
        """Record ``count`` queries that shared one measured duration (a
        shard's amortized per-query latency). Moments are exact; the
        reservoir takes one representative offer per call, which keeps
        every shard equally weighted in the percentile sketch."""
        if count <= 0:
            return
        self._moments_add(float(seconds), int(count))
        self._reservoir_offer(float(seconds))

    def extend(self, seconds: list[float]) -> None:
        for s in seconds:
            self.record(float(s))

    def _moments_add(self, value: float, count: int) -> None:
        # Chan et al. parallel update: fold `count` copies of `value` in.
        n2 = self.n + count
        delta = value - self._mean
        self._mean += delta * count / n2
        self._m2 += delta * delta * count * self.n / n2
        self.n = n2
        # bisect_left puts value == bound in that bound's bucket (le=bound).
        self.buckets[bisect.bisect_left(self.BUCKET_BOUNDS, value)] += count

    def _reservoir_offer(self, value: float) -> None:
        # Algorithm R: the i-th offer is kept with probability K/i, so the
        # reservoir stays a uniform sample of ALL offers, not a recency
        # window. The denominator is offers-so-far, not reservoir size.
        self._offers += 1
        if len(self.reservoir) < self.RESERVOIR_SIZE:
            self.reservoir.append(value)
            return
        j = self._rng.randrange(self._offers)
        if j < self.RESERVOIR_SIZE:
            self.reservoir[j] = value

    # ---- queries -------------------------------------------------------

    def __len__(self) -> int:
        return self.n

    @property
    def mean(self) -> float:
        return self._mean if self.n else float("nan")

    @property
    def std(self) -> float:
        if self.n == 0:
            return float("nan")
        if self.n < 2:
            return 0.0
        return math.sqrt(self._m2 / (self.n - 1))

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the reservoir, p in [0, 100]."""
        if not self.reservoir:
            return float("nan")
        xs = sorted(self.reservoir)
        rank = max(1, math.ceil(p / 100.0 * len(xs)))
        return xs[min(rank, len(xs)) - 1]

    def summary(self) -> dict:
        """The reference's report shape (mean/std/median/p90/p95/p99) plus
        cumulative histogram bucket counts keyed by upper bound (``le``
        semantics; ``"+Inf"`` last) — the exact counterpart the Prometheus
        exposition renders as a histogram family."""
        cum, buckets = 0, {}
        for bound, count in zip(self.BUCKET_BOUNDS, self.buckets):
            cum += count
            buckets[repr(bound)] = cum
        buckets["+Inf"] = cum + self.buckets[-1]
        return {
            "count": float(self.n),
            "mean": self.mean,
            "std": self.std,
            "median": self.percentile(50),
            "p90": self.percentile(90),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
            "buckets": buckets,
        }

    def merge(self, other: "LatencyStats") -> None:
        if other.n == 0:
            return
        n2 = self.n + other.n
        delta = other._mean - self._mean
        self._mean += delta * other.n / n2
        self._m2 += other._m2 + delta * delta * self.n * other.n / n2
        self.n = n2
        self.buckets = [a + b for a, b in zip(self.buckets, other.buckets)]
        self._merge_reservoirs(other)

    def _merge_reservoirs(self, other: "LatencyStats") -> None:
        """WEIGHTED reservoir merge. Each side's reservoir is a uniform
        sample of ``_offers`` underlying observations. Offering ``other``'s
        elements one by one into Algorithm R (the old code) ignored that
        multiplicity and under-weighted any peer whose offer count exceeds
        its reservoir size — a member that served 100k queries merged like
        one that served 4k.

        Correct merge: a uniform sample of the UNION stream. When both
        reservoirs are exact (every offer kept) and fit, the union IS that
        sample. Otherwise each merged slot picks a side with probability
        proportional to its offer count and a uniform element from that
        side's reservoir — expected composition exactly matches the true
        mixture for any weights (with-replacement within a side is fine:
        each reservoir already stands in for its whole stream). Drawn from
        this instance's seeded PRNG so merges stay deterministic."""
        if not other.reservoir:
            return
        mine, theirs = self.reservoir, other.reservoir
        na, nb = self._offers, other._offers
        if na == len(mine) and nb == len(theirs) and na + nb <= self.RESERVOIR_SIZE:
            mine.extend(theirs)
            self._offers = na + nb
            return
        # One side inexact implies its offers exceed RESERVOIR_SIZE, so
        # na + nb > RESERVOIR_SIZE here and the merged sample is full-size.
        p_other = nb / (na + nb)
        self.reservoir = [
            theirs[self._rng.randrange(len(theirs))]
            if (not mine or self._rng.random() < p_other)
            else mine[self._rng.randrange(len(mine))]
            for _ in range(self.RESERVOIR_SIZE)
        ]
        self._offers = na + nb

    # ---- wire ----------------------------------------------------------

    def to_wire(self) -> dict:
        return {
            "n": self.n,
            "mean": self._mean,
            "m2": self._m2,
            "offers": self._offers,
            "reservoir": list(self.reservoir),
            "buckets": list(self.buckets),
        }

    @classmethod
    def from_wire(cls, w) -> "LatencyStats":
        if isinstance(w, list):  # legacy raw-sample form
            return cls(samples=w)
        out = cls()
        out.n = int(w["n"])
        out._mean = float(w["mean"])
        out._m2 = float(w["m2"])
        out.reservoir = [float(x) for x in w["reservoir"]][: cls.RESERVOIR_SIZE]
        out._offers = int(w.get("offers", len(out.reservoir)))
        # Pre-histogram peers omit buckets; their counts stay zero (the
        # renderer skips a histogram whose bucket total lags n).
        wb = w.get("buckets")
        if wb is not None and len(wb) == len(out.buckets):
            out.buckets = [int(x) for x in wb]
        return out


# ---------------------------------------------------------------------------
# Fleet merging: fold many mergeable snapshots into one, exactly
# ---------------------------------------------------------------------------


def merge_counter_dicts(into: dict, part: dict) -> None:
    """Fold one counters dict into an accumulator: plain counters ADD;
    ``*_high`` watermarks take the MAX (a fleet high-water mark is the
    highest any node saw, not a sum)."""
    for name, value in (part or {}).items():
        if name.endswith("_high"):
            prev = into.get(name)
            into[name] = value if prev is None else max(prev, value)
        else:
            into[name] = into.get(name, 0) + value


def merge_mergeable_snapshots(parts) -> dict:
    """Fold ``Registry.snapshot(mergeable=True)``-shaped dicts into ONE
    mergeable snapshot. Associative — a scrape-tree delegate folds its
    span's members and the leader folds delegate partials with the same
    function, and the result is counter-exact either way: counters and
    histogram bucket counts are integer sums, latency moments merge via
    Chan's update, reservoirs offer-weighted (``LatencyStats.merge``).
    Gauges SUM numeric values (fleet totals: pages free, queue depths);
    ``nodes`` counts contributors so per-node means stay recoverable."""
    counters: dict = {}
    gauges: dict = {}
    latency: dict[str, LatencyStats] = {}
    nodes = 0
    for part in parts:
        if not part:
            continue
        nodes += int(part.get("nodes", 1))
        merge_counter_dicts(counters, part.get("counters") or {})
        for name, value in (part.get("gauges") or {}).items():
            if value is None:
                continue
            gauges[name] = gauges.get(name, 0.0) + float(value)
        for name, wire in (part.get("latency") or {}).items():
            stats = latency.get(name)
            if stats is None:
                latency[name] = LatencyStats.from_wire(wire)
            else:
                stats.merge(LatencyStats.from_wire(wire))
    return {
        "counters": counters,
        "gauges": gauges,
        "latency": {n: s.to_wire() for n, s in sorted(latency.items())},
        "nodes": nodes,
    }


def summarize_mergeable(snapshot: dict) -> dict:
    """Convert a mergeable snapshot to the standard render shape (latency
    wire records -> ``summary()`` dicts), so CLI / Prometheus /
    ``CostProfiler.ingest_scrape`` consumers see exactly what a direct
    ``Registry.snapshot()`` would have handed them."""
    out = dict(snapshot)
    out["latency"] = {
        n: LatencyStats.from_wire(w).summary()
        for n, w in sorted((snapshot.get("latency") or {}).items())
    }
    return out


# ---------------------------------------------------------------------------
# Registry: one node's whole metric surface behind one snapshot
# ---------------------------------------------------------------------------

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(prefix: str, name: str) -> str:
    return f"{prefix}_{_PROM_NAME_RE.sub('_', name)}"


class Registry:
    """Unifies a node's ``Counters``, named ``LatencyStats``, and gauges
    behind ONE snapshot (docs/OBSERVABILITY.md) — the payload of the
    ``obs.metrics`` RPC the leader scrapes fleet-wide, and the source of
    the Prometheus text exposition.

    Naming conventions: counters and gauges are ``snake_case`` (gauges
    suffixed with the thing they measure, e.g. ``predict_gate_active``);
    latency collectors are ``component/verb`` like span names. Gauges are
    registered as zero-arg callables read at snapshot time — a gauge whose
    read raises reports ``None`` rather than failing the scrape.
    """

    def __init__(self, counters: Counters | None = None):
        self.counters = counters if counters is not None else Counters()
        self._latency: dict[str, LatencyStats] = {}
        self._gauges: dict[str, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def latency(self, name: str) -> LatencyStats:
        """The named latency collector, created on first use."""
        with self._lock:
            stats = self._latency.get(name)
            if stats is None:
                stats = self._latency[name] = LatencyStats()
            return stats

    def gauge(self, name: str, read: Callable[[], float]) -> None:
        with self._lock:
            self._gauges[name] = read

    def snapshot(self, mergeable: bool = False) -> dict:
        """Wire-shaped view of everything: ``{"counters": {...},
        "gauges": {...}, "latency": {name: summary}}``. With ``mergeable``
        the latency section carries ``LatencyStats.to_wire()`` records
        instead of summaries — the exact-merge form scrape-tree delegates
        request so span partials fold counter-exactly into one fleet
        snapshot (docs/OBSERVABILITY.md §6)."""
        with self._lock:
            if mergeable:
                latency = {n: s.to_wire() for n, s in sorted(self._latency.items())}
            else:
                latency = {n: s.summary() for n, s in sorted(self._latency.items())}
            gauges: dict = {}
            for name, read in sorted(self._gauges.items()):
                try:
                    gauges[name] = float(read())
                except Exception:
                    gauges[name] = None  # a broken gauge must not fail the scrape
        return {"counters": self.counters.snapshot(), "gauges": gauges,
                "latency": latency}

    def prometheus_text(self, prefix: str = "dmlc", labels: str = "") -> str:
        """Prometheus text-format exposition of ``snapshot()``. ``labels``
        is a pre-rendered label body (e.g. ``node="10.0.0.1:8852"``) the
        fleet exposition uses to distinguish scraped nodes."""
        return render_prometheus(self.snapshot(), prefix=prefix, labels=labels)


def render_prometheus(snapshot: dict, prefix: str = "dmlc", labels: str = "") -> str:
    """Render one ``Registry.snapshot()``-shaped dict as Prometheus text.
    Module-level so the leader can render snapshots it scraped off other
    nodes (cluster/observe.py) identically to local ones."""
    body = f"{{{labels}}}" if labels else ""

    def qbody(extra: str) -> str:
        inner = ",".join(x for x in (labels, extra) if x)
        return f"{{{inner}}}"

    lines: list[str] = []
    for name, value in sorted((snapshot.get("counters") or {}).items()):
        metric = _prom_name(prefix, name)
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric}{body} {value}")
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        if value is None:
            continue
        metric = _prom_name(prefix, name)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric}{body} {value}")
    for name, s in sorted((snapshot.get("latency") or {}).items()):
        metric = _prom_name(prefix, name) + "_seconds"
        lines.append(f"# TYPE {metric} summary")
        for q, key in (("0.5", "median"), ("0.9", "p90"), ("0.95", "p95"),
                       ("0.99", "p99")):
            v = s.get(key)
            if v is not None and not math.isnan(v):
                qlabel = f'quantile="{q}"'
                lines.append(f"{metric}{qbody(qlabel)} {v}")
        count = s.get("count", 0.0)
        mean = s.get("mean", float("nan"))
        lines.append(f"{metric}_count{body} {int(count)}")
        if count and not math.isnan(mean):
            lines.append(f"{metric}_sum{body} {mean * count}")
        # Sibling histogram family: exact cumulative bucket counts (lossless
        # under cross-node aggregation, unlike quantiles). Emitted only when
        # the buckets cover every observation — a legacy peer's snapshot
        # without buckets must not render a histogram that contradicts its
        # own _count.
        buckets = s.get("buckets") or {}
        total = buckets.get("+Inf", 0)
        if total and total == int(count):
            hist = _prom_name(prefix, name) + "_hist_seconds"
            lines.append(f"# TYPE {hist} histogram")
            for le, cum in buckets.items():
                lelabel = f'le="{le}"'
                lines.append(f"{hist}_bucket{qbody(lelabel)} {int(cum)}")
            lines.append(f"{hist}_count{body} {total}")
            if not math.isnan(mean):
                lines.append(f"{hist}_sum{body} {mean * count}")
    return "\n".join(lines) + ("\n" if lines else "")
