"""Synthetic evaluation corpus: the reference's fixture-dataset shape.

The reference ships ``test_files/imagenet_1k/train/<synset>/<one JPEG>`` (one
image per each of 1,000 classes) plus ``synset_words.txt`` mapping synset ids
to labels (src/services.rs:170-184, 485-490). That corpus is not
redistributable here, so this module *generates* one with the same layout:
deterministic random JPEGs, one directory per synthetic synset. It powers the
end-to-end (JPEG -> top-1) bench mode and any test that wants a real
decode-from-disk path without shipping binary fixtures.
"""

from __future__ import annotations

import shutil
from pathlib import Path

import numpy as np


def write_synset_words(path: str | Path, n_classes: int) -> Path:
    """``synset_words.txt`` with synthetic ids n00000000..: one per class."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(f"n{i:08d} synthetic class {i}\n" for i in range(n_classes)))
    return path


def _reusable(
    root: Path, n_classes: int, images_per_class: int, kind: str
) -> tuple[Path, Path] | None:
    """Existing corpora with the right shape AND kind are reused, not
    regenerated. Reuse only when BOTH dimensions match: a corpus with fewer
    images per class than requested would silently shrink whatever
    measurement asked for this shape. The kind marker keeps the two
    generators from adopting each other's output at a shared root — an
    i.i.d. corpus reused by generate_learnable would cap training at
    chance with no hint why (a pre-marker corpus counts as "iid", which is
    what every pre-marker corpus was)."""
    data_dir = root / "train"
    synset_path = root / "synset_words.txt"
    if not (synset_path.exists() and data_dir.exists()):
        return None
    marker = root / ".corpus_kind"
    existing_kind = marker.read_text().strip() if marker.exists() else "iid"
    if existing_kind != kind:
        return None
    dirs = [d for d in data_dir.iterdir() if d.is_dir()]
    if len(dirs) >= n_classes and all(
        sum(1 for f in d.iterdir() if f.is_file()) >= images_per_class
        for d in dirs[:n_classes]
    ):
        return data_dir, synset_path
    return None


def _fresh_tree(root: Path) -> None:
    """Remove a non-reusable corpus before regenerating. The generators
    write only the first n_classes dirs / images_per_class files; without
    this wipe, leftover class dirs and higher-index images from a previous
    different-kind (or bigger) corpus at the same root would survive under
    the new ``.corpus_kind`` marker, and any consumer that globs class
    dirs would see mixed-kind data."""
    shutil.rmtree(root / "train", ignore_errors=True)
    (root / ".corpus_kind").unlink(missing_ok=True)


def generate_learnable(
    root: str | Path,
    n_classes: int = 40,
    images_per_class: int = 8,
    size: int = 32,
    seed: int = 0,
    noise: int = 28,
    quality: int = 90,
) -> tuple[Path, Path]:
    """A corpus a model can actually LEARN: every image of class ``i`` is a
    class-specific low-frequency pattern (deterministic in ``i``) plus
    per-image noise, JPEG-encoded. ``img0.jpg`` in each class directory is
    the held-out sample the cluster's predict path evaluates on
    (ops/preprocess.class_image_path picks the first file) — train on
    ``img1..`` and the jobs report's accuracy measures generalization to
    an unseen image of each class, not memorization.

    ``generate`` (below) keeps the reference fixture's *shape* with
    unlearnable i.i.d. images; this variant exists for the train→publish→
    hot-swap→accuracy loop (reference ships pretrained checkpoints and
    reports live accuracy, services.rs:74-80,139-144 — here the framework
    trains the checkpoint itself). Same layout, same reuse rule.
    """
    from PIL import Image

    root = Path(root)
    reuse = _reusable(root, n_classes, images_per_class, "learnable")
    if reuse is not None:
        return reuse
    _fresh_tree(root)

    data_dir = root / "train"
    synset_path = write_synset_words(root / "synset_words.txt", n_classes)
    rng = np.random.default_rng(seed)
    low = 4  # class signature lives in the lowest frequencies
    for i in range(n_classes):
        d = data_dir / f"n{i:08d}"
        d.mkdir(parents=True, exist_ok=True)
        sig_rng = np.random.default_rng(10_000 + i)  # per-class, not per-run
        base = Image.fromarray(
            sig_rng.integers(40, 216, (low, low, 3), np.uint8)
        ).resize((size, size), Image.BILINEAR)
        base = np.asarray(base, dtype=np.int16)
        for j in range(images_per_class):
            jitter = rng.integers(-noise, noise + 1, (size, size, 3), np.int16)
            im = np.clip(base + jitter, 0, 255).astype(np.uint8)
            Image.fromarray(im).save(d / f"img{j}.jpg", quality=quality)
    (root / ".corpus_kind").write_text("learnable\n")
    return data_dir, synset_path


def generate(
    root: str | Path,
    n_classes: int = 100,
    images_per_class: int = 1,
    size: int = 256,
    seed: int = 0,
    quality: int = 90,
) -> tuple[Path, Path]:
    """Create the corpus under ``root``; returns (data_dir, synset_path).

    Layout: ``root/train/n{i:08d}/img{j}.jpg`` + ``root/synset_words.txt``.
    Images are smooth random fields (not pure noise) so JPEG encode/decode
    behaves like photographs rather than degenerate high-entropy blocks.
    Existing corpora with the right shape and kind are reused (see
    ``_reusable``), not regenerated.
    """
    from PIL import Image

    root = Path(root)
    reuse = _reusable(root, n_classes, images_per_class, "iid")
    if reuse is not None:
        return reuse
    _fresh_tree(root)

    data_dir = root / "train"
    synset_path = write_synset_words(root / "synset_words.txt", n_classes)
    rng = np.random.default_rng(seed)
    low = max(8, size // 8)
    for i in range(n_classes):
        d = data_dir / f"n{i:08d}"
        d.mkdir(parents=True, exist_ok=True)
        for j in range(images_per_class):
            # Low-frequency field upsampled to full size: photograph-like
            # JPEG statistics at ~100x the encode speed of per-pixel noise.
            base = rng.integers(0, 256, (low, low, 3), np.uint8)
            im = Image.fromarray(base).resize((size, size), Image.BILINEAR)
            im.save(d / f"img{j}.jpg", quality=quality)
    (root / ".corpus_kind").write_text("iid\n")
    return data_dir, synset_path
