"""Synthetic evaluation corpus: the reference's fixture-dataset shape.

The reference ships ``test_files/imagenet_1k/train/<synset>/<one JPEG>`` (one
image per each of 1,000 classes) plus ``synset_words.txt`` mapping synset ids
to labels (src/services.rs:170-184, 485-490). That corpus is not
redistributable here, so this module *generates* one with the same layout:
deterministic random JPEGs, one directory per synthetic synset. It powers the
end-to-end (JPEG -> top-1) bench mode and any test that wants a real
decode-from-disk path without shipping binary fixtures.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


def write_synset_words(path: str | Path, n_classes: int) -> Path:
    """``synset_words.txt`` with synthetic ids n00000000..: one per class."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(f"n{i:08d} synthetic class {i}\n" for i in range(n_classes)))
    return path


def generate(
    root: str | Path,
    n_classes: int = 100,
    images_per_class: int = 1,
    size: int = 256,
    seed: int = 0,
    quality: int = 90,
) -> tuple[Path, Path]:
    """Create the corpus under ``root``; returns (data_dir, synset_path).

    Layout: ``root/train/n{i:08d}/img{j}.jpg`` + ``root/synset_words.txt``.
    Images are smooth random fields (not pure noise) so JPEG encode/decode
    behaves like photographs rather than degenerate high-entropy blocks.
    Existing corpora with the right shape are reused, not regenerated.
    """
    from PIL import Image

    root = Path(root)
    data_dir = root / "train"
    synset_path = root / "synset_words.txt"
    if synset_path.exists() and data_dir.exists():
        dirs = [d for d in data_dir.iterdir() if d.is_dir()]
        # Reuse only when BOTH dimensions match: a corpus with fewer images
        # per class than requested would silently shrink whatever measurement
        # asked for this shape (e.g. the bench's multi-batch overlap run).
        if len(dirs) >= n_classes and all(
            sum(1 for f in d.iterdir() if f.is_file()) >= images_per_class
            for d in dirs[:n_classes]
        ):
            return data_dir, synset_path

    write_synset_words(synset_path, n_classes)
    rng = np.random.default_rng(seed)
    low = max(8, size // 8)
    for i in range(n_classes):
        d = data_dir / f"n{i:08d}"
        d.mkdir(parents=True, exist_ok=True)
        for j in range(images_per_class):
            # Low-frequency field upsampled to full size: photograph-like
            # JPEG statistics at ~100x the encode speed of per-pixel noise.
            base = rng.integers(0, 256, (low, low, 3), np.uint8)
            im = Image.fromarray(base).resize((size, size), Image.BILINEAR)
            im.save(d / f"img{j}.jpg", quality=quality)
    return data_dir, synset_path
