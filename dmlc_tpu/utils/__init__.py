from dmlc_tpu.utils.ring import symmetric_ring_neighbors
from dmlc_tpu.utils.metrics import LatencyStats
from dmlc_tpu.utils.config import ClusterConfig

__all__ = ["symmetric_ring_neighbors", "LatencyStats", "ClusterConfig"]
