"""Persistent XLA compilation cache — ONE switch shared by every entry
point (bench.py, __graft_entry__.py, tests/conftest.py).

The cache is keyed by platform+topology+HLO, so remote-TPU and virtual-CPU
entries coexist in one directory; a warm process spends ~0 s compiling
(probed on the axon tunnel: 2.3 s -> 0.02 s). ``DMLC_JAX_CACHE_DIR``
overrides the location (default: ``<repo>/.jax_cache``, gitignored).
"""

from __future__ import annotations

import os
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[2]


def enable(cache_dir: str | None = None) -> None:
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        cache_dir
        or os.environ.get("DMLC_JAX_CACHE_DIR", str(_REPO_ROOT / ".jax_cache")),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    # Persist XLA's internal (autotuning etc.) caches too, not just final
    # executables — without these a "warm" hit still re-runs part of the
    # compile pipeline.
    jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
