"""Persistent XLA compilation cache — ONE switch shared by every entry
point (bench.py, __graft_entry__.py, tests/conftest.py).

The cache is keyed by platform+topology+HLO, so remote-TPU and virtual-CPU
entries coexist in one directory; a warm process spends ~0 s compiling
(probed on the axon tunnel: 2.3 s -> 0.02 s). ``DMLC_JAX_CACHE_DIR``
overrides the location (default: ``<repo>/.jax_cache``, gitignored).

CPU entries are additionally scoped by a machine fingerprint: XLA:CPU
persists ahead-of-time *machine-code* artifacts keyed only by HLO, so a
cache written on one host feeds binaries compiled for a different CPU
feature set to the loader on another (the repo directory travels between
driver/judge machines). That is at best a wall of ``cpu_aot_loader.cc``
machine-feature-mismatch errors and at worst silent deopts — so virtual-CPU
runs (the multichip dryrun, the hermetic test mesh) each land in
``.jax_cache/cpu-<fingerprint>`` instead of the shared root.
"""

from __future__ import annotations

import hashlib
import logging
import os
import platform as _platform
import threading
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parents[2]

log = logging.getLogger(__name__)

# Persistent-cache observability (docs/OBSERVABILITY.md §8): jax announces
# hits/misses through jax.monitoring events; ``enable()`` registers ONE
# listener folding them here, and writes are inferred from cache-directory
# growth since enable() (jax emits no write event). ``export_metrics``
# exposes the lot as registry gauges so the silent cache becomes a scraped
# fleet signal.
_counts_lock = threading.Lock()
_COUNTS = {"hits": 0, "misses": 0, "requests": 0}
_LISTENER_INSTALLED = False
_CACHE_ROOT: Path | None = None
_BASELINE_ENTRIES = 0


def _on_cache_event(event: str, **kw) -> None:
    """jax.monitoring event listener (also driven directly by the unit
    test): counts persistent-cache hit/miss/request events."""
    if "/jax/compilation_cache/" not in event:
        return
    with _counts_lock:
        if event.endswith("cache_hits"):
            _COUNTS["hits"] += 1
        elif event.endswith("cache_misses"):
            _COUNTS["misses"] += 1
        elif event.endswith("compile_requests_use_cache"):
            _COUNTS["requests"] += 1


def _count_entries(root: Path | None) -> int:
    if root is None:
        return 0
    try:
        return sum(1 for p in root.iterdir() if p.is_file())
    except OSError:
        return 0


def counters() -> dict:
    """Hit/miss/request counts since process start, plus writes (entries
    added to the cache dir since ``enable()``) and the current entry
    count. All zeros until ``enable()`` has installed the listener."""
    with _counts_lock:
        out = dict(_COUNTS)
    entries = _count_entries(_CACHE_ROOT)
    out["entries"] = entries
    out["writes"] = max(0, entries - _BASELINE_ENTRIES)
    return out


def export_metrics(registry) -> None:
    """Register the cache counters as gauges on a metrics Registry
    (utils/metrics.py): ``jax_cache_hits`` / ``jax_cache_misses`` /
    ``jax_cache_writes`` / ``jax_cache_entries``. Gauges read live, so one
    registration at node build covers the process lifetime."""
    registry.gauge("jax_cache_hits", lambda: counters()["hits"])
    registry.gauge("jax_cache_misses", lambda: counters()["misses"])
    registry.gauge("jax_cache_writes", lambda: counters()["writes"])
    registry.gauge("jax_cache_entries", lambda: counters()["entries"])


def _machine_fingerprint() -> str:
    """Stable id for this host's CPU code-generation surface: ISA flags and
    model, the inputs XLA:CPU's AOT specializes machine code against."""
    parts = [_platform.machine(), _platform.processor()]
    # One line PER KEY (cores are uniform; the first package suffices):
    # 'model name' alone is not discriminating — hypervisors report generic
    # model strings while masking different feature sets, and the flags are
    # what AOT code generation actually keys on.
    wanted = {"flags", "model name", "Features"}
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                if key in wanted:
                    wanted.remove(key)
                    parts.append(line.strip())
                    if not wanted:
                        break
    except OSError:
        pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


def _cpu_platform_selected() -> bool:
    """True when jax will SELECT the cpu backend — i.e. cpu is the first
    entry of the platform priority list. Membership is not enough: driver
    machines run with ``jax_platforms='axon,cpu'`` (TPU first, cpu as
    fallback), and scoping those runs' TPU cache entries per-host would
    silently discard the shared warm cache."""
    import jax

    cfg = getattr(jax.config, "jax_platforms", None) or os.environ.get(
        "JAX_PLATFORMS", ""
    )
    if cfg:
        return cfg.split(",")[0].strip().lower() == "cpu"
    # Nothing configured: jax auto-selects. Asking the backend initializes
    # it, which is fine here — enable() callers are about to compile anyway,
    # and on plugin machines cfg is always set so this path stays local.
    return jax.default_backend() == "cpu"


def enable(cache_dir: str | None = None) -> None:
    global _LISTENER_INSTALLED, _CACHE_ROOT, _BASELINE_ENTRIES
    import jax

    root = cache_dir or os.environ.get(
        "DMLC_JAX_CACHE_DIR", str(_REPO_ROOT / ".jax_cache")
    )
    cpu = _cpu_platform_selected()
    if cpu:
        root = str(Path(root) / f"cpu-{_machine_fingerprint()}")
    _CACHE_ROOT = Path(root)
    _BASELINE_ENTRIES = _count_entries(_CACHE_ROOT)
    if not _LISTENER_INSTALLED:
        try:
            from jax import monitoring as _monitoring

            _monitoring.register_event_listener(_on_cache_event)
            _LISTENER_INSTALLED = True
        except Exception:  # noqa: BLE001 - older jax without monitoring: stay silent
            log.debug("jax.monitoring unavailable; cache counters stay 0",
                      exc_info=True)
    jax.config.update("jax_compilation_cache_dir", root)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    # Persist XLA's internal (autotuning etc.) caches too, not just final
    # executables — without these a "warm" hit still re-runs part of the
    # compile pipeline. NOT on CPU: there the internal cache stores AOT
    # machine-code kernels whose loader error-logs a feature-set comparison
    # on every hit (XLA stamps tuning pseudo-features like
    # +prefer-no-scatter that never appear in the detected host set), and
    # virtual-CPU compiles are cheap anyway.
    jax.config.update("jax_persistent_cache_enable_xla_caches", "none" if cpu else "all")
