"""Ring-neighbor topology.

Capability parity with the reference's ``symmetric_ring_neighbors``
(reference: src/utils.rs:5-21): given a sorted ring of node ids, pick the k
nearest predecessors and k nearest successors of ``self_id`` with wrap-around,
deduplicated, optionally filtered by a predicate (the reference filters to
Active members, src/membership.rs:242-246).

The heartbeat fan-out of the gossip layer (cluster/membership.py) pings exactly
this neighbor set every round, which bounds per-node network load at O(k) while
keeping the failure-detection graph connected.
"""

from __future__ import annotations

from typing import Callable, Iterable, Sequence, TypeVar

T = TypeVar("T")


def symmetric_ring_neighbors(
    ids: Iterable[T],
    self_id: T,
    k: int,
    predicate: Callable[[T], bool] | None = None,
) -> list[T]:
    """k predecessors + k successors of ``self_id`` on the sorted id ring.

    ``ids`` need not contain ``self_id``. Results are deduplicated (small rings
    where the windows overlap yield fewer than 2k neighbors) and never include
    ``self_id`` itself. Order: predecessors nearest-first, then successors
    nearest-first.
    """
    ring: list[T] = sorted(x for x in set(ids) if x != self_id and (predicate is None or predicate(x)))
    if not ring or k <= 0:
        return []
    # Position where self_id would be inserted: successors start here.
    import bisect

    pos = bisect.bisect_left(ring, self_id)
    n = len(ring)
    out: list[T] = []
    seen: set[T] = set()
    for i in range(1, k + 1):  # predecessors, nearest first
        cand = ring[(pos - i) % n]
        if cand not in seen:
            seen.add(cand)
            out.append(cand)
    for i in range(k):  # successors, nearest first
        cand = ring[(pos + i) % n]
        if cand not in seen:
            seen.add(cand)
            out.append(cand)
    return out
