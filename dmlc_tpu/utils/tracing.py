"""Trace spans: lightweight instrumentation for the cluster and device path.

The reference's only observability is per-query `Instant` timing at the
scheduler (src/services.rs:419-424) plus log lines. Here every subsystem can
open named spans (thread-safe, ~no overhead when disabled); the collector
exports

- per-name aggregates (count/mean/percentiles via LatencyStats), and
- Chrome trace-event JSON (chrome://tracing / Perfetto compatible) for
  timeline inspection of e.g. decode vs device-dispatch overlap.

Spans are DISTRIBUTED (docs/OBSERVABILITY.md): each span records the
``trace_id``/``span_id``/``parent_id`` of the ambient trace context
(cluster/tracectx.py), which the RPC fabrics carry hop to hop in the frame
field ``t`` — so a leader-dispatch span, the member's predict span, and the
SDFS replica's fetch span all share one trace with correct parent edges,
and ``obs.trace_dump`` + the leader-side merge (cluster/observe.py) render
them as one fleet-wide timeline.

``lane`` is the serving-node identity ambient at record time: RPC servers
bind their node's member address around method execution, so a process
hosting several nodes (the localcluster harness) can still attribute every
span to the node that executed it — it becomes the Perfetto pid lane.

Device work is asynchronous under JAX; callers that want true device time
wrap the block_until_ready boundary (as InferenceEngine.run_batch does).
"""

from __future__ import annotations

import contextvars
import json
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator

from dmlc_tpu.cluster import tracectx
from dmlc_tpu.utils.metrics import LatencyStats


@dataclass
class SpanRecord:
    name: str
    start_s: float
    duration_s: float
    thread_id: int
    attrs: dict = field(default_factory=dict)
    trace_id: str | None = None
    span_id: str | None = None
    parent_id: str | None = None
    lane: str | None = None


# ---------------------------------------------------------------------------
# Lane: which node is executing (ambient; the Perfetto pid dimension)
# ---------------------------------------------------------------------------

_lane: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "dmlc_trace_lane", default=None
)


def current_lane() -> str | None:
    return _lane.get()


@contextmanager
def lane(name: str | None) -> Iterator[None]:
    """Bind the executing-node identity for the dynamic extent of the
    block. RPC servers bind their node's member address here; node
    maintenance threads bind it at spawn. None leaves the ambient lane."""
    if name is None:
        yield
        return
    token = _lane.set(name)
    try:
        yield
    finally:
        _lane.reset(token)


class Tracer:
    """Span collector. Disabled by default; enabling costs one branch per
    span entry. Bounded: keeps aggregates forever, raw events up to
    ``max_events`` — newest raw spans are dropped past that, aggregates
    stay exact, and every drop is COUNTED (``dropped_events``) so a
    truncated timeline is visibly truncated instead of silently short.

    Head-based sampling (docs/OBSERVABILITY.md §7): each fresh ROOT trace
    is kept with probability ``effective_rate``; the decision rides the
    wire in the ``t`` frame field so every hop of an unsampled request
    skips raw span storage (aggregates — the profiler's food — stay exact
    for every request). An adaptive controller shrinks/regrows the rate
    toward a spans/s budget, and spans that end in an exception are
    recorded REGARDLESS of the bit, so error and deadline-exceeded
    requests always survive into the merged fleet timeline."""

    MIN_SAMPLE_RATE = 1e-3

    def __init__(self, max_events: int = 100_000):
        self.enabled = False
        self.max_events = max_events
        self._events: list[SpanRecord] = []
        self._dropped = 0
        self.resets = 0  # bumped by reset(); cursor-based drains re-seek
        self._aggregates: dict[str, LatencyStats] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        # --- head-based sampling state (all guarded by self._lock) ---
        self.sample_rate = 1.0            # configured base rate for roots
        self.spans_per_s_budget = 0.0     # adaptive target; 0 = controller off
        self.adapt_window_s = 5.0
        self._effective_rate = 1.0
        self._srng = random.Random(0x5A3B1E)  # sampling is a label, not control flow
        self._sample_clock = time.monotonic
        self._sampled_roots = 0
        self._unsampled_roots = 0
        self._forced_records = 0
        self._window_start: float | None = None
        self._window_records = 0
        self._force_until: float | None = None

    def now(self) -> float:
        """The tracer's own clock (seconds since construction/reset) — the
        timebase every SpanRecord.start_s lives in. ``obs.clock`` echoes
        this so the leader-side merge can align per-node timelines."""
        return time.perf_counter() - self._t0

    @contextmanager
    def span(self, name: str, **attrs):
        if not self.enabled:
            yield
            return
        if tracectx.current() is None:
            ctx = tracectx.child(sampled=self._decide_root())
        else:
            ctx = tracectx.child()
        start = time.perf_counter()
        error: BaseException | None = None
        try:
            with tracectx.bind(ctx):
                yield
        except BaseException as e:
            error = e
            raise
        finally:
            dur = time.perf_counter() - start
            if error is not None:
                attrs = dict(attrs, error=type(error).__name__)
                if not ctx.sampled:
                    attrs["forced"] = "error"
            rec = SpanRecord(
                name, start - self._t0, dur, threading.get_ident(), attrs,
                trace_id=ctx.trace_id, span_id=ctx.span_id,
                parent_id=ctx.parent_id, lane=_lane.get(),
            )
            with self._lock:
                self._aggregates.setdefault(name, LatencyStats()).record(dur)
                # Forced sampling: a span that raised is stored even when
                # the head decision said drop — every enclosing span of the
                # failing request sees the same exception on unwind, so the
                # whole local chain survives into the merged trace.
                if ctx.sampled or error is not None:
                    if error is not None and not ctx.sampled:
                        self._forced_records += 1
                    self._append_locked(rec)

    def record(self, name: str, duration_s: float, **attrs) -> None:
        """Record an externally-timed duration (e.g. device execution) as a
        leaf span under the ambient trace context."""
        if not self.enabled:
            return
        ctx = tracectx.child()
        rec = SpanRecord(
            name, time.perf_counter() - self._t0 - duration_s, duration_s,
            threading.get_ident(), attrs,
            trace_id=ctx.trace_id, span_id=ctx.span_id,
            parent_id=ctx.parent_id, lane=_lane.get(),
        )
        with self._lock:
            self._aggregates.setdefault(name, LatencyStats()).record(duration_s)
            if ctx.sampled:
                self._append_locked(rec)

    def _append_locked(self, rec: SpanRecord) -> None:
        self._window_records += 1
        if len(self._events) < self.max_events:
            self._events.append(rec)
        else:
            self._dropped += 1

    # ---- head-based sampling -------------------------------------------

    def set_sampling(self, rate=None, spans_per_s=None, clock=None) -> None:
        """Configure head sampling: ``rate`` is the base keep-probability
        for fresh roots (clamped to [0, 1]); ``spans_per_s`` a storage
        budget the adaptive controller steers the effective rate toward
        (0 disables adaptation); ``clock`` overrides the controller's
        timebase (the sim harness injects its virtual clock)."""
        with self._lock:
            if rate is not None:
                self.sample_rate = max(0.0, min(1.0, float(rate)))
                self._effective_rate = self.sample_rate
            if spans_per_s is not None:
                self.spans_per_s_budget = max(0.0, float(spans_per_s))
                if self.spans_per_s_budget <= 0.0:
                    self._effective_rate = self.sample_rate
            if clock is not None:
                self._sample_clock = clock
            self._window_start = None
            self._window_records = 0

    def force_sampling(self, seconds: float) -> None:
        """Sample every fresh root for the next ``seconds`` regardless of
        rate — the SLO-burn hook: when a model is burning budget, the
        leader wants whole traces, not a 1% lottery."""
        with self._lock:
            until = self._sample_clock() + float(seconds)
            if self._force_until is None or until > self._force_until:
                self._force_until = until

    def _decide_root(self) -> bool:
        with self._lock:
            now = self._sample_clock()
            if self._force_until is not None and now < self._force_until:
                self._sampled_roots += 1
                return True
            self._maybe_adapt_locked(now)
            r = self._effective_rate
            sampled = r >= 1.0 or (r > 0.0 and self._srng.random() < r)
            if sampled:
                self._sampled_roots += 1
            else:
                self._unsampled_roots += 1
            return sampled

    def _maybe_adapt_locked(self, now: float) -> None:
        if self.spans_per_s_budget <= 0.0:
            return
        if self._window_start is None:
            self._window_start = now
            self._window_records = 0
            return
        dt = now - self._window_start
        if dt < self.adapt_window_s:
            return
        observed = self._window_records / dt
        budget = self.spans_per_s_budget
        if observed > budget:
            # Over budget: cut proportionally (a 10x overshoot drops the
            # rate 10x in one window, not by baby steps).
            self._effective_rate = max(
                self.MIN_SAMPLE_RATE, self._effective_rate * budget / observed
            )
        elif observed < 0.5 * budget:
            # Comfortably under: regrow gently toward the base rate.
            self._effective_rate = min(self.sample_rate, self._effective_rate * 1.5)
        self._window_start = now
        self._window_records = 0

    def sampling_summary(self) -> dict:
        """Root decisions + controller state, surfaced via ``obs.metrics``
        so the adaptive behavior is observable fleet-wide."""
        with self._lock:
            total = self._sampled_roots + self._unsampled_roots
            return {
                "sampled": self._sampled_roots,
                "unsampled": self._unsampled_roots,
                "forced_records": self._forced_records,
                "base_rate": self.sample_rate,
                "effective_rate": self._effective_rate,
                "spans_per_s_budget": self.spans_per_s_budget,
                "observed_rate": (self._sampled_roots / total) if total else 1.0,
            }

    # ---- reporting -----------------------------------------------------

    @property
    def dropped_events(self) -> int:
        with self._lock:
            return self._dropped

    def summary(self) -> dict:
        """Per-name aggregate summaries. When raw spans were dropped past
        ``max_events`` the count rides along under the reserved
        ``dropped_events`` key (absent otherwise, so the common case keeps
        its pure name->stats shape)."""
        with self._lock:
            out: dict = {
                name: st.summary() for name, st in sorted(self._aggregates.items())
            }
            if self._dropped:
                out["dropped_events"] = self._dropped
            return out

    @property
    def event_count(self) -> int:
        """Raw spans currently buffered — with ``resets``, the cursor
        contract for incremental drains (``events_wire(offset=...)``)."""
        with self._lock:
            return len(self._events)

    def events_wire(self, lane: str | None = None, offset: int = 0) -> list[dict]:
        """Raw spans in wire form for ``obs.trace_dump``. With ``lane``
        given, only spans executed under that lane (plus unlaned spans —
        in production one process is one node, so ambient work with no
        serving scope still belongs to it). ``offset`` skips already-seen
        spans (the buffer is append-only between resets, so an index plus
        the ``resets`` counter is a stable drain cursor)."""
        with self._lock:
            events = self._events[offset:] if offset > 0 else list(self._events)
        out = []
        for e in events:
            if lane is not None and e.lane is not None and e.lane != lane:
                continue
            out.append(
                {
                    "name": e.name,
                    "start": e.start_s,
                    "dur": e.duration_s,
                    "tid": e.thread_id % 1_000_000,
                    "trace": e.trace_id,
                    "span": e.span_id,
                    "parent": e.parent_id,
                    "lane": e.lane,
                    "attrs": dict(e.attrs),
                }
            )
        return out

    def chrome_trace(self) -> list[dict]:
        """Trace-event JSON objects (phase 'X' = complete events, µs)."""
        with self._lock:
            events = list(self._events)
        out = []
        for e in events:
            args = dict(e.attrs)
            if e.trace_id is not None:
                args.update(trace=e.trace_id, span=e.span_id)
                if e.parent_id is not None:
                    args["parent"] = e.parent_id
            if e.lane is not None:
                args["lane"] = e.lane
            out.append(
                {
                    "name": e.name,
                    "ph": "X",
                    "ts": e.start_s * 1e6,
                    "dur": e.duration_s * 1e6,
                    "pid": 0,
                    "tid": e.thread_id % 1_000_000,
                    "args": args,
                }
            )
        return out

    def export(self, path: str | Path) -> None:
        doc: dict = {"traceEvents": self.chrome_trace()}
        dropped = self.dropped_events
        if dropped:
            # Visible truncation: Perfetto shows otherData in the trace
            # info pane, so a timeline missing its tail says so.
            doc["otherData"] = {
                "dropped_events": dropped,
                "note": f"timeline truncated: {dropped} span(s) past "
                        f"max_events={self.max_events} were not recorded",
            }
        Path(path).write_text(json.dumps(doc))

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._aggregates.clear()
            self._dropped = 0
            self.resets += 1
            self._t0 = time.perf_counter()
            self._sampled_roots = 0
            self._unsampled_roots = 0
            self._forced_records = 0
            self._window_start = None
            self._window_records = 0
            self._force_until = None


# Process-global tracer: subsystems import this; tools flip .enabled.
tracer = Tracer()


def enable() -> Tracer:
    tracer.enabled = True
    return tracer


def disable() -> None:
    tracer.enabled = False


# ---------------------------------------------------------------------------
# RPC handler instrumentation (lint rule O1's contract)
# ---------------------------------------------------------------------------


def traced(method_name: str, fn):
    """Wrap one RPC handler so it executes under a ``rpc/<method>`` span.
    The span parents onto the caller's wire context (which the serving
    layer binds ambiently), so the cross-process edge is recorded here —
    once, for every handler, instead of per-handler boilerplate. Idempotent:
    an already-wrapped handler passes through."""
    if getattr(fn, "_dmlc_traced", False):
        return fn

    def handler(payload: dict, _fn=fn, _span_name=f"rpc/{method_name}") -> dict:
        with tracer.span(_span_name):
            return _fn(payload)

    handler._dmlc_traced = True  # type: ignore[attr-defined]
    handler.__name__ = getattr(fn, "__name__", method_name)
    handler.__wrapped__ = fn  # type: ignore[attr-defined]
    return handler


def traced_methods(table: dict) -> dict:
    """Wrap a whole RPC method table (the form lint rule O1 requires every
    ``methods()`` to return): each handler runs under its ``rpc/<method>``
    span. Safe to nest — tables merged from already-traced sub-tables are
    not double-wrapped."""
    return {name: traced(name, fn) for name, fn in table.items()}
