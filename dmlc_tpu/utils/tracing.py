"""Trace spans: lightweight instrumentation for the cluster and device path.

The reference's only observability is per-query `Instant` timing at the
scheduler (src/services.rs:419-424) plus log lines. Here every subsystem can
open named spans (thread-safe, ~no overhead when disabled); the collector
exports

- per-name aggregates (count/mean/percentiles via LatencyStats), and
- Chrome trace-event JSON (chrome://tracing / Perfetto compatible) for
  timeline inspection of e.g. decode vs device-dispatch overlap.

Device work is asynchronous under JAX; callers that want true device time
wrap the block_until_ready boundary (as InferenceEngine.run_batch does).
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path

from dmlc_tpu.utils.metrics import LatencyStats


@dataclass
class SpanRecord:
    name: str
    start_s: float
    duration_s: float
    thread_id: int
    attrs: dict = field(default_factory=dict)


class Tracer:
    """Span collector. Disabled by default; enabling costs one branch per
    span entry. Bounded: keeps aggregates forever, raw events up to
    ``max_events`` (newest dropped past that, aggregates stay exact)."""

    def __init__(self, max_events: int = 100_000):
        self.enabled = False
        self.max_events = max_events
        self._events: list[SpanRecord] = []
        self._aggregates: dict[str, LatencyStats] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @contextmanager
    def span(self, name: str, **attrs):
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - start
            rec = SpanRecord(name, start - self._t0, dur, threading.get_ident(), attrs)
            with self._lock:
                self._aggregates.setdefault(name, LatencyStats()).record(dur)
                if len(self._events) < self.max_events:
                    self._events.append(rec)

    def record(self, name: str, duration_s: float, **attrs) -> None:
        """Record an externally-timed duration (e.g. device execution)."""
        if not self.enabled:
            return
        rec = SpanRecord(
            name, time.perf_counter() - self._t0 - duration_s, duration_s,
            threading.get_ident(), attrs,
        )
        with self._lock:
            self._aggregates.setdefault(name, LatencyStats()).record(duration_s)
            if len(self._events) < self.max_events:
                self._events.append(rec)

    # ---- reporting -----------------------------------------------------

    def summary(self) -> dict[str, dict[str, float]]:
        with self._lock:
            return {name: st.summary() for name, st in sorted(self._aggregates.items())}

    def chrome_trace(self) -> list[dict]:
        """Trace-event JSON objects (phase 'X' = complete events, µs)."""
        with self._lock:
            events = list(self._events)
        return [
            {
                "name": e.name,
                "ph": "X",
                "ts": e.start_s * 1e6,
                "dur": e.duration_s * 1e6,
                "pid": 0,
                "tid": e.thread_id % 1_000_000,
                "args": e.attrs,
            }
            for e in events
        ]

    def export(self, path: str | Path) -> None:
        Path(path).write_text(json.dumps({"traceEvents": self.chrome_trace()}))

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._aggregates.clear()
            self._t0 = time.perf_counter()


# Process-global tracer: subsystems import this; tools flip .enabled.
tracer = Tracer()


def enable() -> Tracer:
    tracer.enabled = True
    return tracer


def disable() -> None:
    tracer.enabled = False
