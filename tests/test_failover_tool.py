"""tools/measure_failover.py: the reference's VM-kill experiment, automated.

One real trial (3 localhost nodes, leader crashed mid-run): the tool must
report a finite detection/resume time and ZERO lost or wrong queries."""

import importlib.util
import json
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_failover_trial_exactly_once(tmp_path, capsys):
    spec = importlib.util.spec_from_file_location(
        "measure_failover", os.path.join(REPO_ROOT, "tools", "measure_failover.py")
    )
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)

    rc = tool.main(["--trials", "1", "--queries", "600"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    r = json.loads(line)
    assert r["wrong"] == 0
    assert 0 < r["detection_s"] < 10
    assert r["detection_s"] <= r["resume_s"] < 15
