"""Tracing spans and checkpoint/restore (local + SDFS-backed)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlc_tpu.utils import checkpoint as ckpt
from dmlc_tpu.utils.tracing import Tracer


class TestTracer:
    def test_disabled_records_nothing(self):
        t = Tracer()
        with t.span("x"):
            pass
        t.record("y", 0.5)
        assert t.summary() == {}

    def test_spans_aggregate_and_export(self, tmp_path):
        t = Tracer()
        t.enabled = True
        for i in range(5):
            with t.span("host/decode", n=i):
                pass
        t.record("device/forward", 0.25, model="resnet18")
        s = t.summary()
        assert s["host/decode"]["count"] == 5
        assert s["device/forward"]["mean"] == pytest.approx(0.25)
        out = tmp_path / "trace.json"
        t.export(out)
        events = json.loads(out.read_text())["traceEvents"]
        assert len(events) == 6
        assert {e["name"] for e in events} == {"host/decode", "device/forward"}
        assert all(e["ph"] == "X" and "dur" in e for e in events)

    def test_span_exception_still_recorded(self):
        t = Tracer()
        t.enabled = True
        with pytest.raises(RuntimeError):
            with t.span("boom"):
                raise RuntimeError("x")
        assert t.summary()["boom"]["count"] == 1

    def test_event_cap_keeps_aggregates_exact(self):
        t = Tracer(max_events=10)
        t.enabled = True
        for _ in range(50):
            with t.span("s"):
                pass
        assert t.summary()["s"]["count"] == 50
        assert len(t.chrome_trace()) == 10


def tiny_state():
    import optax

    from dmlc_tpu.models.vit import ViT
    from dmlc_tpu.parallel import train as train_lib

    model = ViT(num_classes=4, patch_size=8, hidden_size=16, num_layers=1,
                num_heads=2, mlp_dim=32, dtype=jnp.float32)
    x = jnp.zeros((2, 16, 16, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    return train_lib.create_train_state(model, variables, train_lib.default_optimizer())


class TestLocalCheckpoint:
    def test_roundtrip_and_latest(self, tmp_path):
        state = tiny_state()
        state2 = state.replace(step=state.step + 7)
        ckpt.save_local(state, tmp_path, 0)
        ckpt.save_local(state2, tmp_path, 7)
        restored, step = ckpt.restore_local(state, tmp_path)
        assert step == 7
        assert int(restored.step) == 7
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            restored.params,
            state2.params,
        )

    def test_empty_dir_returns_template(self, tmp_path):
        state = tiny_state()
        restored, step = ckpt.restore_local(state, tmp_path / "nope")
        assert step == 0 and restored is state


class TestSdfsCheckpoint:
    def make_cluster(self, tmp_path):
        from dmlc_tpu.cluster.rpc import SimRpcNetwork
        from dmlc_tpu.cluster.sdfs import MemberStore, SdfsClient, SdfsLeader, SdfsMember

        net = SimRpcNetwork()
        live = ["m0", "m1", "m2"]
        stores = {}
        for m in live:
            store = MemberStore(tmp_path / m)
            net.serve(m, SdfsMember(store, net.client(m)).methods())
            stores[m] = store
        leader = SdfsLeader(net.client("L"), lambda: list(live), replication_factor=2)
        net.serve("L", leader.methods())
        return SdfsClient(net.client("m0"), "L", stores["m0"], "m0")

    def test_versioned_save_restore(self, tmp_path):
        client = self.make_cluster(tmp_path)
        cp = ckpt.SdfsCheckpointer(client)
        state = tiny_state()
        assert cp.save(state, step=0) == 1
        later = state.replace(step=state.step + 100)
        assert cp.save(later, step=100) == 2

        restored, step = cp.restore(state)  # latest
        assert step == 100 and int(restored.step) == 100
        old, step0 = cp.restore(state, version=1)  # time travel
        assert step0 == 0 and int(old.step) == 0

    def test_restore_rejects_non_checkpoint(self, tmp_path):
        client = self.make_cluster(tmp_path)
        client.put_bytes(b"garbage", "checkpoints/train_state")
        cp = ckpt.SdfsCheckpointer(client)
        with pytest.raises(ValueError, match="not a dmlc checkpoint"):
            cp.restore(tiny_state())
