"""Sequence-parallel transformer: long-context as a trainable model.

The SP schedules must be interchangeable INSIDE a model (same params, same
logits), causal, and trainable end-to-end with the sequence axis sharded
over sp on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlc_tpu.parallel import make_mesh
from dmlc_tpu.parallel.sp_transformer import SPTransformerLM

VOCAB, LAYERS, HEADS, HIDDEN, MLP = 32, 2, 4, 32, 64
B, S = 4, 32


def build(mesh, schedule):
    return SPTransformerLM(
        vocab=VOCAB, num_layers=LAYERS, num_heads=HEADS, hidden=HIDDEN,
        mlp_dim=MLP, max_len=S, mesh=mesh, schedule=schedule,
    )


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh({"dp": 2, "sp": 4})
    tokens = jax.random.randint(jax.random.PRNGKey(0), (B, S), 0, VOCAB)
    variables = build(None, "dense").init(jax.random.PRNGKey(1), tokens)
    return mesh, tokens, variables


def test_schedules_agree_inside_the_model(setup):
    """Same params: dense, ring, and ulysses logits must match with the
    sequence sharded over sp (dp x sp mesh)."""
    mesh, tokens, variables = setup
    want = np.asarray(build(None, "dense").apply(variables, tokens))
    shd = NamedSharding(mesh, P("dp", "sp"))
    tokens_sharded = jax.device_put(tokens, shd)
    for schedule in ("ring", "ring_flash", "ulysses"):
        model = build(mesh, schedule)
        # dmlc-lint: disable=J2 -- each iteration jits a DIFFERENT schedule's model; one compile per schedule is the comparison itself
        got = np.asarray(jax.jit(model.apply)(variables, tokens_sharded))
        np.testing.assert_allclose(got, want, atol=3e-5, rtol=1e-4)
    # The single-device Pallas flash schedule agrees too (same params),
    # and so does the crossover-dispatched "auto" schedule.
    for schedule in ("flash", "auto"):
        # dmlc-lint: disable=J2 -- each iteration jits a DIFFERENT schedule's model; one compile per schedule is the comparison itself
        got = np.asarray(jax.jit(build(None, schedule).apply)(variables, tokens))
        np.testing.assert_allclose(got, want, atol=3e-5, rtol=1e-4)


def test_causal(setup):
    """Changing future tokens must not change past logits."""
    _, tokens, variables = setup
    model = build(None, "dense")
    base = np.asarray(model.apply(variables, tokens))
    mutated = tokens.at[:, S // 2 :].set((tokens[:, S // 2 :] + 1) % VOCAB)
    out = np.asarray(model.apply(variables, mutated))
    np.testing.assert_allclose(out[:, : S // 2], base[:, : S // 2], atol=1e-5)
    assert not np.allclose(out[:, S // 2 :], base[:, S // 2 :])


def test_trains_with_flash_schedule(setup):
    """The Pallas flash schedule must train end to end (its custom_vjp
    recomputes exact grads through the dense path)."""
    _, tokens, variables = setup
    model = build(None, "flash")
    tx = optax.adam(1e-2)
    opt_state = tx.init(variables)

    @jax.jit
    def step(v, opt_state, toks):
        def loss_fn(v):
            logits = model.apply(v, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], toks[:, 1:]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(v)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(v, updates), opt_state, loss

    v = variables
    losses = []
    for _ in range(4):
        v, opt_state, loss = step(v, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("schedule", ["ring", "ring_flash", "ulysses"])
def test_trains_sequence_parallel(setup, schedule):
    """Next-token LM training with sequence sharded over sp: loss must
    decrease on a fixed batch, grads stay finite, all under one jit."""
    mesh, tokens, variables = setup
    model = build(mesh, schedule)
    tx = optax.adam(1e-2)
    opt_state = tx.init(variables)
    shd = NamedSharding(mesh, P("dp", "sp"))
    tokens = jax.device_put(tokens, shd)

    def loss_fn(v, toks):
        logits = model.apply(v, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], toks[:, 1:]
        ).mean()

    @jax.jit
    def step(v, opt_state, toks):
        loss, grads = jax.value_and_grad(loss_fn)(v, toks)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(v, updates), opt_state, loss

    losses = []
    v = variables
    for _ in range(5):
        v, opt_state, loss = step(v, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], f"no learning: {losses}"
