"""Golden-value parity tests against HuggingFace reference implementations.

The reference validated inference against libtorch outputs implicitly (tch-rs
IS libtorch, src/services.rs:513-524); since this rebuild re-implements the
models from scratch, we verify numerics explicitly: instantiate a small
randomly-initialized HF torch model (no network access needed), copy its
weights into our Flax model, and require the outputs to agree.

Also checks canonical parameter counts for the torchvision-topology models
(resnet/alexnet), which pins the architecture without a torch reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlc_tpu.models import get_model

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def t2np(t):
    return t.detach().cpu().numpy()


def small_vit_config():
    return transformers.ViTConfig(
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
        image_size=32,
        patch_size=8,
        num_labels=10,
    )


def copy_vit_weights(hf, num_layers):
    """HF ViTForImageClassification state_dict -> flax params for models.vit.ViT."""
    sd = {k: t2np(v) for k, v in hf.state_dict().items()}
    p = {
        "patch_embed": {
            "kernel": sd["vit.embeddings.patch_embeddings.projection.weight"].transpose(2, 3, 1, 0),
            "bias": sd["vit.embeddings.patch_embeddings.projection.bias"],
        },
        "cls_token": sd["vit.embeddings.cls_token"],
        "pos_embed": sd["vit.embeddings.position_embeddings"],
        "ln_final": {"scale": sd["vit.layernorm.weight"], "bias": sd["vit.layernorm.bias"]},
        "head": {"kernel": sd["classifier.weight"].T, "bias": sd["classifier.bias"]},
    }
    for i in range(num_layers):
        hfp = f"vit.encoder.layer.{i}"
        p[f"block{i}"] = {
            "ln1": {"scale": sd[f"{hfp}.layernorm_before.weight"], "bias": sd[f"{hfp}.layernorm_before.bias"]},
            "ln2": {"scale": sd[f"{hfp}.layernorm_after.weight"], "bias": sd[f"{hfp}.layernorm_after.bias"]},
            "attn": {
                "query": {
                    "kernel": sd[f"{hfp}.attention.attention.query.weight"].T,
                    "bias": sd[f"{hfp}.attention.attention.query.bias"],
                },
                "key": {
                    "kernel": sd[f"{hfp}.attention.attention.key.weight"].T,
                    "bias": sd[f"{hfp}.attention.attention.key.bias"],
                },
                "value": {
                    "kernel": sd[f"{hfp}.attention.attention.value.weight"].T,
                    "bias": sd[f"{hfp}.attention.attention.value.bias"],
                },
                "out": {
                    "kernel": sd[f"{hfp}.attention.output.dense.weight"].T,
                    "bias": sd[f"{hfp}.attention.output.dense.bias"],
                },
            },
            "mlp_in": {"kernel": sd[f"{hfp}.intermediate.dense.weight"].T, "bias": sd[f"{hfp}.intermediate.dense.bias"]},
            "mlp_out": {"kernel": sd[f"{hfp}.output.dense.weight"].T, "bias": sd[f"{hfp}.output.dense.bias"]},
        }
    return {"params": p}


def test_vit_parity_with_hf():
    from dmlc_tpu.models.vit import ViT

    cfg = small_vit_config()
    torch.manual_seed(0)
    hf = transformers.ViTForImageClassification(cfg).eval()
    mine = ViT(
        num_classes=10,
        patch_size=cfg.patch_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_hidden_layers,
        num_heads=cfg.num_attention_heads,
        mlp_dim=cfg.intermediate_size,
        dtype=jnp.float32,
        layer_norm_eps=cfg.layer_norm_eps,
        activation="gelu",
    )
    params = copy_vit_weights(hf, cfg.num_hidden_layers)
    x = np.random.RandomState(0).randn(2, cfg.image_size, cfg.image_size, 3).astype(np.float32)
    with torch.no_grad():
        ref = t2np(hf(pixel_values=torch.from_numpy(x.transpose(0, 3, 1, 2))).logits)
    got = np.asarray(mine.apply(params, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-4)


def small_clip_config():
    return transformers.CLIPVisionConfig(
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
        image_size=32,
        patch_size=8,
        projection_dim=32,
    )


def copy_clip_weights(hf, num_layers):
    sd = {k: t2np(v) for k, v in hf.state_dict().items()}
    vp = "vision_model"
    p = {
        "patch_embed": {"kernel": sd[f"{vp}.embeddings.patch_embedding.weight"].transpose(2, 3, 1, 0)},
        "cls_token": sd[f"{vp}.embeddings.class_embedding"].reshape(1, 1, -1),
        "pos_embed": sd[f"{vp}.embeddings.position_embedding.weight"][None],
        "pre_ln": {"scale": sd[f"{vp}.pre_layrnorm.weight"], "bias": sd[f"{vp}.pre_layrnorm.bias"]},
        "post_ln": {"scale": sd[f"{vp}.post_layernorm.weight"], "bias": sd[f"{vp}.post_layernorm.bias"]},
        "projection": {"kernel": sd["visual_projection.weight"].T},
    }
    for i in range(num_layers):
        hfp = f"{vp}.encoder.layers.{i}"
        p[f"block{i}"] = {
            "ln1": {"scale": sd[f"{hfp}.layer_norm1.weight"], "bias": sd[f"{hfp}.layer_norm1.bias"]},
            "ln2": {"scale": sd[f"{hfp}.layer_norm2.weight"], "bias": sd[f"{hfp}.layer_norm2.bias"]},
            "attn": {
                "query": {"kernel": sd[f"{hfp}.self_attn.q_proj.weight"].T, "bias": sd[f"{hfp}.self_attn.q_proj.bias"]},
                "key": {"kernel": sd[f"{hfp}.self_attn.k_proj.weight"].T, "bias": sd[f"{hfp}.self_attn.k_proj.bias"]},
                "value": {"kernel": sd[f"{hfp}.self_attn.v_proj.weight"].T, "bias": sd[f"{hfp}.self_attn.v_proj.bias"]},
                "out": {"kernel": sd[f"{hfp}.self_attn.out_proj.weight"].T, "bias": sd[f"{hfp}.self_attn.out_proj.bias"]},
            },
            "mlp_in": {"kernel": sd[f"{hfp}.mlp.fc1.weight"].T, "bias": sd[f"{hfp}.mlp.fc1.bias"]},
            "mlp_out": {"kernel": sd[f"{hfp}.mlp.fc2.weight"].T, "bias": sd[f"{hfp}.mlp.fc2.bias"]},
        }
    return {"params": p}


def test_clip_parity_with_hf():
    from dmlc_tpu.models.clip import CLIPVisionEncoder

    cfg = small_clip_config()
    torch.manual_seed(0)
    hf = transformers.CLIPVisionModelWithProjection(cfg).eval()
    mine = CLIPVisionEncoder(
        projection_dim=cfg.projection_dim,
        patch_size=cfg.patch_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_hidden_layers,
        num_heads=cfg.num_attention_heads,
        mlp_dim=cfg.intermediate_size,
        dtype=jnp.float32,
        layer_norm_eps=cfg.layer_norm_eps,
    )
    params = copy_clip_weights(hf, cfg.num_hidden_layers)
    x = np.random.RandomState(1).randn(2, cfg.image_size, cfg.image_size, 3).astype(np.float32)
    with torch.no_grad():
        ref = t2np(hf(pixel_values=torch.from_numpy(x.transpose(0, 3, 1, 2))).image_embeds)
    got = np.asarray(mine.apply(params, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize(
    "name,expected",
    [
        # Canonical torchvision parameter counts (weights+biases, not running stats).
        ("resnet18", 11_689_512),
        ("resnet50", 25_557_032),
        ("alexnet", 61_100_840),
        ("vit_b16", 86_567_656),  # torchvision vit_b_16 (1000-class head)
    ],
)
def test_canonical_param_counts(name, expected):
    # eval_shape: abstract init only — no compilation, instant even for ViT-B.
    spec = get_model(name)
    model = spec.module(dtype=jnp.float32)
    dummy = jnp.zeros((1, spec.input_size, spec.input_size, 3), jnp.float32)
    shapes = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), dummy, train=False))
    count = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(shapes["params"]))
    assert count == expected
