"""Golden-value parity tests against torch/HF reference implementations.

The reference validated inference against libtorch outputs implicitly (tch-rs
IS libtorch, src/services.rs:513-524); since this rebuild re-implements the
models from scratch, we verify numerics explicitly: instantiate a small
randomly-initialized torch reference model (no network access needed), run its
state dict through the REAL weight importers in models/convert.py, and require
the Flax outputs to agree. This tests model topology and converter layout
together — the same path `train`-distributed checkpoints take in production.

torchvision is not installed; for resnet/alexnet the reference modules are
defined here with torchvision's exact state-dict layout (the layout the
converters and common checkpoints use).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dmlc_tpu.models import convert, get_model

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

tnn = torch.nn
F = torch.nn.functional


def t2np(t):
    return t.detach().cpu().numpy()


def state_dict_np(module):
    return {k: t2np(v) for k, v in module.state_dict().items()}


def small_vit_config():
    return transformers.ViTConfig(
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
        image_size=32,
        patch_size=8,
        num_labels=10,
    )


def test_vit_parity_with_hf():
    from dmlc_tpu.models.vit import ViT

    cfg = small_vit_config()
    torch.manual_seed(0)
    hf = transformers.ViTForImageClassification(cfg).eval()
    mine = ViT(
        num_classes=10,
        patch_size=cfg.patch_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_hidden_layers,
        num_heads=cfg.num_attention_heads,
        mlp_dim=cfg.intermediate_size,
        dtype=jnp.float32,
        layer_norm_eps=cfg.layer_norm_eps,
        activation="gelu",
    )
    params = convert.vit_params_from_hf(state_dict_np(hf), cfg.num_hidden_layers)
    x = np.random.RandomState(0).randn(2, cfg.image_size, cfg.image_size, 3).astype(np.float32)
    with torch.no_grad():
        ref = t2np(hf(pixel_values=torch.from_numpy(x.transpose(0, 3, 1, 2))).logits)
    got = np.asarray(mine.apply(params, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-4)


def small_clip_config():
    return transformers.CLIPVisionConfig(
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
        image_size=32,
        patch_size=8,
        projection_dim=32,
    )


def test_clip_parity_with_hf():
    from dmlc_tpu.models.clip import CLIPVisionEncoder

    cfg = small_clip_config()
    torch.manual_seed(0)
    hf = transformers.CLIPVisionModelWithProjection(cfg).eval()
    mine = CLIPVisionEncoder(
        projection_dim=cfg.projection_dim,
        patch_size=cfg.patch_size,
        hidden_size=cfg.hidden_size,
        num_layers=cfg.num_hidden_layers,
        num_heads=cfg.num_attention_heads,
        mlp_dim=cfg.intermediate_size,
        dtype=jnp.float32,
        layer_norm_eps=cfg.layer_norm_eps,
    )
    params = convert.clip_params_from_hf(state_dict_np(hf), cfg.num_hidden_layers)
    x = np.random.RandomState(1).randn(2, cfg.image_size, cfg.image_size, 3).astype(np.float32)
    with torch.no_grad():
        ref = t2np(hf(pixel_values=torch.from_numpy(x.transpose(0, 3, 1, 2))).image_embeds)
    got = np.asarray(mine.apply(params, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=1e-4)


# ---------------------------------------------------------------------------
# torchvision-layout reference models (torchvision itself is not installed)
# ---------------------------------------------------------------------------


class TorchBasicBlock(tnn.Module):
    def __init__(self, in_ch, out_ch, stride=1):
        super().__init__()
        self.conv1 = tnn.Conv2d(in_ch, out_ch, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(out_ch)
        self.conv2 = tnn.Conv2d(out_ch, out_ch, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(out_ch)
        self.downsample = None
        if stride != 1 or in_ch != out_ch:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(in_ch, out_ch, 1, stride, bias=False), tnn.BatchNorm2d(out_ch)
            )

    def forward(self, x):
        identity = x
        y = F.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        if self.downsample is not None:
            identity = self.downsample(x)
        return F.relu(y + identity)


class TorchResNet18(tnn.Module):
    """torchvision resnet18 topology + state-dict layout."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = tnn.BatchNorm2d(64)
        self.maxpool = tnn.MaxPool2d(3, 2, 1)
        in_ch = 64
        for i, ch in enumerate([64, 128, 256, 512]):
            blocks = []
            for j in range(2):
                stride = 2 if i > 0 and j == 0 else 1
                blocks.append(TorchBasicBlock(in_ch, ch, stride))
                in_ch = ch
            setattr(self, f"layer{i + 1}", tnn.Sequential(*blocks))
        self.fc = tnn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        for i in range(4):
            x = getattr(self, f"layer{i + 1}")(x)
        return self.fc(x.mean(dim=(2, 3)))


class TorchAlexNet(tnn.Module):
    """torchvision alexnet topology + state-dict layout (224 input)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = tnn.Sequential(
            tnn.Conv2d(3, 64, 11, 4, 2), tnn.ReLU(), tnn.MaxPool2d(3, 2),
            tnn.Conv2d(64, 192, 5, 1, 2), tnn.ReLU(), tnn.MaxPool2d(3, 2),
            tnn.Conv2d(192, 384, 3, 1, 1), tnn.ReLU(),
            tnn.Conv2d(384, 256, 3, 1, 1), tnn.ReLU(),
            tnn.Conv2d(256, 256, 3, 1, 1), tnn.ReLU(), tnn.MaxPool2d(3, 2),
        )
        self.classifier = tnn.Sequential(
            tnn.Dropout(), tnn.Linear(256 * 6 * 6, 4096), tnn.ReLU(),
            tnn.Dropout(), tnn.Linear(4096, 4096), tnn.ReLU(),
            tnn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        return self.classifier(torch.flatten(x, 1))


def randomize_bn_stats(module, seed=0):
    """Random running stats so eval-mode BN actually exercises the converted
    batch_stats (fresh stats are 0/1, which would hide a mapping bug)."""
    g = torch.Generator().manual_seed(seed)
    for m in module.modules():
        if isinstance(m, tnn.BatchNorm2d):
            m.running_mean.copy_(torch.randn(m.running_mean.shape, generator=g) * 0.1)
            m.running_var.copy_(torch.rand(m.running_var.shape, generator=g) + 0.5)


def test_resnet18_parity_with_torch():
    from dmlc_tpu.models.resnet import resnet18

    torch.manual_seed(0)
    ref = TorchResNet18(num_classes=10)
    randomize_bn_stats(ref)
    ref.eval()
    variables = convert.resnet_params_from_torch(
        state_dict_np(ref), stage_sizes=[2, 2, 2, 2], bottleneck=False
    )
    mine = resnet18(num_classes=10, dtype=jnp.float32)
    x = np.random.RandomState(0).randn(2, 64, 64, 3).astype(np.float32)
    with torch.no_grad():
        want = t2np(ref(torch.from_numpy(x.transpose(0, 3, 1, 2))))
    got = np.asarray(mine.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_alexnet_parity_with_torch():
    from dmlc_tpu.models.alexnet import alexnet

    torch.manual_seed(1)
    ref = TorchAlexNet(num_classes=10).eval()
    variables = convert.alexnet_params_from_torch(state_dict_np(ref))
    mine = alexnet(num_classes=10, dtype=jnp.float32)
    x = np.random.RandomState(1).randn(2, 224, 224, 3).astype(np.float32)
    with torch.no_grad():
        want = t2np(ref(torch.from_numpy(x.transpose(0, 3, 1, 2))))
    got = np.asarray(mine.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_import_external_resnet18_full_size():
    """The registry-level importer: a full torchvision-layout resnet18 state
    dict converts into a tree that passes the registry shape validation."""
    from dmlc_tpu.models import weights as weights_lib

    torch.manual_seed(2)
    sd = state_dict_np(TorchResNet18(num_classes=1000))
    variables = weights_lib.import_external("resnet18", sd)  # validates internally
    assert "params" in variables and "batch_stats" in variables
    with pytest.raises(KeyError):
        weights_lib.import_external("no_such_model", sd)


@pytest.mark.parametrize(
    "name,expected",
    [
        # Canonical torchvision parameter counts (weights+biases, not running stats).
        ("resnet18", 11_689_512),
        ("resnet50", 25_557_032),
        ("alexnet", 61_100_840),
        ("vit_b16", 86_567_656),  # torchvision vit_b_16 (1000-class head)
    ],
)
def test_canonical_param_counts(name, expected):
    # eval_shape: abstract init only — no compilation, instant even for ViT-B.
    spec = get_model(name)
    model = spec.module(dtype=jnp.float32)
    dummy = jnp.zeros((1, spec.input_size, spec.input_size, 3), jnp.float32)
    shapes = jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0), dummy, train=False))
    count = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(shapes["params"]))
    assert count == expected
