"""Metrics plane + flight recorder units (docs/OBSERVABILITY.md).

- utils/metrics.Registry: counters + named LatencyStats + gauges behind one
  snapshot; Prometheus text exposition (local and fleet-labeled).
- LatencyStats.merge reservoir weighting: the statistical regression for
  the old per-element offer bias.
- Tracer drop accounting: past max_events drops are counted, surfaced in
  summary(), and annotated in the Chrome export metadata.
- cluster/flight.FlightRecorder: bounded ring, wire shape, durable dump,
  and the component wiring (breaker open/close, gray demote, shed,
  quarantine).
"""

from __future__ import annotations

import json
import statistics

import pytest

from dmlc_tpu.cluster.admission import AdmissionGate
from dmlc_tpu.cluster.flight import FlightRecorder
from dmlc_tpu.cluster.retrypolicy import RetryPolicy
from dmlc_tpu.cluster.rpc import Overloaded, RpcUnreachable
from dmlc_tpu.utils.metrics import (
    Counters,
    LatencyStats,
    Registry,
    render_prometheus,
)
from dmlc_tpu.utils.tracing import Tracer


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_snapshot_unifies_counters_latency_gauges(self):
        r = Registry()
        r.counters.inc("shed", 3)
        r.latency("rpc/job.predict").extend([0.01, 0.02, 0.03])
        r.gauge("queue_depth", lambda: 7)
        snap = r.snapshot()
        assert snap["counters"]["shed"] == 3
        assert snap["latency"]["rpc/job.predict"]["count"] == 3.0
        assert snap["gauges"]["queue_depth"] == 7.0

    def test_shares_an_existing_counters_instance(self):
        c = Counters()
        r = Registry(counters=c)
        c.inc("deadline_exceeded")
        assert r.snapshot()["counters"]["deadline_exceeded"] == 1

    def test_broken_gauge_reports_none_not_error(self):
        r = Registry()
        r.gauge("bad", lambda: 1 / 0)
        assert r.snapshot()["gauges"]["bad"] is None

    def test_latency_returns_same_collector(self):
        r = Registry()
        assert r.latency("a") is r.latency("a")

    def test_prometheus_text(self):
        r = Registry()
        r.counters.inc("shed", 2)
        r.counters.observe_high("queue", 9)
        r.gauge("active", lambda: 4)
        r.latency("rpc/sdfs.fetch").extend([0.1] * 10)
        text = r.prometheus_text()
        assert "# TYPE dmlc_shed counter" in text
        assert "dmlc_shed 2" in text
        assert "dmlc_active 4.0" in text
        assert 'dmlc_rpc_sdfs_fetch_seconds{quantile="0.99"} 0.1' in text
        assert "dmlc_rpc_sdfs_fetch_seconds_count 10" in text
        # high-water marks ride the counters snapshot
        assert "dmlc_queue_high 9" in text

    def test_prometheus_node_labels(self):
        r = Registry()
        r.counters.inc("shed")
        text = render_prometheus(r.snapshot(), labels='node="10.0.0.1:8852"')
        assert 'dmlc_shed{node="10.0.0.1:8852"} 1' in text

    def test_empty_registry_renders_empty(self):
        assert Registry().prometheus_text() == ""

    def test_prometheus_histogram_exposition(self):
        r = Registry()
        r.latency("rpc/sdfs.fetch").extend([0.1] * 10)
        text = r.prometheus_text()
        assert "# TYPE dmlc_rpc_sdfs_fetch_hist_seconds histogram" in text
        assert 'dmlc_rpc_sdfs_fetch_hist_seconds_bucket{le="0.1"} 10' in text
        assert 'dmlc_rpc_sdfs_fetch_hist_seconds_bucket{le="+Inf"} 10' in text
        assert "dmlc_rpc_sdfs_fetch_hist_seconds_count 10" in text
        assert "dmlc_rpc_sdfs_fetch_hist_seconds_sum" in text
        # Cumulative: buckets below the value stay at 0.
        assert 'dmlc_rpc_sdfs_fetch_hist_seconds_bucket{le="0.05"} 0' in text

    def test_prometheus_histogram_with_node_label(self):
        r = Registry()
        r.latency("rpc/sdfs.fetch").extend([0.1] * 4)
        text = render_prometheus(r.snapshot(), labels='node="10.0.0.1:8852"')
        assert (
            'dmlc_rpc_sdfs_fetch_hist_seconds_bucket'
            '{node="10.0.0.1:8852",le="0.1"} 4' in text
        )

    def test_histogram_absent_for_legacy_wire(self):
        """A pre-histogram peer's snapshot (no buckets) must not render a
        hist family contradicting its own _count."""
        r = Registry()
        r.latency("x").extend([0.1] * 10)
        snap = r.snapshot()
        del snap["latency"]["x"]["buckets"]
        text = render_prometheus(snap)
        assert "_hist_seconds" not in text
        assert "dmlc_x_seconds_count 10" in text

    def test_histogram_buckets_merge_and_roundtrip(self):
        a = LatencyStats([0.01] * 4)
        b = LatencyStats([1.0] * 6)
        a.merge(LatencyStats.from_wire(b.to_wire()))
        buckets = a.summary()["buckets"]
        assert buckets["0.01"] == 4
        assert buckets["1.0"] == 10
        assert buckets["+Inf"] == 10


# ---------------------------------------------------------------------------
# LatencyStats.merge: weighted reservoir regression
# ---------------------------------------------------------------------------


class TestWeightedMerge:
    def test_moments_still_exact(self):
        a = LatencyStats([1.0, 2.0, 3.0])
        b = LatencyStats([4.0, 5.0])
        a.merge(b)
        assert a.n == 5
        assert a.mean == pytest.approx(3.0)
        assert a.std == pytest.approx(statistics.stdev([1, 2, 3, 4, 5]))

    def test_small_merges_keep_everything(self):
        a = LatencyStats([1.0, 2.0])
        a.merge(LatencyStats([3.0]))
        assert sorted(a.reservoir) == [1.0, 2.0, 3.0]

    def test_peer_with_many_offers_gets_its_true_weight(self):
        """The regression: ``other`` saw 64x more observations than its
        reservoir holds. A correct weighted merge yields a reservoir whose
        composition tracks the TRUE mixture (~98.5% other); the old
        per-element Algorithm-R offer walk converged to ~len(reservoir)
        worth of weight instead (~66% here) — far outside the tolerance."""
        K = LatencyStats.RESERVOIR_SIZE
        a = LatencyStats()
        for _ in range(2 * K):          # self: 8192 offers of 0.0
            a.record(0.0)
        b = LatencyStats()
        for _ in range(128 * K):        # other: 524288 offers of 1.0
            b.record(1.0)
        a.merge(b)
        assert a._offers == 130 * K
        frac_other = sum(1 for v in a.reservoir if v == 1.0) / len(a.reservoir)
        expected = 128 / 130  # ≈ 0.9846
        assert frac_other == pytest.approx(expected, abs=0.01)
        # And the percentile view agrees: the p50/p90 are the peer's value.
        assert a.percentile(50) == 1.0

    def test_merge_is_deterministic(self):
        def build():
            a = LatencyStats([float(i) for i in range(5000)])
            b = LatencyStats()
            for i in range(20000):
                b.record(float(i) + 0.5)
            a.merge(b)
            return list(a.reservoir)

        assert build() == build()

    def test_wire_roundtrip_preserves_offer_weight(self):
        b = LatencyStats()
        for _ in range(100_000):
            b.record(1.0)
        b2 = LatencyStats.from_wire(b.to_wire())
        a = LatencyStats([0.0] * 100)
        a.merge(b2)
        frac = sum(1 for v in a.reservoir if v == 1.0) / len(a.reservoir)
        assert frac > 0.99  # 100k vs 100 offers


# ---------------------------------------------------------------------------
# Tracer drop accounting
# ---------------------------------------------------------------------------


class TestTracerDrops:
    def test_drops_counted_and_surfaced(self, tmp_path):
        t = Tracer(max_events=5)
        t.enabled = True
        for i in range(12):
            with t.span("s"):
                pass
        assert t.dropped_events == 7
        summary = t.summary()
        assert summary["dropped_events"] == 7
        assert summary["s"]["count"] == 12.0  # aggregates stay exact
        path = tmp_path / "trace.json"
        t.export(path)
        doc = json.loads(path.read_text())
        assert doc["otherData"]["dropped_events"] == 7
        assert len(doc["traceEvents"]) == 5

    def test_no_drops_keeps_pure_summary_shape(self):
        t = Tracer()
        t.enabled = True
        with t.span("s"):
            pass
        assert "dropped_events" not in t.summary()

    def test_reset_clears_drop_count(self):
        t = Tracer(max_events=1)
        t.enabled = True
        for _ in range(3):
            with t.span("s"):
                pass
        assert t.dropped_events == 2
        t.reset()
        assert t.dropped_events == 0


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded_and_counts_everything(self):
        clock = FakeClock()
        fr = FlightRecorder(capacity=4, clock=clock, node="n1")
        for i in range(10):
            clock.t = float(i)
            fr.note("shed", seq=i)
        wire = fr.to_wire()
        assert wire["recorded"] == 10
        assert wire["dropped"] == 6
        assert [e["seq"] for e in wire["events"]] == [6, 7, 8, 9]
        assert [e["t"] for e in wire["events"]] == [6.0, 7.0, 8.0, 9.0]
        assert wire["node"] == "n1"

    def test_dump_is_valid_json_on_disk(self, tmp_path):
        fr = FlightRecorder(capacity=8, clock=FakeClock())
        fr.note("breaker_open", dest="m1", error="unreachable")
        path = tmp_path / "flight.json"
        assert fr.dump(path, reason="test")
        doc = json.loads(path.read_text())
        assert doc["dump_reason"] == "test"
        assert doc["events"][0]["kind"] == "breaker_open"

    def test_breaker_transitions_recorded(self):
        clock = FakeClock()
        fr = FlightRecorder(clock=clock)
        policy = RetryPolicy(
            clock=clock, breaker_threshold=2, breaker_cooldown_s=1.0,
            flight=fr,
        )
        err = RpcUnreachable("down")
        policy.record("m1", err)
        policy.record("m1", err)   # threshold -> open
        clock.t = 2.0              # past cooldown
        assert policy.allow("m1")  # half-open probe
        policy.record("m1")        # probe success -> close
        kinds = [(e["kind"], e.get("dest")) for e in fr.events()]
        assert ("breaker_open", "m1") in kinds
        assert ("breaker_close", "m1") in kinds

    def test_shed_recorded_by_admission_gate(self):
        fr = FlightRecorder(clock=FakeClock())
        gate = AdmissionGate(1, 0, name="predict", flight=fr)
        with gate.admit():
            with pytest.raises(Overloaded):
                with gate.admit():
                    pass
        events = fr.events()
        assert events and events[0]["kind"] == "shed"
        assert events[0]["gate"] == "predict"

    def test_quarantine_recorded_by_store(self, tmp_path):
        from dmlc_tpu.cluster.sdfs import MemberStore

        fr = FlightRecorder(clock=FakeClock())
        store = MemberStore(tmp_path / "storage", flight=fr)
        store.receive("f", 1, b"bytes")
        # Rot the blob at rest, then read: quarantine + flight event.
        path = store.blob_path("f", 1)
        path.write_bytes(b"rotten")
        with pytest.raises(Exception):
            store.read("f", 1)
        events = [e for e in fr.events() if e["kind"] == "quarantine"]
        assert events and events[0]["name"] == "f" and events[0]["version"] == 1

    def test_gray_demotion_recorded_by_scheduler(self):
        from dmlc_tpu.cluster.rpc import SimRpcNetwork
        from dmlc_tpu.scheduler.jobs import JobScheduler

        clock = FakeClock()
        fr = FlightRecorder(clock=clock)
        net = SimRpcNetwork()
        sched = JobScheduler(
            net.client("L"), lambda: ["m1", "m2", "m3"], jobs={},
            timer=clock, gray_factor=2.0, gray_min_latency_s=0.01,
            flight=fr,
        )
        # m3 is 100x slower than the fleet; the gray check demotes it.
        for m, lat in (("m1", 0.02), ("m2", 0.02), ("m3", 2.0)):
            with sched._lock:
                sched._observe_member(m, lat)
        with sched._lock:
            sched._gray_check()
        assert "m3" in sched.demoted
        kinds = [(e["kind"], e.get("member")) for e in fr.events()]
        assert ("gray_demote", "m3") in kinds

    def test_node_crash_dump_on_loop_error(self, tmp_path):
        """A crashing maintenance loop must leave a postmortem file behind
        (the auto-dump path), not just a log line."""
        from dmlc_tpu.cluster.localcluster import (
            start_local_cluster,
            stop_local_cluster,
            wait_until,
        )

        nodes = start_local_cluster(
            tmp_path, 1, n_leader_candidates=1,
            scrub_interval_s=0.05, scrub_batch=1,
        )
        try:
            node = nodes[0]
            # Sabotage the scrub loop's body: next tick raises inside _loop.
            node.store.scrub_once = None  # type: ignore[assignment]
            wait_until(
                lambda: node.flight_dump_path().exists(),
                timeout=15.0,
                msg="flight ring dumped on loop error",
            )
            doc = json.loads(node.flight_dump_path().read_text())
            assert doc["dump_reason"] == "loop_error"
            assert any(e["kind"] == "loop_error" for e in doc["events"])
        finally:
            stop_local_cluster(nodes)
        # stop() dumps again with reason=stop, overwriting — fine: the ring
        # still contains the loop_error event.
        doc = json.loads(nodes[0].flight_dump_path().read_text())
        assert any(e["kind"] == "loop_error" for e in doc["events"])

    def test_obs_flight_rpc_serves_the_ring(self):
        from dmlc_tpu.cluster.observe import ObsService
        from dmlc_tpu.cluster.rpc import SimRpcNetwork

        fr = FlightRecorder(clock=FakeClock(), node="n1")
        fr.note("gray_demote", member="m9", reason="slow")
        net = SimRpcNetwork()
        net.serve("n1", ObsService(Registry(), flight=fr, lane="n1").methods())
        wire = net.client("c").call("n1", "obs.flight", {}, timeout=5.0)
        assert wire["events"][0]["kind"] == "gray_demote"
        assert wire["node"] == "n1"


# ---------------------------------------------------------------------------
# Fleet trace merge: per-node skew accounting + the clamp alert
# ---------------------------------------------------------------------------


class TestTraceSkew:
    @staticmethod
    def _node(events, offset=0.0, rtt=0.001):
        return {"dump": {"events": events, "dropped": 0},
                "offset": offset, "rtt": rtt}

    PARENT = {"name": "rpc/job.predict", "start": 1.0, "dur": 0.5,
              "span": "s1", "trace": "t1"}

    def _child(self, start: float) -> dict:
        return {"name": "device/forward", "start": start, "dur": 0.1,
                "span": "s2", "parent": "s1", "trace": "t1"}

    def test_clamp_skew_measured_per_node_and_alerted(self):
        from dmlc_tpu.cluster.observe import merge_fleet_trace

        fr = FlightRecorder(clock=FakeClock())
        doc = merge_fleet_trace(
            {"a": self._node([self.PARENT]),
             "b": self._node([self._child(0.9)])},
            flight=fr, skew_alert_s=0.05,
        )
        nodes = doc["otherData"]["nodes"]
        assert nodes["b"]["max_skew_s"] == pytest.approx(0.1)
        assert nodes["a"]["max_skew_s"] == 0.0
        assert doc["otherData"]["skew_clamped_children"] == 1
        # The child renders AT its parent's start, never before it.
        rendered = [e for e in doc["traceEvents"]
                    if e.get("ph") == "X" and e["name"] == "device/forward"]
        assert rendered[0]["ts"] == pytest.approx(1.0 * 1e6)
        alerts = [e for e in fr.events() if e["kind"] == "trace_skew_clamp"]
        assert len(alerts) == 1
        assert alerts[0]["node"] == "b"
        assert alerts[0]["max_skew_s"] == pytest.approx(0.1)
        assert alerts[0]["clamped"] == 1
        assert alerts[0]["threshold_s"] == 0.05

    def test_sub_threshold_skew_clamps_quietly(self):
        from dmlc_tpu.cluster.observe import merge_fleet_trace

        fr = FlightRecorder(clock=FakeClock())
        doc = merge_fleet_trace(
            {"a": self._node([self.PARENT]),
             "b": self._node([self._child(0.99)])},
            flight=fr, skew_alert_s=0.05,
        )
        # Clamped (causality must still render forward) but under the
        # alert line: no flight noise for sub-RTT jitter.
        assert doc["otherData"]["skew_clamped_children"] == 1
        assert doc["otherData"]["nodes"]["b"]["max_skew_s"] == pytest.approx(0.01)
        assert not [e for e in fr.events() if e["kind"] == "trace_skew_clamp"]

    def test_merge_without_flight_still_reports_skew(self):
        from dmlc_tpu.cluster.observe import merge_fleet_trace

        doc = merge_fleet_trace(
            {"a": self._node([self.PARENT]),
             "b": self._node([self._child(0.8)])},
        )
        assert doc["otherData"]["nodes"]["b"]["max_skew_s"] == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# Partial fleets: scrape-tree staleness + streaming trace merge
# ---------------------------------------------------------------------------


def _sim_obs_fleet(n: int):
    """N sim members each serving the real obs surface (ObsService +
    ScrapeDelegate) with a distinguishable counter load."""
    from dmlc_tpu.cluster.observe import ObsService
    from dmlc_tpu.cluster.rpc import SimRpcNetwork
    from dmlc_tpu.cluster.scrapetree import ScrapeDelegate
    from dmlc_tpu.utils.metrics import Registry

    net = SimRpcNetwork()
    addrs = [f"m{i:02d}:1" for i in range(n)]
    registries: dict[str, Registry] = {}
    for i, addr in enumerate(addrs):
        reg = Registry()
        reg.counters.inc("work", i + 1)
        reg.latency("rpc/job.predict").extend([0.01 * (i + 1)] * 3)
        table = ObsService(reg, lane=addr).methods()
        table.update(ScrapeDelegate(
            net.client(addr), timeout_s=1.0, concurrency=1
        ).methods())
        net.serve(addr, table)
        registries[addr] = reg
    return net, addrs, registries


class TestScrapeTreePartialFleet:
    def _coordinator(self, net, clock=None):
        from dmlc_tpu.cluster.scrapetree import ScrapeTreeCoordinator

        return ScrapeTreeCoordinator(
            net.client("leader:0"), clock=clock or net.clock, timeout_s=1.0,
            concurrency=1,
        )

    def test_dead_span_is_flagged_stale_not_lost_not_raised(self):
        # THE pinned contract: every delegate candidate of one span dying
        # mid-cycle still yields a merged snapshot — the dark span is
        # FLAGGED stale (never an exception, never silently absent).
        net, addrs, registries = _sim_obs_fleet(9)  # spans of 3
        spans_of_three = [addrs[0:3], addrs[3:6], addrs[6:9]]
        for dead in spans_of_three[1][:2]:  # both delegate candidates
            net.crash(dead)
        coord = self._coordinator(net)
        result = coord.scrape(addrs)  # must not raise
        assert len(result.stale_spans) == 1
        assert result.stale_spans[0]["addrs"] == spans_of_three[1]
        assert result.stale_spans[0]["reason"]
        # Live spans are all present; the dark span is absent from members
        # but named in stale_spans — flagged loss, not silent loss.
        assert sorted(result.members) == sorted(spans_of_three[0] + spans_of_three[2])
        merged_work = result.merged["counters"]["work"]
        expected = sum(
            registries[a].counters.get("work")
            for a in spans_of_three[0] + spans_of_three[2]
        )
        assert merged_work == expected

    def test_stale_for_tracks_last_fresh_stamp(self):
        net, addrs, _ = _sim_obs_fleet(9)
        coord = self._coordinator(net)
        first = coord.scrape(addrs)
        assert not first.stale_spans and len(first.members) == 9
        for dead in addrs[3:5]:
            net.crash(dead)
        net.advance(5.0)
        second = coord.scrape(addrs)
        assert len(second.stale_spans) == 1
        assert second.stale_spans[0]["stale_for_s"] == pytest.approx(5.0)
        # Fresh spans carry this cycle's stamp.
        assert all(t == pytest.approx(5.0) for t in second.stamps.values())

    def test_dead_primary_redelegates_to_next_in_span(self):
        net, addrs, _ = _sim_obs_fleet(9)
        net.crash(addrs[3])  # span 2's primary delegate; alternate lives
        result = self._coordinator(net).scrape(addrs)
        assert not result.stale_spans
        assert result.redelegations == 1
        assert addrs[4] in result.delegates
        # The crashed node is still a member of the span: it shows up as
        # missed by the alternate's fan-out, not silently dropped.
        assert addrs[3] in result.missed
        assert addrs[3] not in result.members


class TestFleetTraceMergerStreaming:
    @staticmethod
    def _node(events, offset=0.0, rtt=0.001):
        return {"dump": {"events": events, "dropped": 0},
                "offset": offset, "rtt": rtt}

    PARENT = {"name": "rpc/job.predict", "start": 1.0, "dur": 0.5,
              "span": "s1", "trace": "t1"}
    CHILD = {"name": "device/forward", "start": 0.9, "dur": 0.1,
             "span": "s2", "parent": "s1", "trace": "t1"}

    def test_streaming_merge_equals_one_shot(self):
        from dmlc_tpu.cluster.observe import FleetTraceMerger, merge_fleet_trace

        per_node = {
            "a": self._node([self.PARENT], offset=0.002),
            "b": self._node([self.CHILD], offset=-0.001),
        }
        one_shot = merge_fleet_trace(per_node, unreachable={"c": "down"})
        merger = FleetTraceMerger()
        for addr in sorted(per_node):
            entry = per_node[addr]
            merger.add_node(addr, entry["dump"], offset=entry["offset"],
                            rtt=entry["rtt"])
        merger.add_unreachable("c", "down")
        assert merger.finish() == one_shot

    def test_partial_fleet_is_flagged_not_silent(self):
        from dmlc_tpu.cluster.observe import merge_fleet_trace

        doc = merge_fleet_trace(
            {"a": self._node([self.PARENT])}, unreachable={"b": "rpc: boom"}
        )
        assert doc["otherData"]["unreachable"] == {"b": "rpc: boom"}
        assert "b" not in doc["otherData"]["nodes"]
        # The reachable node's spans still made it.
        assert sum(1 for e in doc["traceEvents"] if e.get("ph") == "X") == 1

    def test_parent_arriving_after_child_still_clamps(self):
        from dmlc_tpu.cluster.observe import FleetTraceMerger

        # Collection order: the child's node reports BEFORE the parent's —
        # the deferred clamp pass must still see the parent's start.
        merger = FleetTraceMerger()
        merger.add_node("b", {"events": [self.CHILD], "dropped": 0})
        merger.add_node("a", {"events": [self.PARENT], "dropped": 0})
        doc = merger.finish()
        rendered = [e for e in doc["traceEvents"]
                    if e.get("ph") == "X" and e["name"] == "device/forward"]
        assert rendered[0]["ts"] == pytest.approx(1.0 * 1e6)
        assert doc["otherData"]["skew_clamped_children"] == 1

    def test_orphan_spans_adopted_under_synthetic_root(self):
        # A span whose parent was dropped (sampling budget, ring overflow,
        # dead member) must NOT vanish from the merged document or dangle
        # with a broken parent edge: its trace gets ONE synthetic root
        # spanning the hull, adopting the orphan AND the trace's true
        # top-level spans, and the degradation is counted in
        # otherData.orphan_spans (docs/OBSERVABILITY.md section 9).
        from dmlc_tpu.cluster.critpath import ORPHAN_ROOT_NAME
        from dmlc_tpu.cluster.observe import merge_fleet_trace

        orphan = {"name": "gen/step", "start": 1.2, "dur": 0.1,
                  "span": "s9", "parent": "never-arrived", "trace": "t1"}
        doc = merge_fleet_trace({
            "a": self._node([self.PARENT]),
            "b": self._node([orphan]),
        })
        assert doc["otherData"]["orphan_spans"] == 1
        roots = [e for e in doc["traceEvents"]
                 if e.get("name") == ORPHAN_ROOT_NAME]
        assert len(roots) == 1
        root = roots[0]
        assert root["args"]["trace"] == "t1"
        assert root["args"]["synthetic"] is True
        # The root spans the trace hull (PARENT [1.0, 1.5] + orphan
        # [1.2, 1.3], in microseconds).
        assert root["ts"] == pytest.approx(1.0 * 1e6)
        assert root["dur"] == pytest.approx(0.5 * 1e6)
        # BOTH the orphan and the true top-level span hang off it, so
        # downstream consumers (Perfetto nesting, critpath extraction) see
        # one rooted tree per trace.
        by_span = {e["args"].get("span"): e for e in doc["traceEvents"]
                   if e.get("ph") == "X"}
        assert by_span["s9"]["args"]["parent"] == root["args"]["span"]
        assert by_span["s1"]["args"]["parent"] == root["args"]["span"]

    def test_orphan_adoption_keeps_critpath_shares_partitioned(self):
        # Graceful degradation end to end: the adopted document feeds the
        # critical-path extractor and shares still partition the charged
        # time exactly — overlap between the orphan subtree and the covered
        # chain stays concurrent, never double-charged.
        from dmlc_tpu.cluster.critpath import breakdown, spans_from_perfetto
        from dmlc_tpu.cluster.observe import merge_fleet_trace

        orphan = {"name": "gen/step", "start": 1.2, "dur": 0.1,
                  "span": "s9", "parent": "never-arrived", "trace": "t1",
                  "attrs": {"model": "lm_small"}}
        doc = merge_fleet_trace({
            "a": self._node([dict(self.PARENT, lane="a")]),
            "b": self._node([dict(orphan, lane="b")]),
        })
        crit = breakdown(spans_from_perfetto(doc))
        (body,) = crit.values()
        total = sum(ln["share"] for ln in body["lanes"])
        assert total == pytest.approx(1.0)
        assert body["requests"] == 1

    def test_no_orphans_no_synthetic_roots(self):
        from dmlc_tpu.cluster.critpath import ORPHAN_ROOT_NAME
        from dmlc_tpu.cluster.observe import merge_fleet_trace

        doc = merge_fleet_trace({
            "a": self._node([self.PARENT]),
            "b": self._node([self.CHILD]),
        })
        assert "orphan_spans" not in doc["otherData"]
        assert not [e for e in doc["traceEvents"]
                    if e.get("name") == ORPHAN_ROOT_NAME]
