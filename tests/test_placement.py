"""Profile-driven placement + SLO burn-rate monitoring (the control loop).

Three layers, all on virtual clocks:

- PlacementAdvisor unit tests: cost-balanced dealing, weight normalization
  to the slowest member, sticky exclusion with re-entry hysteresis,
  plan hysteresis, the move budget, and the stale-plan bypass;
- SloEvaluator transition tests: burn-rate math, alert edges (fire once,
  clear with hysteresis), the fast-burn callback, gauges and flight events;
- the acceptance soak on the sim fabric: one member degraded 5x -> the
  fast-burn alert fires -> the advisor excludes it within the move budget
  -> fleet p99 returns under the objective within three fast windows, and
  every decision along the way is reconstructible from the flight recorder.

CI runs this file inside the chaos seed matrix (tools/ci_check.sh): the
DMLC_CHAOS_SEED base offsets every parametrized seed range.
"""

from __future__ import annotations

import os
import random

import pytest

from dmlc_tpu.cluster.flight import FlightRecorder
from dmlc_tpu.cluster.profile import CostProfiler
from dmlc_tpu.cluster.rpc import SimRpcNetwork
from dmlc_tpu.scheduler.jobs import JobScheduler
from dmlc_tpu.scheduler.placement import (
    PlacementAdvisor,
    PlacementPlan,
    SloEvaluator,
    SloObjective,
)
from dmlc_tpu.scheduler.worker import PredictWorker, gang_slice
from dmlc_tpu.utils.metrics import Counters

SEED_BASE = int(os.environ.get("DMLC_CHAOS_SEED", "0"))


def seeds(n: int) -> range:
    return range(SEED_BASE, SEED_BASE + n)


class VClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_profiler(clock, **kw) -> CostProfiler:
    kw.setdefault("window_s", 10.0)
    kw.setdefault("windows", 4)
    kw.setdefault("decay", 0.5)
    return CostProfiler(clock=clock, **kw)


def feed(prof: CostProfiler, costs: dict, model: str = "resnet18", n: int = 8):
    """One amortized dispatch record per member at its scripted cost."""
    for m, c in costs.items():
        prof.record(model, m, "dispatch", c, count=n)


def make_workload(n):
    return [(f"n{i:05d}", i) for i in range(n)]


# ---------------------------------------------------------------------------
# PlacementAdvisor: the solver
# ---------------------------------------------------------------------------


class TestPlacementAdvisor:
    def test_abstains_with_nothing_to_place(self):
        adv = PlacementAdvisor(make_profiler(VClock()))
        assert adv.advise({}, ["m0"]) is None
        assert adv.advise({"job": 10}, []) is None

    def test_weights_normalize_to_the_slowest_member(self):
        clock = VClock()
        prof = make_profiler(clock)
        flight = FlightRecorder(clock=clock)
        adv = PlacementAdvisor(prof, flight=flight, clock=clock)
        feed(prof, {"m0": 0.1, "m1": 0.4})
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        assert plan.assignment == {"job": ["m0", "m1"]}
        # The slowest member anchors at weight 1; the 4x-faster one gets 4x
        # the dispatch-pool share.
        assert plan.weights["job"] == {"m0": 4, "m1": 1}
        assert any(e["kind"] == "placement_decision" for e in flight.events())

    def test_weight_amplification_is_capped(self):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(prof, clock=clock)
        feed(prof, {"m0": 0.01, "m1": 1.0})
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        # Raw ratio is 100x; the cap keeps one fast member from starving
        # the interleave of everyone else.
        assert plan.weights["job"]["m0"] == PlacementAdvisor.MAX_WEIGHT
        assert plan.weights["job"]["m1"] == 1

    def test_exclusion_is_sticky_until_well_under_the_line(self):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(prof, clock=clock, exclude_factor=3.0)
        jobs = {"job": 100}
        members = ["m0", "m1", "m2", "m3", "m4"]
        # Fleet at 0.1, one outlier at 1.0: line = 3 x median = 0.3.
        feed(prof, {"m0": 0.1, "m1": 0.1, "m2": 0.1, "m3": 0.1, "m4": 1.0})
        plan = adv.advise(jobs, members)
        assert plan.excluded == ["m4"]
        assert "m4" not in plan.assignment["job"]
        # Recovers into the hysteresis band (0.25 > 0.7 x line = 0.21):
        # still excluded — a member hovering at the line must not flap.
        clock.advance(50.0)  # the old windows age past the whole history
        feed(prof, {"m0": 0.1, "m1": 0.1, "m2": 0.1, "m3": 0.1, "m4": 0.25})
        adv.advise(jobs, members)
        assert adv.status()["excluded"] == ["m4"]
        # Well back under the re-entry line: re-admitted.
        clock.advance(50.0)
        feed(prof, {"m0": 0.1, "m1": 0.1, "m2": 0.1, "m3": 0.1, "m4": 0.12})
        plan3 = adv.advise(jobs, members)
        assert adv.status()["excluded"] == []
        assert "m4" in plan3.assignment["job"]

    def test_readmits_cheapest_when_jobs_outnumber_eligible(self):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(prof, clock=clock)
        feed(prof, {"m0": 0.1, "m1": 0.1, "m2": 10.0})
        plan = adv.advise({"a": 10, "b": 10, "c": 10}, ["m0", "m1", "m2"])
        # m2 is over the line, but three jobs need three members:
        # availability wins and the outlier is re-admitted.
        assert plan.excluded == []
        assert sorted(m for ms in plan.assignment.values() for m in ms) == [
            "m0", "m1", "m2",
        ]
        assert all(len(ms) == 1 for ms in plan.assignment.values())

    def test_identical_inputs_return_the_cached_plan(self):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(prof, clock=clock)
        feed(prof, {"m0": 0.1, "m1": 0.1})
        first = adv.advise({"job": 10}, ["m0", "m1"])
        assert adv.advise({"job": 10}, ["m0", "m1"]) is first

    def test_move_budget_throttles_churn(self):
        clock = VClock()
        prof = make_profiler(clock)
        metrics = Counters()
        flight = FlightRecorder(clock=clock)
        adv = PlacementAdvisor(
            prof, flight=flight, metrics=metrics, clock=clock,
            max_moves=2, window_s=1000.0, hysteresis=0.15,
        )
        jobs = {"a": 10, "b": 10}
        members = ["m0", "m1", "m2", "m3"]
        feed(prof, {m: 0.1 for m in members})
        first = adv.advise(jobs, members)
        # m3 becomes 10x faster: the solver wants a 3-move reshuffle that
        # clears hysteresis but blows the 2-move budget — throttled.
        clock.advance(50.0)
        feed(prof, {"m0": 0.1, "m1": 0.1, "m2": 0.1, "m3": 0.01})
        second = adv.advise(jobs, members)
        assert second is first
        assert metrics.get("placement_throttled") == 1
        assert any(e["kind"] == "placement_throttled" for e in flight.events())

    def test_hysteresis_rejects_marginal_improvements(self):
        clock = VClock()
        prof = make_profiler(clock)
        metrics = Counters()
        adv = PlacementAdvisor(
            prof, metrics=metrics, clock=clock,
            max_moves=100, window_s=1000.0, hysteresis=0.5,
        )
        jobs = {"a": 10, "b": 10}
        members = ["m0", "m1", "m2", "m3"]
        feed(prof, {m: 0.1 for m in members})
        first = adv.advise(jobs, members)
        clock.advance(50.0)
        feed(prof, {"m0": 0.1, "m1": 0.1, "m2": 0.1, "m3": 0.01})
        # The reshuffle improves the estimate ~33% — under the 50% bar, so
        # the previous plan stands (and this is NOT the budget's doing).
        assert adv.advise(jobs, members) is first
        assert metrics.get("placement_throttled") == 0
        assert metrics.get("placement_decisions") == 1

    def test_stale_plan_bypasses_hysteresis_and_budget(self):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(
            prof, clock=clock, max_moves=0, window_s=1000.0, hysteresis=0.99,
        )
        jobs = {"a": 10, "b": 10}
        feed(prof, {m: 0.1 for m in ["m0", "m1", "m2", "m3"]})
        first = adv.advise(jobs, ["m0", "m1", "m2", "m3"])
        assert "m3" in {m for ms in first.assignment.values() for m in ms}
        # m3 departs: the cached plan references a gone member, so even a
        # zero budget and maximal hysteresis cannot pin the fleet to it.
        second = adv.advise(jobs, ["m0", "m1", "m2"])
        assert second is not first
        assert all(
            m != "m3" for ms in second.assignment.values() for m in ms
        )

    def test_ingest_factors_bias_weights_and_are_flight_stamped(self):
        # ISSUE 13: with equal measured dispatch cost, the member that can
        # FEED its chips (idle decode lanes + local SDFS blobs) earns the
        # larger dispatch-pool share — and the factors are reconstructible
        # from the flight recorder (lint O2) and advisor status.
        clock = VClock()
        prof = make_profiler(clock)
        flight = FlightRecorder(clock=clock)
        idle = {"m0": 0.0, "m1": 8.0}
        locality = {"m0": 0.0, "m1": 1.0}
        adv = PlacementAdvisor(
            prof, flight=flight, clock=clock,
            decode_idle=idle.get, blob_locality=locality.get,
        )
        feed(prof, {"m0": 0.2, "m1": 0.2})
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        # Bounded bias: full idle + full locality = 1 + 2 * ingest_bias.
        assert adv.status()["ingest_factors"] == {"m1": 1.6}
        assert plan.weights["job"]["m1"] > plan.weights["job"]["m0"]
        note = next(
            e for e in flight.events() if e["kind"] == "placement_decision"
        )
        assert "m1=1.6" in note["ingest"]

    def test_no_ingest_signals_means_pre_tier_behavior(self):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(prof, clock=clock)
        feed(prof, {"m0": 0.1, "m1": 0.4})
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        # Neither callable wired: factors empty, weights exactly the
        # measured-cost normalization (bit-for-bit pre-decode-tier).
        assert adv.status()["ingest_factors"] == {}
        assert plan.weights["job"] == {"m0": 4, "m1": 1}

    def test_unknown_ingest_readings_stay_neutral(self):
        # A member the leader has not scraped yet (None) must not read as
        # zero capacity — factors only ever help, never penalize below 1x.
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(
            prof, clock=clock,
            decode_idle=lambda m: None, blob_locality=lambda m: None,
        )
        feed(prof, {"m0": 0.1, "m1": 0.4})
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        assert adv.status()["ingest_factors"] == {}
        assert plan.weights["job"] == {"m0": 4, "m1": 1}


# ---------------------------------------------------------------------------
# SloEvaluator: burn rates and alert edges
# ---------------------------------------------------------------------------


def make_evaluator(prof, clock, **kw):
    kw.setdefault("fast_window_s", 10.0)
    kw.setdefault("slow_window_s", 40.0)
    kw.setdefault("fast_burn", 5.0)
    kw.setdefault("slow_burn", 2.0)
    obj = SloObjective("resnet18", latency_s=0.5, availability=0.9)
    return SloEvaluator(prof, {"resnet18": obj}, **kw)


class TestSloEvaluator:
    def test_objective_parsing(self):
        objs = SloObjective.from_config({
            "resnet18": {"latency_s": 0.25},
            "llm": {"latency_s": 1.0, "availability": 0.999},
        })
        assert objs["resnet18"].availability == 0.99
        assert objs["llm"].error_budget == pytest.approx(0.001)
        assert SloObjective.from_config(None) == {}

    def test_alert_fires_once_and_clears_after_recovery(self):
        clock = VClock()
        prof = make_profiler(clock)
        metrics = Counters()
        flight = FlightRecorder(clock=clock)
        fired: list[str] = []
        ev = make_evaluator(
            prof, clock, metrics=metrics, flight=flight,
            on_fast_burn=fired.append,
        )
        state = ev.evaluate()
        assert state["resnet18"]["fast"] == 0.0
        assert not state["resnet18"]["fast_alert"]  # no evidence, no alert
        # Every observation over the objective: frac 1.0 / budget 0.1 = 10x.
        for _ in range(20):
            prof.record("resnet18", "m0", "dispatch", 1.0)
        state = ev.evaluate()
        assert state["resnet18"]["fast"] == pytest.approx(10.0)
        assert state["resnet18"]["fast_alert"] and state["resnet18"]["slow_alert"]
        assert fired == ["resnet18"]
        assert metrics.get("slo_fast_burn_alerts") == 1
        kinds = [e["kind"] for e in flight.events()]
        assert "slo_fast_burn" in kinds and "slo_slow_burn" in kinds
        # Still burning: the alert is edge-triggered, nothing refires.
        ev.evaluate()
        assert fired == ["resnet18"]
        assert metrics.get("slo_fast_burn_alerts") == 1
        # Recovery: the bad windows age past every horizon, burn hits 0,
        # both alerts clear.
        clock.advance(100.0)
        for _ in range(20):
            prof.record("resnet18", "m0", "dispatch", 0.01)
        state = ev.evaluate()
        assert not state["resnet18"]["fast_alert"]
        assert not state["resnet18"]["slow_alert"]
        assert any(e["kind"] == "slo_burn_clear" for e in flight.events())

    def test_alert_holds_inside_the_hysteresis_band(self):
        clock = VClock()
        prof = make_profiler(clock)
        ev = make_evaluator(prof, clock)
        for _ in range(10):
            prof.record("resnet18", "m0", "dispatch", 1.0)
        assert ev.evaluate()["resnet18"]["fast_alert"]
        # 30% over the objective: burn 3.0 — under the 5x threshold but
        # above the clear line (0.5 x 5 = 2.5), so the alert holds.
        clock.advance(100.0)
        for _ in range(7):
            prof.record("resnet18", "m0", "dispatch", 0.01)
        for _ in range(3):
            prof.record("resnet18", "m0", "dispatch", 1.0)
        state = ev.evaluate()
        assert state["resnet18"]["fast"] == pytest.approx(3.0)
        assert state["resnet18"]["fast_alert"]

    def test_status_and_registry_gauges(self):
        class Reg:
            def __init__(self):
                self.gauges = {}

            def gauge(self, name, fn):
                self.gauges[name] = fn

        clock = VClock()
        prof = make_profiler(clock)
        reg = Reg()
        ev = make_evaluator(prof, clock, registry=reg)
        for _ in range(4):
            prof.record("resnet18", "m0", "dispatch", 1.0)
        ev.evaluate()
        assert reg.gauges["slo_fast_burn_resnet18"]() == pytest.approx(10.0)
        assert reg.gauges["slo_slow_burn_resnet18"]() == pytest.approx(10.0)
        s = ev.status()
        assert s["fast_burn_threshold"] == 5.0
        m = s["models"]["resnet18"]
        assert m["objective_latency_s"] == 0.5
        assert m["p99_s"] == pytest.approx(1.0)
        assert m["fast_alert"] is True


# ---------------------------------------------------------------------------
# Scheduler integration: plan application + replan triggers
# ---------------------------------------------------------------------------


class SpyAdvisor:
    """Records every trigger the scheduler consults it with; abstains."""

    def __init__(self):
        self.calls: list[str] = []

    def advise(self, jobs, members, chip_weight=None, trigger="periodic"):
        self.calls.append(trigger)
        return None


class TestSchedulerIntegration:
    def _scheduler(self, advisor, members, flight=None):
        net = SimRpcNetwork()
        s = JobScheduler(
            net.client("L"),
            lambda: list(members),
            jobs={"resnet18": make_workload(8)},
            timer=net.clock,
            advisor=advisor,
            flight=flight,
        )
        s.is_leading = True
        return s

    def test_request_replan_reaches_the_advisor_once(self):
        spy = SpyAdvisor()
        s = self._scheduler(spy, ["m0", "m1"])
        s._start({})
        assert spy.calls and spy.calls[0] == "periodic"
        s.request_replan("slo_fast_burn:resnet18")
        s.assign_once()
        assert spy.calls[-1] == "slo_fast_burn:resnet18"
        s.assign_once()  # the trigger was consumed, not latched
        assert spy.calls[-1] == "periodic"

    def test_membership_change_is_its_own_trigger(self):
        spy = SpyAdvisor()
        members = ["m0", "m1"]
        s = self._scheduler(spy, members)
        s._start({})
        members.remove("m1")
        s.assign_once()
        assert spy.calls[-1] == "membership"

    def test_plan_application_builds_weighted_pool_and_stamps_flight(self):
        net = SimRpcNetwork()
        flight = FlightRecorder(clock=net.clock)
        plan = PlacementPlan(
            assignment={"resnet18": ["m0", "m1"]},
            weights={"resnet18": {"m0": 2, "m1": 1}},
        )

        class Fixed:
            def advise(self, *a, **k):
                return plan

        s = JobScheduler(
            net.client("L"),
            lambda: ["m0", "m1", "m2"],
            jobs={"resnet18": make_workload(8)},
            timer=net.clock,
            advisor=Fixed(),
            flight=flight,
        )
        s.is_leading = True
        s._start({})
        job = s.jobs["resnet18"]
        assert job.assigned == ["m0", "m1"]
        assert job.dispatch_pool == ["m0", "m1", "m0"]
        assert any(e["kind"] == "placement_apply" for e in flight.events())

    def test_incomplete_plan_falls_back_to_round_robin(self):
        plan = PlacementPlan(assignment={"resnet18": ["ghost"]})

        class Fixed:
            def advise(self, *a, **k):
                return plan

        s = self._scheduler(Fixed(), ["m0", "m1"])
        s._start({})
        # The plan references a member the scheduler cannot see: the pass
        # keeps the round-robin baseline instead of stranding the job.
        assert s.jobs["resnet18"].assigned == ["m0", "m1"]


# ---------------------------------------------------------------------------
# Acceptance soak: degrade -> fast burn -> replan -> recovery, all on the
# flight recorder (ISSUE 9's closing criterion)
# ---------------------------------------------------------------------------


class PlacementFixture:
    """Six echo members on the sim fabric; the profiler, advisor, and SLO
    evaluator are wired exactly as cluster/node.py wires them, but driven
    synchronously on the fabric's virtual clock."""

    def __init__(self, seed: int, n_members=6, n_queries=40_000, shard=16):
        rng = random.Random(seed)
        self.net = SimRpcNetwork()
        self.members = [f"m{i}" for i in range(n_members)]
        self.base: dict[str, float] = {}
        for m in self.members:
            def backend(synsets, member=m):
                return [int(s[1:]) for s in synsets]

            self.net.serve(m, PredictWorker({"resnet18": backend}).methods())
            self.base[m] = 0.03 + rng.uniform(0.0, 0.01)
            self.net.set_latency("L", m, self.base[m])
        self.flight = FlightRecorder(clock=self.net.clock)
        self.metrics = Counters()
        self.profiler = CostProfiler(
            window_s=5.0, windows=8, decay=0.5, clock=self.net.clock
        )
        self.advisor = PlacementAdvisor(
            self.profiler, flight=self.flight, metrics=self.metrics,
            clock=self.net.clock, max_moves=4, window_s=10.0,
            hysteresis=0.1, exclude_factor=3.0,
        )
        self.scheduler = JobScheduler(
            self.net.client("L"),
            lambda: list(self.members),
            jobs={"resnet18": make_workload(n_queries)},
            shard_size=shard,
            shard_timeout_s=5.0,
            timer=self.net.clock,
            hedge_tail=False,
            metrics=self.metrics,
            flight=self.flight,
            profiler=self.profiler,
            advisor=self.advisor,
        )
        self.scheduler.is_leading = True
        self.evaluator = SloEvaluator(
            self.profiler,
            {"resnet18": SloObjective("resnet18", latency_s=0.1,
                                      availability=0.95)},
            fast_window_s=5.0, slow_window_s=20.0,
            fast_burn=2.0, slow_burn=1.0,
            metrics=self.metrics, flight=self.flight,
            on_fast_burn=lambda model: self.scheduler.request_replan(
                f"slo_fast_burn:{model}"
            ),
        )

    def step(self) -> dict:
        """One scheduler tick + one SLO evaluation (the leader's scrape
        cadence, collapsed to every tick for the sim)."""
        self.scheduler.assign_once()
        if self.scheduler.dispatch_all_once() == 0:
            self.net.advance(0.05)
        return self.evaluator.evaluate()

    def p99(self) -> float:
        return self.profiler.percentile(
            99, model="resnet18", stage="dispatch", horizon_s=5.0
        )


class TestPlacementSoak:
    @pytest.mark.parametrize("seed", seeds(2))
    def test_degraded_member_burns_then_placement_recovers(self, seed):
        f = PlacementFixture(seed)
        f.scheduler._start({})
        victim = random.Random(seed + 1).choice(f.members)

        # Phase 1 — healthy warmup: profiles accumulate, nothing alerts.
        while f.net.now < 10.0:
            state = f.step()
        assert not state["resnet18"]["fast_alert"]
        assert f.p99() < 0.1

        # Phase 2 — degrade one member 5x: well over the 0.1 s objective,
        # well under the shard timeout (slow-but-alive, gray's blind spot
        # with gray ejection disabled — placement must carry this alone).
        f.net.set_latency("L", victim, 5 * f.base[victim])
        alert_t = None
        for _ in range(4000):
            if f.step()["resnet18"]["fast_alert"]:
                alert_t = f.net.now
                break
        assert alert_t is not None, "degraded member never tripped fast burn"

        # Phase 3 — the advisor must exclude the victim and fleet p99 must
        # come back under the objective within three fast windows.
        deadline = alert_t + 3 * f.evaluator.fast_window_s
        recovered_t = None
        for _ in range(8000):
            f.step()
            assert not all(j.done for j in f.scheduler.jobs.values()), (
                "workload drained before recovery could be observed"
            )
            if victim in f.advisor.status()["excluded"] and f.p99() < 0.1:
                recovered_t = f.net.now
                break
        assert recovered_t is not None, "victim never excluded / p99 stuck"
        assert recovered_t <= deadline, (
            f"recovery took {recovered_t - alert_t:.1f}s "
            f"(> {deadline - alert_t:.1f}s budget)"
        )
        assert victim not in f.scheduler.jobs["resnet18"].assigned

        # Churn stayed inside the move budget.
        st = f.advisor.status()
        assert st["moves_used"] <= st["max_moves"]

        # Every decision on the path is reconstructible from the recorder:
        # the burn alert, the advisor's decision (naming the exclusion),
        # and the scheduler applying it.
        kinds = {e["kind"] for e in f.flight.events()}
        assert {"slo_fast_burn", "placement_decision", "placement_apply"} <= kinds
        assert any(
            e["kind"] == "placement_decision" and victim in e.get("excluded", "")
            for e in f.flight.events()
        )


# ---------------------------------------------------------------------------
# Memory-headroom HARD constraint (cluster/devicemon.py, ISSUE 15)
# ---------------------------------------------------------------------------


class TestHeadroomHardConstraint:
    """A member whose scraped HBM headroom (hbm_limit - hbm_in_use) cannot
    hold a model's analytic resident bytes is never dealt that model — a
    refusal inside the solver, not a cost weighting. Unknown on either side
    (unscraped member, CPU backend with no stats, unregistered model) never
    blocks: absence of telemetry must not strand a job."""

    def _advisor(self, headroom, model_bytes, **kw):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(
            prof, clock=clock, headroom=headroom, model_bytes=model_bytes, **kw
        )
        feed(prof, {"m0": 0.1, "m1": 0.1})
        return adv

    def test_refuses_member_whose_headroom_cannot_hold_the_model(self):
        clock = VClock()
        flight = FlightRecorder(clock=clock)
        metrics = Counters()
        room = {"m0": 8e9, "m1": 1e9}
        adv = self._advisor(
            room.get, lambda j: 2e9, flight=flight, metrics=metrics
        )
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        assert plan.assignment["job"] == ["m0"]
        assert adv.status()["headroom_blocked"] == {"job": ["m1"]}
        assert metrics.get("placement_headroom_blocked") == 1
        # The refusal is reconstructible from the recorder (lint O2).
        note = [e for e in flight.events() if e["kind"] == "placement_decision"][-1]
        assert note["headroom_blocked"] == "job=m1"

    def test_unknown_headroom_never_blocks(self):
        adv = self._advisor(lambda m: None, lambda j: 2e9)
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        assert sorted(plan.assignment["job"]) == ["m0", "m1"]
        assert adv.status()["headroom_blocked"] == {}

    def test_unknown_model_bytes_never_blocks(self):
        adv = self._advisor(lambda m: 1e9, lambda j: None)
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        assert sorted(plan.assignment["job"]) == ["m0", "m1"]
        assert adv.status()["headroom_blocked"] == {}

    def test_blocks_are_per_job_not_fleet_wide(self):
        # m1 is too full for the big model but fine for the small one.
        room = {"m0": 8e9, "m1": 1e9}
        sizes = {"big": 4e9, "small": 1e8}
        adv = self._advisor(room.get, sizes.get)
        plan = adv.advise({"big": 50, "small": 50}, ["m0", "m1"])
        assert plan.assignment["big"] == ["m0"]
        assert "m1" in plan.assignment["small"]
        assert adv.status()["headroom_blocked"] == {"big": ["m1"]}

    def test_job_blocked_everywhere_gets_no_members(self):
        # Dispatching it anywhere would OOM the member; an empty
        # assignment is the correct, visible answer.
        adv = self._advisor(lambda m: 1e9, {"big": 4e9, "small": 1e8}.get)
        plan = adv.advise({"big": 50, "small": 50}, ["m0", "m1"])
        assert plan.assignment["big"] == []
        assert sorted(plan.assignment["small"]) == ["m0", "m1"]
        assert adv.status()["headroom_blocked"] == {"big": ["m0", "m1"]}

    def test_callback_errors_treated_as_unknown(self):
        def boom(_):
            raise RuntimeError("scrape race")

        adv = self._advisor(boom, lambda j: 2e9)
        plan = adv.advise({"job": 100}, ["m0", "m1"])
        assert sorted(plan.assignment["job"]) == ["m0", "m1"]


# ---------------------------------------------------------------------------
# Gang-sharded placement (ISSUE 17, docs/SHARDING.md): a model that fits NO
# single member's HBM becomes a chip gang, not a refusal
# ---------------------------------------------------------------------------


class GangEchoBackend:
    """Gang-capable fake: ``predict_gang`` answers this rank's contiguous
    slice; solo dispatch of the over-HBM model is a bug, so ``__call__``
    fails loudly (the real LmBackend refuses with a typed RpcError)."""

    def __call__(self, synsets):
        raise AssertionError("over-HBM model must never be dispatched solo")

    def predict_gang(self, synsets, rank, world):
        start, stop = gang_slice(len(synsets), rank, world)
        return [int(s[1:]) for s in synsets[start:stop]]


class TestGangPlacement:
    """Over-HBM models gang instead of starving: the advisor trades replica
    count against shard width from the same cost lanes and HBM gauges the
    solo path uses."""

    def _advisor(self, headroom, model_bytes, costs=None, **kw):
        clock = VClock()
        prof = make_profiler(clock)
        adv = PlacementAdvisor(
            prof, clock=clock, headroom=headroom, model_bytes=model_bytes, **kw
        )
        feed(prof, costs or {"m0": 0.1, "m1": 0.1, "m2": 0.1, "m3": 0.1})
        return adv

    def test_over_hbm_job_gets_a_gang_not_a_refusal(self):
        clock = VClock()
        flight = FlightRecorder(clock=clock)
        metrics = Counters()
        # 25 MB model, 10 MB headroom everywhere: solo is impossible on
        # every member, but a 3-wide gang's ~8.3 MB share fits each.
        adv = self._advisor(
            lambda m: 10e6, {"lm": 25e6, "small": 1e6}.get,
            flight=flight, metrics=metrics,
        )
        plan = adv.advise({"lm": 50, "small": 50}, ["m0", "m1", "m2", "m3"])
        assert plan.gangs == {"lm": 3}
        assert len(plan.assignment["lm"]) == 3
        assert plan.weights["lm"] == {}  # gangs have no dispatch pool
        assert metrics.get("placement_gangs_formed") == 1
        # The small job still places solo; it did not inherit gang shape.
        assert plan.assignment["small"] and "small" not in plan.gangs
        assert adv.status()["gangs"] == {"lm": 3}
        # The decision is reconstructible from the recorder (lint O2).
        note = [
            e for e in flight.events() if e["kind"] == "placement_decision"
        ][-1]
        assert note["gangs"].startswith("lm:3=")

    def test_gang_width_is_minimal_feasible(self):
        # 40 MB over 25 MB headroom: a 2-wide share (20 MB) already fits,
        # so the advisor must NOT burn a third chip on this job.
        adv = self._advisor(lambda m: 25e6, {"lm": 40e6}.get)
        plan = adv.advise({"lm": 10}, ["m0", "m1", "m2", "m3"])
        assert plan.gangs == {"lm": 2}

    def test_gang_members_follow_cost_lane_capacity(self):
        # m0's dispatch lane runs 2x the fleet cost (still under the
        # exclusion line): the 3-wide gang must land on the three members
        # whose lanes can actually feed it.
        adv = self._advisor(
            lambda m: 10e6, {"lm": 25e6}.get,
            costs={"m0": 0.2, "m1": 0.1, "m2": 0.1, "m3": 0.1},
        )
        plan = adv.advise({"lm": 10}, ["m0", "m1", "m2", "m3"])
        assert plan.gangs["lm"] == 3
        assert "m0" not in plan.assignment["lm"]

    def test_gang_members_follow_chip_weights(self):
        # Equal costs, but m3 advertises 4 chips: capacity = chips/cost
        # puts it first in the gang.
        adv = self._advisor(lambda m: 13e6, {"lm": 25e6}.get)
        plan = adv.advise(
            {"lm": 10}, ["m0", "m1", "m2", "m3"],
            chip_weight={"m0": 1, "m1": 1, "m2": 1, "m3": 4},
        )
        assert plan.gangs["lm"] == 2
        assert "m3" in plan.assignment["lm"]

    def test_truly_unplaceable_job_still_gets_no_members(self):
        # Even the widest gang cannot shard 100 MB into 10 MB headrooms
        # across two members: empty assignment remains the honest answer.
        adv = self._advisor(lambda m: 10e6, {"lm": 100e6}.get)
        plan = adv.advise({"lm": 10}, ["m0", "m1"])
        assert plan.assignment["lm"] == []
        assert plan.gangs == {}


class GangFixture:
    """Four gang-capable members on the sim fabric with headroom gauges too
    small for the model solo — wired like cluster/node.py wires the leader,
    driven on the virtual clock."""

    def __init__(self, n_members: int = 4, n_queries: int = 64, shard: int = 8):
        self.net = SimRpcNetwork()
        self.members = [f"m{i}" for i in range(n_members)]
        for m in self.members:
            self.net.serve(
                m, PredictWorker({"lm": GangEchoBackend()}).methods()
            )
        self.flight = FlightRecorder(clock=self.net.clock)
        self.metrics = Counters()
        self.profiler = CostProfiler(
            window_s=5.0, windows=8, decay=0.5, clock=self.net.clock
        )
        self.advisor = PlacementAdvisor(
            self.profiler, flight=self.flight, metrics=self.metrics,
            clock=self.net.clock,
            headroom=lambda m: 10e6, model_bytes={"lm": 25e6}.get,
        )
        feed(self.profiler, {m: 0.1 for m in self.members}, model="lm")
        self.scheduler = JobScheduler(
            self.net.client("L"),
            lambda: list(self.members),
            jobs={"lm": [(f"p{i}", i) for i in range(n_queries)]},
            shard_size=shard,
            shard_timeout_s=5.0,
            timer=self.net.clock,
            hedge_tail=False,
            metrics=self.metrics,
            flight=self.flight,
            profiler=self.profiler,
            advisor=self.advisor,
        )
        self.scheduler.is_leading = True

    def step(self) -> None:
        self.scheduler.assign_once()
        if self.scheduler.dispatch_all_once() == 0:
            self.net.advance(0.05)

    def run_until(self, pred, budget_s: float = 60.0) -> bool:
        deadline = self.net.now + budget_s
        while self.net.now < deadline:
            self.step()
            if pred():
                return True
        return False


class TestGangDispatch:
    def test_over_hbm_model_serves_through_the_gang_path(self):
        f = GangFixture()
        f.scheduler._start({})
        job = f.scheduler.jobs["lm"]
        assert job.gang_world == 3
        assert f.run_until(lambda: job.done), job.report()
        assert job.accuracy == 1.0
        assert job.gang_shards == 8  # 64 queries / shard 8, all collective
        # Solo predict never fired: every dispatch was the gang verb.
        assert all(m != "job.predict" for _, m in f.net.calls)

    @pytest.mark.parametrize("seed", seeds(3))
    def test_gang_member_death_tears_down_and_replans(self, seed):
        f = GangFixture()
        f.scheduler._start({})
        job = f.scheduler.jobs["lm"]
        gang = list(job.assigned)
        assert job.gang_world == 3 and len(gang) == 3

        # Phase 1 — healthy gang serves a few collective shards.
        assert f.run_until(lambda: job.gang_shards >= 2), job.report()

        # Phase 2 — kill one member MID-STREAM (chaos-seeded choice). The
        # in-flight shard fails with the typed unreachable error, the whole
        # gang is released (all-or-nothing), and a replan is forced.
        victim = random.Random(seed).choice(gang)
        f.net.crash(victim)
        assert f.run_until(
            lambda: any(
                e["kind"] == "gang_teardown" for e in f.flight.events()
            ),
            budget_s=30.0,
        ), "gang teardown never recorded"
        tear = [e for e in f.flight.events() if e["kind"] == "gang_teardown"][0]
        assert tear["job"] == "lm" and tear["world"] == 3
        assert set(tear["released"].split(",")) == set(gang)
        assert "unreachable" in tear["why"].lower()

        # Phase 3 — failure detection removes the member; the advisor
        # re-forms the gang from survivors and the stream drains with no
        # hung dispatches and full accuracy.
        f.members.remove(victim)
        assert f.run_until(lambda: job.done, budget_s=120.0), job.report()
        assert job.accuracy == 1.0
        assert victim not in job.assigned
        assert job.gang_world == 3 and len(job.assigned) == 3
        assert not job.outstanding, "hung gang dispatches left behind"
        # The replan is attributable: teardown forced its own trigger.
        assert any(
            e["kind"] == "placement_decision"
            and e.get("trigger", "").startswith(("gang_member_lost", "membership"))
            for e in f.flight.events()
        )
