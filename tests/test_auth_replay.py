"""Replay protection on both control-plane fabrics.

VERDICT r3 weak #6: with HMAC-only sealing, a recorded ``sdfs.delete`` frame
replayed while the key was unchanged would re-execute. Frames now carry a
per-sender monotonic sequence inside the MAC'd envelope (cluster/auth.py);
these tests pin the unit semantics and the end-to-end drop on TCP and UDP.
"""

import socket
import struct
import time

import msgpack
import pytest

from dmlc_tpu.cluster.auth import AuthError, FrameAuth
from dmlc_tpu.cluster.rpc import RpcError, RpcUnreachable, TcpRpc, TcpRpcServer
from dmlc_tpu.cluster.transport import UdpTransport


class TestFrameAuthReplay:
    def test_roundtrip_and_replay_rejected(self):
        a, b = FrameAuth("k", sender="a"), FrameAuth("k", sender="b")
        frame = a.seal(b"payload", recipient="b")
        assert b.open(frame) == (b"payload", b"a")
        with pytest.raises(AuthError, match="replay"):
            b.open(frame)

    def test_sequences_strictly_increase_per_sender(self):
        a, b = FrameAuth("k", sender="a"), FrameAuth("k", sender="b")
        frames = [a.seal(f"m{i}".encode(), recipient="b") for i in range(50)]
        for i, f in enumerate(frames):
            assert b.open(f)[0] == f"m{i}".encode()
        # Every already-delivered frame is a replay, wherever it sits.
        for f in (frames[0], frames[25], frames[-1]):
            with pytest.raises(AuthError, match="replay"):
                b.open(f)

    def test_out_of_order_within_window_accepted(self):
        # UDP reordering: an older-but-fresh datagram still lands once.
        a, b = FrameAuth("k", sender="a"), FrameAuth("k", sender="b")
        f1, f2 = a.seal(b"one", recipient="b"), a.seal(b"two", recipient="b")
        assert b.open(f2)[0] == b"two"
        assert b.open(f1)[0] == b"one"
        with pytest.raises(AuthError, match="replay"):
            b.open(f1)

    def test_below_window_rejected(self):
        a = FrameAuth("k", sender="a")
        b = FrameAuth("k", sender="b", window_s=0.05)
        old = a.seal(b"old", recipient="b")
        time.sleep(0.1)
        assert b.open(a.seal(b"fresh", recipient="b"))[0] == b"fresh"
        with pytest.raises(AuthError, match="below replay window"):
            b.open(old)

    def test_stale_frame_from_unknown_sender_rejected(self):
        # A recorded frame replayed against a RESTARTED receiver (no state
        # for the sender) is rejected once it is older than max_age_s.
        a = FrameAuth("k", sender="a")
        old = a.seal(b"recorded", recipient="b")
        restarted = FrameAuth("k", sender="b", max_age_s=0.05)
        time.sleep(0.1)
        with pytest.raises(AuthError, match="stale frame from unknown sender"):
            restarted.open(old)

    def test_tampered_and_truncated_frames_rejected(self):
        a, b = FrameAuth("k", sender="a"), FrameAuth("k", sender="b")
        frame = bytearray(a.seal(b"payload", recipient="b"))
        frame[-1] ^= 0xFF
        with pytest.raises(AuthError, match="bad frame tag"):
            b.open(bytes(frame))
        with pytest.raises(AuthError, match="shorter than the envelope"):
            b.open(b"short")

    def test_cross_recipient_replay_rejected(self):
        # ADVICE r4 medium: a frame recorded in flight to member B must not
        # open at member C — even fresh, even on its first delivery.
        a = FrameAuth("k", sender="a")
        b = FrameAuth("k", sender="b")
        c = FrameAuth("k", sender="c")
        frame = a.seal(b"sdfs.delete", recipient="b")
        with pytest.raises(AuthError, match="different recipient"):
            c.open(frame)
        assert b.open(frame)[0] == b"sdfs.delete"  # intended target still works
        # Registered server identities are honored alongside the sender id.
        c.add_identity("10.0.0.3:9001")
        assert c.open(a.seal(b"req", recipient="10.0.0.3:9001"))[0] == b"req"

    def test_sender_state_bounded(self):
        from dmlc_tpu.cluster import auth as auth_mod

        b = FrameAuth("k", sender="rx")
        for i in range(auth_mod._MAX_SENDERS + 10):
            b.open(FrameAuth("k", sender=f"s{i}").seal(b"x", recipient="rx"))
        assert len(b._peers) <= auth_mod._MAX_SENDERS


def _raw_send_tcp(address: str, frame: bytes) -> bytes:
    """Attacker's replay: ship recorded sealed bytes down a new connection;
    returns whatever reply bytes arrive (empty = connection dropped)."""
    host, _, port = address.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=2.0) as s:
        s.sendall(struct.pack("!I", len(frame)) + frame)
        s.settimeout(2.0)
        try:
            return s.recv(4096)
        except (socket.timeout, OSError):
            return b""


class TestTcpReplay:
    def test_recorded_delete_frame_dropped(self):
        """The VERDICT scenario: a recorded sdfs.delete request replayed on
        a fresh connection must not re-execute the method."""
        deleted = []
        methods = {"sdfs.delete": lambda p: (deleted.append(p["name"]), {"ok": True})[1]}
        server = TcpRpcServer(
            "127.0.0.1", 0, methods, auth=FrameAuth("fleet", sender="leader")
        )
        try:
            client_auth = FrameAuth("fleet", sender="cli")
            # The legitimate call, captured on the wire by the attacker.
            recorded = client_auth.seal(
                msgpack.packb({"m": "sdfs.delete", "p": {"name": "f1"}}, use_bin_type=True),
                recipient=server.address,
            )
            reply = _raw_send_tcp(server.address, recorded)
            assert deleted == ["f1"] and reply  # legit call executed
            # Replay: same bytes, new connection -> dropped without reply.
            reply = _raw_send_tcp(server.address, recorded)
            assert reply == b""
            assert deleted == ["f1"], "replayed delete re-executed"
            # The server still serves fresh keyed traffic afterwards.
            rpc = TcpRpc(auth=client_auth)
            assert rpc.call(server.address, "sdfs.delete", {"name": "f2"}) == {"ok": True}
            assert deleted == ["f1", "f2"]
        finally:
            server.close()

    def test_recorded_frame_dropped_at_other_member(self):
        """ADVICE r4 medium, end to end: a request recorded in flight to
        member A replayed at member B (same fleet key, independent replay
        window for the sender) must not execute at B."""
        calls = {"a": [], "b": []}
        server_a = TcpRpcServer(
            "127.0.0.1", 0, {"sdfs.delete": lambda p: (calls["a"].append(p["name"]), {})[1]},
            auth=FrameAuth("fleet", sender="member-a"),
        )
        server_b = TcpRpcServer(
            "127.0.0.1", 0, {"sdfs.delete": lambda p: (calls["b"].append(p["name"]), {})[1]},
            auth=FrameAuth("fleet", sender="member-b"),
        )
        try:
            recorded = FrameAuth("fleet", sender="cli").seal(
                msgpack.packb({"m": "sdfs.delete", "p": {"name": "f1"}}, use_bin_type=True),
                recipient=server_a.address,
            )
            assert _raw_send_tcp(server_a.address, recorded)  # legit target runs it
            assert _raw_send_tcp(server_b.address, recorded) == b""
            assert calls == {"a": ["f1"], "b": []}, "frame executed at the wrong member"
        finally:
            server_a.close()
            server_b.close()

    def test_normal_repeated_calls_unaffected(self):
        server = TcpRpcServer(
            "127.0.0.1", 0, {"echo": lambda p: {"echo": p}},
            auth=FrameAuth("fleet", sender="srv"),
        )
        try:
            rpc = TcpRpc(auth=FrameAuth("fleet", sender="cli"))
            for i in range(20):
                assert rpc.call(server.address, "echo", {"i": i}) == {"echo": {"i": i}}
        finally:
            server.close()


def test_udp_replayed_datagram_dropped():
    """Same property on the gossip fabric: identical sealed bytes sent twice
    land exactly once and bump the rejected counter."""
    rx = UdpTransport("127.0.0.1", 0, auth=FrameAuth("fleet", sender="rx"))
    got = []
    rx.set_handler(lambda src, msg: got.append(msg))
    try:
        sender_auth = FrameAuth("fleet", sender="tx")
        datagram = sender_auth.seal(
            msgpack.packb({"t": "failed-claim"}, use_bin_type=True),
            recipient=rx.address,
        )
        host, _, port = rx.address.rpartition(":")
        raw = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            raw.sendto(datagram, (host, int(port)))
            raw.sendto(datagram, (host, int(port)))  # replay
            deadline = time.time() + 5
            while not got and time.time() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)  # window for the replay to (wrongly) land
        finally:
            raw.close()
        assert [m["t"] for m in got] == ["failed-claim"]
        assert rx.rejected == 1
    finally:
        rx.close()


class TestReplayWindowProperties:
    """Hypothesis invariants for the replay window: under ANY delivery
    order of a sealed frame sequence (UDP reordering), each frame is
    accepted exactly once and every re-delivery is rejected."""

    def test_any_order_each_frame_accepted_exactly_once(self):
        from hypothesis import given, settings
        from hypothesis import strategies as st

        @settings(max_examples=100, deadline=None)
        @given(st.permutations(list(range(16)) * 2))
        def check(schedule):
            # The drawn permutation IS the delivery order: each frame
            # appears twice (a delivery and a duplicate), interleaved
            # however Hypothesis explores.
            tx = FrameAuth("k", sender="tx")
            rx = FrameAuth("k", sender="rx")
            frames = [tx.seal(f"m{i}".encode(), recipient="rx") for i in range(16)]
            accepted = []
            seen = set()
            for i in schedule:
                try:
                    payload, _ = rx.open(frames[i])
                    assert payload == f"m{i}".encode()
                    assert i not in seen, f"frame {i} accepted twice"
                    seen.add(i)
                    accepted.append(i)
                except AuthError:
                    assert i in seen, f"frame {i} rejected before first delivery"
            assert seen == set(range(16)), "some frame was never accepted"

        check()
