"""Critical-path attribution + drift sentinel (docs/OBSERVABILITY.md §9).

Pins the ISSUE-20 acceptance math:

- a hand-built DAG with overlapped children charges only the max-lane
  chain — stage shares sum to ~1.0 of wall time, never more;
- gang fan-out charges the slowest rank;
- the backwards-walk attribution matches a brute-force longest-path
  reference (elementary intervals x latest-ending-active-child) on
  randomized seeded DAGs;
- orphan subtrees degrade gracefully (charged under a virtual root,
  never crashing or double-counting);
- the analyzer charges each trace once fleet-wide (root ownership), and
  the fleet fold + sentinel name a drifting member within
  ``confirm_windows`` ticks across chaos seeds 0/1000/2000.
"""

from __future__ import annotations

import math
import random

import pytest

from dmlc_tpu.cluster.critpath import (
    GAP_STAGE,
    CritPathAnalyzer,
    FleetCritPath,
    Span,
    breakdown,
    critical_path,
    spans_from_perfetto,
    spans_from_wire,
    stage_of,
)
from dmlc_tpu.cluster.sentinel import DriftSentinel


def mk(name, start, end, span_id, parent=None, trace="t1", lane=None,
       model=None):
    return Span(name=name, start=float(start), end=float(end),
                span_id=span_id, parent_id=parent, trace_id=trace,
                lane=lane, model=model)


def charged_by_span(path):
    out: dict[str, float] = {}
    for span, sec in path.charges:
        out[span.span_id] = out.get(span.span_id, 0.0) + sec
    return out


# ---------------------------------------------------------------------------
# Extraction math
# ---------------------------------------------------------------------------


class TestCriticalPath:
    def test_overlapped_children_charge_max_lane_only(self):
        # root [0,10]; A [1,6] and B [2,9] overlap: B (later-ending)
        # shadows A on [2,6]; A is charged only its uncovered head [1,2].
        spans = [
            mk("rpc/job.predict", 0, 10, "r", model="m"),
            mk("scheduler/dispatch", 1, 6, "a", parent="r", lane="n1"),
            mk("scheduler/dispatch", 2, 9, "b", parent="r", lane="n2"),
        ]
        path = critical_path(spans)
        got = charged_by_span(path)
        assert got == pytest.approx({"r": 1 + 1, "a": 1, "b": 7})
        assert path.total_s == pytest.approx(10.0)  # exact wall partition
        shares = sum(got.values()) / 10.0
        assert shares == pytest.approx(1.0)

    def test_gang_fanout_charges_slowest_rank(self):
        # Four gang ranks fan out at t=1; the slowest ([1,9]) is the
        # blocking chain — the three faster ranks finish in its shadow
        # and charge nothing.
        spans = [mk("rpc/job.predict", 0, 10, "r", model="m")]
        ends = [4, 5, 9, 3]
        for i, e in enumerate(ends):
            spans.append(mk("rpc/job.decode_gang", 1, e, f"g{i}",
                            parent="r", lane=f"rank{i}"))
        path = critical_path(spans)
        got = charged_by_span(path)
        assert got["g2"] == pytest.approx(8.0)  # slowest rank [1,9]
        assert all(f"g{i}" not in got for i in (0, 1, 3))
        assert got["r"] == pytest.approx(2.0)  # [0,1] + [9,10]
        assert path.total_s == pytest.approx(10.0)

    def test_nested_pipeline_charges_blocking_chain(self):
        # dispatch [1,5] with decode child [2,4]; compute [4,9] pipelined
        # after: each inner span charges only its unshadowed self-time.
        spans = [
            mk("rpc/job.predict", 0, 10, "r", model="m"),
            mk("scheduler/dispatch", 1, 5, "d", parent="r", lane="n1"),
            mk("host/decode", 2, 4, "dec", parent="d", lane="n1"),
            mk("device/forward", 4, 9, "fwd", parent="r", lane="n1"),
        ]
        got = charged_by_span(critical_path(spans))
        # forward (ends later) claims [4,9]; dispatch keeps [1,4], inside
        # which decode claims [2,4] and dispatch self-time [1,2]; the
        # root's own gaps are [0,1] and [9,10]. Wall partitions exactly.
        assert got == pytest.approx({"r": 2, "fwd": 5, "d": 1, "dec": 2})
        assert sum(got.values()) == pytest.approx(10.0)

    def test_child_overhanging_parent_is_clamped(self):
        # A child recorded past its parent's end (clock skew / late flush)
        # must not push shares past 1.0.
        spans = [
            mk("rpc/job.predict", 0, 10, "r", model="m"),
            mk("host/decode", 8, 14, "c", parent="r", lane="n1"),
        ]
        path = critical_path(spans)
        got = charged_by_span(path)
        assert got == pytest.approx({"r": 8, "c": 2})
        assert path.total_s == pytest.approx(10.0)

    def test_multiple_roots_hull_and_gap(self):
        # Two parentless spans: hull [0,10], uncovered middle [4,6] is
        # virtual-root gap time.
        spans = [
            mk("a", 0, 4, "a", lane="n1", model="m"),
            mk("b", 6, 10, "b", lane="n2"),
        ]
        path = critical_path(spans)
        got = charged_by_span(path)
        assert got["a"] == pytest.approx(4.0)
        assert got["b"] == pytest.approx(4.0)
        gap = [sec for s, sec in path.charges if s.name == GAP_STAGE]
        assert sum(gap) == pytest.approx(2.0)
        assert path.total_s == pytest.approx(10.0)

    def test_orphans_charge_under_virtual_root_without_double_count(self):
        # An orphan subtree (parent id never arrived) rides next to the
        # true root: overlap with the covered chain stays shadowed, only
        # the orphan's overhang is charged — shares never exceed 1.0.
        spans = [
            mk("rpc/job.predict", 0, 8, "r", model="m", lane="n1"),
            mk("scheduler/dispatch", 1, 7, "d", parent="r", lane="n1"),
            # orphan: parent "ghost" was dropped by the sampling budget
            mk("host/decode", 2, 9, "o", parent="ghost", lane="n2"),
            mk("gen/step", 3, 5, "os", parent="o", lane="n2"),
        ]
        path = critical_path(spans)
        assert path.orphans == 1
        got = charged_by_span(path)
        # Hull [0,9]: orphan "o" ends last -> claims [2,9] minus its own
        # child's chain; true chain covers [0,2].
        assert path.total_s == pytest.approx(9.0)
        assert sum(got.values()) == pytest.approx(9.0)
        assert got["o"] == pytest.approx((3 - 2) + (9 - 5))
        assert got["os"] == pytest.approx(2.0)

    def test_cycle_guard_terminates(self):
        # A pure 2-cycle has no top-level span: dropped as malformed, not
        # an infinite walk.
        cycle = [
            mk("x", 0, 5, "a", parent="b", model="m"),
            mk("y", 1, 4, "b", parent="a"),
        ]
        assert critical_path(cycle) is None
        # A cycle island next to a real root never hangs the walk either;
        # the rooted chain is charged normally.
        path = critical_path(
            [mk("rpc/job.predict", 0, 10, "r", model="m"), *cycle])
        assert path is not None
        assert path.total_s == pytest.approx(10.0)
        assert sum(charged_by_span(path).values()) == pytest.approx(10.0)

    def test_self_parent_treated_as_root(self):
        path = critical_path([mk("x", 0, 5, "a", parent="a", model="m")])
        assert path.total_s == pytest.approx(5.0)

    def test_empty_and_zero_width(self):
        assert critical_path([]) is None
        assert critical_path([mk("x", 3, 3, "a")]) is None

    def test_model_inheritance_nearest_ancestor(self):
        spans = [
            mk("rpc/job.predict", 0, 10, "r", model="mA"),
            mk("scheduler/dispatch", 1, 9, "d", parent="r"),
            mk("host/decode", 2, 8, "c", parent="d", model="mB"),
            mk("gen/step", 3, 7, "g", parent="c"),
        ]
        path = critical_path(spans)
        assert path.model == "mA"
        by_id = {s.span_id: s.model for s, _ in path.charges}
        assert by_id["d"] == "mA"
        assert by_id["g"] == "mB"


# ---------------------------------------------------------------------------
# Brute-force reference on randomized seeded DAGs
# ---------------------------------------------------------------------------


def _reference_charges(spans: list[Span]) -> dict[str, float]:
    """Forward characterization of the blocking critical path: at each
    instant the charged span is found by descending from the root,
    repeatedly stepping into the latest-ending child active then (ties:
    larger start, then span id). Exact via elementary intervals."""
    by_id = {s.span_id: s for s in spans}
    children: dict[str, list[Span]] = {}
    tops: list[Span] = []
    for s in spans:
        if s.parent_id and s.parent_id != s.span_id and s.parent_id in by_id:
            children.setdefault(s.parent_id, []).append(s)
        else:
            tops.append(s)
    if len(tops) == 1:
        root = tops[0]
    else:
        root = Span(name=GAP_STAGE, start=min(s.start for s in tops),
                    end=max(s.end for s in tops), span_id="(vroot)",
                    parent_id=None, trace_id="t", lane=None, model=None)
        children["(vroot)"] = tops
    points = sorted({p for s in [root, *spans]
                     for p in (s.start, s.end)
                     if root.start <= p <= root.end} | {root.start, root.end})
    out: dict[str, float] = {}
    for lo, hi in zip(points, points[1:]):
        if hi <= lo:
            continue
        u = (lo + hi) / 2.0
        cur = root
        while True:
            active = [c for c in children.get(cur.span_id, ())
                      if c.start <= u < c.end]
            if not active:
                break
            cur = max(active, key=lambda c: (c.end, c.start, c.span_id))
        out[cur.span_id] = out.get(cur.span_id, 0.0) + (hi - lo)
    return out


def _random_tree(rng: random.Random) -> list[Span]:
    spans: list[Span] = []
    counter = [0]

    def grow(parent_id, lo, hi, depth):
        n = rng.randint(0, 3 if depth < 3 else 0)
        for _ in range(n):
            counter[0] += 1
            sid = f"s{counter[0]}"
            a = rng.uniform(lo - 0.5, hi)
            b = a + rng.uniform(0.0, (hi - lo) * rng.uniform(0.2, 1.2))
            if b <= a:
                continue
            spans.append(Span(
                name=rng.choice(["scheduler/dispatch", "host/decode",
                                 "device/forward", "gen/step"]),
                start=round(a, 3), end=round(b, 3), span_id=sid,
                parent_id=parent_id, trace_id="t",
                lane=rng.choice(["n1", "n2", "n3", None]), model=None))
            grow(sid, a, b, depth + 1)

    root = Span(name="rpc/job.predict", start=0.0,
                end=round(rng.uniform(5.0, 20.0), 3), span_id="root",
                parent_id=None, trace_id="t", lane="n1", model="m")
    spans.append(root)
    grow("root", root.start, root.end, 0)
    return spans


@pytest.mark.parametrize("seed", [0, 1000, 2000, 7, 42, 1337])
def test_matches_bruteforce_reference_on_random_dags(seed):
    rng = random.Random(seed)
    for _ in range(25):
        spans = _random_tree(rng)
        path = critical_path(spans)
        ref = _reference_charges(spans)
        got = charged_by_span(path)
        root = spans[0]
        assert path.total_s == pytest.approx(root.end - root.start, abs=1e-9)
        assert sum(got.values()) <= path.total_s + 1e-9  # never > wall
        for sid in set(ref) | set(got):
            assert got.get(sid, 0.0) == pytest.approx(
                ref.get(sid, 0.0), abs=1e-9), (seed, sid, spans)


# ---------------------------------------------------------------------------
# Normalization + one-shot breakdown
# ---------------------------------------------------------------------------


class TestNormalize:
    def test_wire_roundtrip_and_breakdown_shares(self):
        events = [
            {"name": "rpc/job.predict", "start": 0.0, "dur": 10.0,
             "trace": "t1", "span": "r", "parent": None, "lane": "n1",
             "attrs": {"model": "m"}},
            {"name": "scheduler/dispatch", "start": 1.0, "dur": 6.0,
             "trace": "t1", "span": "d", "parent": "r", "lane": "n1",
             "attrs": {"job": "m"}},
            {"name": "host/decode", "start": 2.0, "dur": 4.0,
             "trace": "t1", "span": "c", "parent": "d", "lane": "n2",
             "attrs": {}},
            {"name": "junk-no-ids", "start": 0.0, "dur": 1.0},
        ]
        traces = spans_from_wire(events)
        assert set(traces) == {"t1"}
        bd = breakdown(traces)
        body = bd["m"]
        assert body["requests"] == 1
        assert body["max_lanes"] == 2
        assert sum(ln["share"] for ln in body["lanes"]) == pytest.approx(1.0)
        assert body["total_s"] == pytest.approx(10.0)
        stages = {ln["stage"] for ln in body["lanes"]}
        assert stage_of("host/decode") in stages
        assert stage_of("scheduler/dispatch") in stages

    def test_perfetto_units_are_microseconds(self):
        doc = {"traceEvents": [
            {"ph": "X", "name": "rpc/job.predict", "ts": 0, "dur": 2_000_000,
             "args": {"trace": "t", "span": "r", "model": "m"}},
            {"ph": "M", "name": "meta"},
        ]}
        traces = spans_from_perfetto(doc)
        (span,) = traces["t"]
        assert span.end == pytest.approx(2.0)
        assert span.model == "m"


# ---------------------------------------------------------------------------
# Rolling analyzer: ownership, windows, snapshot
# ---------------------------------------------------------------------------


def _request_events(trace, model, root_lane="n1", t0=0.0, decode_s=2.0,
                    dispatch_member="n2"):
    """A plausible predict request: root -> dispatch -> decode."""
    total = 1.0 + decode_s + 1.0
    return [
        {"name": "host/decode", "start": t0 + 1.5, "dur": decode_s,
         "trace": trace, "span": f"{trace}.c", "parent": f"{trace}.d",
         "lane": dispatch_member, "attrs": {}},
        {"name": "scheduler/dispatch", "start": t0 + 1.0,
         "dur": decode_s + 1.0, "trace": trace, "span": f"{trace}.d",
         "parent": f"{trace}.r", "lane": root_lane,
         "attrs": {"job": model, "member": dispatch_member}},
        {"name": "rpc/job.predict", "start": t0, "dur": total,
         "trace": trace, "span": f"{trace}.r", "parent": None,
         "lane": root_lane, "attrs": {"model": model}},
    ]


class TestAnalyzer:
    def test_charges_once_and_shares_sum_to_one(self):
        clk = [100.0]
        an = CritPathAnalyzer(window_s=10.0, clock=lambda: clk[0])
        for i in range(5):
            an.ingest(_request_events(f"t{i}", "m"))
        snap = an.snapshot()
        body = snap["models"]["m"]
        assert body["requests"] == 5
        assert sum(ln["share"] for ln in body["lanes"]) == pytest.approx(1.0)
        assert snap["counters"]["traces"] == 5
        # Late spans for an already-charged trace are counted, not folded.
        an.ingest(_request_events("t0", "m"))
        snap2 = an.snapshot()
        assert snap2["models"]["m"]["requests"] == 5
        assert snap2["counters"]["late_spans"] == 3

    def test_root_ownership_partition(self):
        clk = [0.0]
        events = _request_events("tx", "m", root_lane="leader")
        owner = CritPathAnalyzer(clock=lambda: clk[0])
        other = CritPathAnalyzer(clock=lambda: clk[0])
        assert owner.ingest(events, own_lane="leader") == 1
        assert other.ingest(events, own_lane="member2") == 0
        # Unlaned roots are claimed only by the claimer (the leader).
        unlaned = _request_events("ty", "m", root_lane=None)
        assert other.ingest(unlaned, own_lane="member2") == 0
        assert owner.ingest(unlaned, own_lane="leader",
                            claim_unlaned=True) == 1

    def test_unrooted_trace_never_charged_and_bounded(self):
        clk = [0.0]
        an = CritPathAnalyzer(clock=lambda: clk[0])
        an.MAX_PENDING = 4
        for i in range(8):  # orphan-only fragments of remote traces
            an.ingest([{"name": "host/decode", "start": 1.0, "dur": 1.0,
                        "trace": f"frag{i}", "span": f"f{i}",
                        "parent": "remote-root", "lane": "n1",
                        "attrs": {}}], own_lane="n1")
        snap = an.snapshot()
        assert snap["models"] == {}
        assert snap["counters"]["unrooted_evicted"] >= 4

    def test_windows_decay_out(self):
        clk = [0.0]
        an = CritPathAnalyzer(window_s=10.0, windows=4,
                              clock=lambda: clk[0])
        an.ingest(_request_events("t1", "m"))
        assert "m" in an.snapshot()["models"]
        clk[0] += 10.0 * 5  # beyond the window horizon
        assert an.snapshot()["models"] == {}

    def test_snapshot_is_jsonable(self):
        import json
        an = CritPathAnalyzer(clock=lambda: 0.0)
        an.ingest(_request_events("t1", "m"))
        json.dumps(an.snapshot())


class TestFleetFold:
    def test_fold_and_culprit(self):
        clk = [0.0]
        fleet = FleetCritPath()
        for member, decode_s in (("n1", 0.5), ("n2", 6.0)):
            an = CritPathAnalyzer(clock=lambda: clk[0])
            for i in range(4):
                an.ingest(_request_events(
                    f"{member}.t{i}", "m", root_lane=member,
                    dispatch_member=member, decode_s=decode_s))
            fleet.fold(member, an.snapshot())
        table = fleet.table()
        assert table["members_reporting"] == 2
        body = table["models"]["m"]
        assert body["requests"] == 8
        assert sum(ln["share"] for ln in body["lanes"]) == pytest.approx(1.0)
        culprit = fleet.culprit("m")
        assert culprit is not None
        assert culprit["stage"] == "decode"
        assert culprit["member"] == "n2"
        assert 0.0 < culprit["critpath_share"] <= 1.0
        assert fleet.culprit("missing") is None
        fleet.forget("n2")
        assert fleet.table()["members_reporting"] == 1


# ---------------------------------------------------------------------------
# Drift sentinel
# ---------------------------------------------------------------------------


def _table(q_samples: dict[tuple[str, str, str], list[float]]):
    models: dict = {}
    for (model, stage, member), samples in q_samples.items():
        body = models.setdefault(model, {"requests": 0, "total_s": 0.0,
                                         "lanes": []})
        body["lanes"].append({
            "stage": stage, "member": member,
            "crit_s": sum(samples), "share": 0.5, "n": len(samples),
            "recent_n": len(samples), "samples": list(samples),
            "p50": 0.0, "p99": 0.0,
        })
    return {"models": models}


class TestSentinel:
    def _mk(self, **kw):
        events: list[tuple[str, dict]] = []
        forces: list[float] = []
        replans: list[str] = []
        s = DriftSentinel(
            min_samples=5, confirm_windows=3, drift_factor=2.0,
            clear_factor=1.3,
            flight_note=lambda kind, **f: events.append((kind, f)),
            force_sample=forces.append,
            request_replan=replans.append, **kw)
        return s, events, forces, replans

    @pytest.mark.parametrize("seed", [0, 1000, 2000])
    def test_drift_alert_within_confirm_windows(self, seed):
        rng = random.Random(seed)
        s, events, forces, replans = self._mk()
        key = ("m", "decode", "n2")
        healthy = lambda: [rng.uniform(0.9, 1.1) for _ in range(10)]
        for _ in range(6):  # learn the baseline
            s.tick(_table({key: healthy()}))
        assert s.alerting() == []
        slow = lambda: [rng.uniform(4.5, 5.5) for _ in range(10)]  # 5x
        ticks_to_alert = 0
        for i in range(5):
            fired = s.tick(_table({key: slow()}))
            if fired:
                ticks_to_alert = i + 1
                break
        assert ticks_to_alert == 3  # exactly confirm_windows
        assert s.alerting() == [key]
        (desc,) = [f for k, f in events if k == "latency_drift"]
        assert (desc["model"], desc["stage"], desc["member"]) == key
        assert desc["factor"] > 2.0
        assert forces == [s.force_sample_s]
        assert any(k == "drift_force_sample" for k, _ in events)
        # Localized to one member -> replan requested.
        assert replans == ["latency_drift:m:decode:n2"]
        assert any(k == "drift_replan_request" for k, _ in events)

    def test_min_samples_floor(self):
        s, events, *_ = self._mk()
        key = ("m", "decode", "n2")
        for _ in range(4):
            s.tick(_table({key: [1.0, 1.0, 1.0]}))  # n=3 < 5: never judged
        for _ in range(6):
            s.tick(_table({key: [100.0] * 3}))
        assert s.alerting() == []
        assert events == []

    def test_baseline_frozen_during_drift_and_hysteresis_clear(self):
        s, events, _, _ = self._mk()
        key = ("m", "decode", "n2")
        for _ in range(4):
            s.tick(_table({key: [1.0] * 8}))
        base = s.status()["lanes"][0]["baseline_s"]
        for _ in range(3):
            s.tick(_table({key: [5.0] * 8}))
        st = s.status()["lanes"][0]
        assert st["alert"] is True
        assert st["baseline_s"] == pytest.approx(base)  # frozen, no launder
        # One healthy tick does not clear (hysteresis)...
        s.tick(_table({key: [1.0] * 8}))
        assert s.alerting() == [key]
        # ...confirm_windows healthy ticks do.
        for _ in range(2):
            s.tick(_table({key: [1.0] * 8}))
        assert s.alerting() == []
        assert any(k == "latency_drift_clear" for k, _ in events)

    def test_fleetwide_drift_does_not_replan(self):
        s, _, _, replans = self._mk()
        keys = [("m", "decode", f"n{i}") for i in range(3)]
        for _ in range(4):
            s.tick(_table({k: [1.0] * 8 for k in keys}))
        for _ in range(4):
            s.tick(_table({k: [5.0] * 8 for k in keys}))
        assert len(s.alerting()) == 3  # all three members drifted
        assert replans == []  # not placement-fixable: no replan

    def test_config_validation(self):
        with pytest.raises(ValueError):
            DriftSentinel(clear_factor=3.0, drift_factor=2.0)
        with pytest.raises(ValueError):
            DriftSentinel(baseline_decay=1.5)

    def test_status_jsonable(self):
        import json
        s, *_ = self._mk()
        s.tick(_table({("m", "decode", "n1"): [1.0] * 8}))
        json.dumps(s.status())


class TestDriftSoak:
    """The ISSUE-20 acceptance soak: the pinned drift scenario (sim
    fabric, virtual clock, 5x decode slowdown on exactly one member at
    half-replay) must produce — reproducibly across the chaos-seed
    matrix — a sentinel alert naming (model, decode, that member) within
    3 fast windows, the next fast-burn alert carrying the same culprit,
    a forced-sampling window, and a placement replan request, all read
    back from the flight recorder."""

    @pytest.mark.parametrize("seed", [0, 1000, 2000])
    def test_drift_detected_and_attributed(self, seed):
        from dmlc_tpu.loadgen import (
            DRIFT_DETECT_FAST_WINDOWS,
            DRIFT_FAST_WINDOW_S,
            DRIFT_MEMBER_INDEX,
            DRIFT_SCRAPE_INTERVAL_S,
            DRIFT_STAGE,
            drift_sentinel_harness,
            validate_slo_cert,
        )
        from tools.slo_cert import critpath_failures

        harness = drift_sentinel_harness(4, seed)
        cert = harness.run()
        assert validate_slo_cert(cert) == []
        # The exact verdicts CI's drift leg gates on (tools/slo_cert.py
        # --critpath) must hold for the pytest matrix too.
        assert critpath_failures(cert) == []

        member = harness.member_addrs[DRIFT_MEMBER_INDEX]
        events = harness.flight.to_wire()["events"]

        # 1. Injection recorded, then the sentinel names the culprit.
        (injected,) = [e for e in events if e["kind"] == "drift_injected"]
        assert injected["member"] == member
        assert injected["stage"] == DRIFT_STAGE
        drifts = [e for e in events if e["kind"] == "latency_drift"]
        assert drifts, "sentinel never alerted"
        first = drifts[0]
        assert (first["model"], first["stage"], first["member"]) == (
            "resnet50", DRIFT_STAGE, member)
        assert first["factor"] > harness.sentinel.drift_factor

        # 2. Within 3 fast windows of the injection.
        bound_s = DRIFT_DETECT_FAST_WINDOWS * DRIFT_FAST_WINDOW_S
        assert first["t"] - injected["t"] <= bound_s + DRIFT_SCRAPE_INTERVAL_S

        # 3. The next fast-burn alert carries the same culprit.
        burns_after = [e for e in events if e["kind"] == "slo_fast_burn"
                       and e["t"] >= first["t"]]
        assert burns_after, "no burn alert after the drift alert"
        assert burns_after[0]["culprit_member"] == member
        assert burns_after[0]["culprit_stage"] == DRIFT_STAGE
        assert 0.0 < burns_after[0]["critpath_share"] <= 1.0

        # 4. Forced sampling opened, replan requested, both recorded.
        assert any(e["kind"] == "drift_force_sample" and e["member"] == member
                   for e in events)
        (replan,) = [e for e in events if e["kind"] == "drift_replan_request"]
        assert replan["reason"] == f"latency_drift:resnet50:{DRIFT_STAGE}:{member}"
        assert harness.replan_requests == [replan["reason"]]

        # 5. The folded table blames the slowed member's decode lane above
        # every other lane, and shares sum to exactly 1.
        body = cert["critpath"]["table"]["models"]["resnet50"]
        top = body["lanes"][0]
        assert (top["stage"], top["member"]) == (DRIFT_STAGE, member)
        assert sum(ln["share"] for ln in body["lanes"]) == pytest.approx(1.0)
