"""Pallas kernels vs plain-jnp references (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_tpu.ops import preprocess as pp
from dmlc_tpu.ops.pallas_kernels import normalize_u8, softmax_top1


def test_normalize_matches_reference():
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 256, (4, 32, 32, 3), np.uint8)
    got = np.asarray(normalize_u8(batch, pp.IMAGENET_MEAN, pp.IMAGENET_STD))
    want = np.asarray(pp.normalize(batch))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_normalize_bf16_output():
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, (2, 16, 16, 3), np.uint8)
    out = normalize_u8(batch, pp.IMAGENET_MEAN, pp.IMAGENET_STD, out_dtype=jnp.bfloat16)
    assert out.dtype == jnp.bfloat16
    want = np.asarray(pp.normalize(batch), np.float32)
    np.testing.assert_allclose(np.asarray(out, np.float32), want, rtol=1e-2, atol=1e-2)


def test_softmax_top1_matches_reference():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(32, 1000)).astype(np.float32) * 4)
    idx, prob = softmax_top1(logits)
    ref = jax.nn.softmax(logits, axis=-1)
    np.testing.assert_array_equal(np.asarray(idx), np.argmax(np.asarray(logits), -1))
    np.testing.assert_allclose(np.asarray(prob), np.max(np.asarray(ref), -1), rtol=1e-5)


def test_softmax_top1_extreme_logits_stable():
    logits = jnp.array([[1e4, -1e4, 0.0, 9.9e3]], jnp.float32)
    idx, prob = softmax_top1(logits)
    assert int(idx[0]) == 0
    assert np.isfinite(float(prob[0])) and 0 < float(prob[0]) <= 1.0


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------


def _qkv(seed, b=2, h=2, s=256, d=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, s, d), dtype) for k in ks)


def test_flash_attention_matches_dense():
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(0)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(flash_attention(q, k, v))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_flash_attention_causal_matches_dense():
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(1, s=256)
    want = np.asarray(dense_attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_flash_attention_bf16():
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(2, s=128, dtype=jnp.bfloat16)
    want = np.asarray(dense_attention(q, k, v, causal=True)).astype(np.float32)
    got = np.asarray(flash_attention(q, k, v, causal=True)).astype(np.float32)
    np.testing.assert_allclose(got, want, atol=3e-2, rtol=3e-2)


def test_flash_attention_short_sequence_shrinks_blocks():
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(3, s=32, d=16)
    want = np.asarray(dense_attention(q, k, v))
    got = np.asarray(flash_attention(q, k, v))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_flash_attention_divisor_blocks():
    # 192 % 128 != 0, but 64 divides it: blocks shrink to the largest
    # divisor instead of rejecting (advisor finding, round 2).
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(4, s=192, d=16)
    want = np.asarray(dense_attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_flash_attention_odd_sequence_full_block():
    # No 8-divisible divisor (prime S): falls back to ONE full-S block,
    # which Mosaic always accepts (block dim == array dim).
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(4, s=193, d=16)
    want = np.asarray(dense_attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_flash_attention_odd_sequence_grads_match_dense():
    # The full-block fallback must be training-grade too: backward with
    # n_q = n_k = 1 (no scratch carries) at an odd S.
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(8, b=1, h=2, s=193, d=16)

    def loss(att, q, k, v):
        return jnp.sum(att(q, k, v, causal=True) ** 2)

    want = jax.grad(lambda q, k, v: loss(dense_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(lambda q, k, v: loss(flash_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-5, rtol=1e-4)


def test_flash_attention_rejects_long_unpaddable_sequence():
    import pytest

    from dmlc_tpu.ops.pallas_kernels import flash_attention

    # Odd AND past the full-block VMEM cap: refuse with advice to pad.
    q, k, v = _qkv(4, b=1, h=1, s=8209, d=16)  # prime > _FULL_BLOCK_CAP
    with pytest.raises(ValueError, match="pad the sequence"):
        flash_attention(q, k, v)


def test_flash_attention_streamed_forward_matches_dense(monkeypatch):
    # Force the HBM-streamed schedule (normally S past the VMEM cap) at a
    # test-sized S by shrinking the resident threshold.
    from dmlc_tpu.ops import pallas_kernels as pk
    from dmlc_tpu.parallel.ring_attention import dense_attention

    monkeypatch.setattr(pk, "_RESIDENT_KV_BYTES", 1)
    q, k, v = _qkv(6, s=256, d=32)
    for causal in (False, True):
        want = np.asarray(dense_attention(q, k, v, causal=causal))
        got = np.asarray(pk.flash_attention(q, k, v, causal=causal))
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)


def test_flash_attention_streamed_grads_match_dense(monkeypatch):
    # Blockwise backward over a streamed forward: several q AND k blocks in
    # every kernel (the scratch-carry paths), both causal and not.
    from dmlc_tpu.ops import pallas_kernels as pk
    from dmlc_tpu.parallel.ring_attention import dense_attention

    monkeypatch.setattr(pk, "_RESIDENT_KV_BYTES", 1)
    q, k, v = _qkv(7, b=1, h=2, s=512, d=16)

    for causal in (False, True):
        def loss(att, q, k, v):
            return jnp.sum(att(q, k, v, causal=causal) ** 2)

        want = jax.grad(lambda q, k, v: loss(dense_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
        got = jax.grad(lambda q, k, v: loss(pk.flash_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-5, rtol=1e-4)


def test_flash_attention_grads_match_dense():
    from dmlc_tpu.ops.pallas_kernels import flash_attention
    from dmlc_tpu.parallel.ring_attention import dense_attention

    q, k, v = _qkv(5, s=128, d=32)

    def loss(att, q, k, v):
        return jnp.sum(att(q, k, v, causal=True) ** 2)

    want = jax.grad(lambda q, k, v: loss(dense_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(lambda q, k, v: loss(flash_attention, q, k, v), argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=3e-5, rtol=1e-4)


def test_auto_block_explicit_oversized_request_falls_back_to_divisors():
    """ADVICE r3: an explicit blk >= S for S past _FULL_BLOCK_CAP used to
    raise 'pad the sequence' even when Mosaic-legal divisors of S exist."""
    from dmlc_tpu.ops.pallas_kernels import _FULL_BLOCK_CAP, _auto_block

    assert _auto_block(8192, 8192, 128) == 128
    assert _auto_block(8192, 100000, 512) == 512
    # The docstring example: S=192 with a 128 request picks 96.
    assert _auto_block(192, 128, 128) == 96
    # Full-S blocks still allowed under the cap...
    assert _auto_block(1021, 1021, 128) == 1021  # prime, <= cap
    # ...and a long sequence with NO legal divisor still gets the advice.
    import pytest

    with pytest.raises(ValueError, match="pad the sequence"):
        _auto_block(_FULL_BLOCK_CAP * 2 + 1, None, 128)  # odd, > cap


class TestAttentionDispatch:
    """Crossover-dispatched attention() (VERDICT r4 item 3): dense below
    both calibrated bounds, flash otherwise; numerically it must agree
    with both legs everywhere."""

    def _routed(self, monkeypatch, b, h, s):
        import importlib

        # The MODULE by dotted path: the package __init__ re-exports a
        # same-named FUNCTION that shadows the submodule under normal
        # attribute-style imports.
        ra = importlib.import_module("dmlc_tpu.parallel.ring_attention")
        from dmlc_tpu.ops import pallas_kernels as pk

        calls = []
        monkeypatch.setattr(
            pk, "flash_attention",
            lambda q, k, v, **kw: (calls.append("flash"), q)[1],
        )
        monkeypatch.setattr(
            ra, "dense_attention",
            lambda q, k, v, **kw: (calls.append("dense"), q)[1],
        )
        q = jnp.zeros((b, h, s, 128), jnp.bfloat16)
        pk.attention(q, q, q)
        return calls[-1]

    def test_small_problem_routes_dense(self, monkeypatch):
        assert self._routed(monkeypatch, 1, 8, 2048) == "dense"

    def test_long_sequence_routes_flash(self, monkeypatch):
        from dmlc_tpu.ops import pallas_kernels as pk

        assert 8192 >= pk.AUTO_FLASH_MIN_S
        assert self._routed(monkeypatch, 1, 2, 8192) == "flash"

    def test_large_batch_heads_routes_flash_below_threshold(self, monkeypatch):
        # The LM regime: S=2048 but bh=48 -> 805 MB f32 scores > cap.
        assert self._routed(monkeypatch, 8, 6, 2048) == "flash"

    def test_dispatch_agrees_with_both_legs(self):
        from dmlc_tpu.ops import pallas_kernels as pk
        from dmlc_tpu.parallel.ring_attention import dense_attention

        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(k1, (2, 2, 128, 128), jnp.float32)
        k = jax.random.normal(k2, (2, 2, 128, 128), jnp.float32)
        v = jax.random.normal(k3, (2, 2, 128, 128), jnp.float32)
        want = dense_attention(q, k, v, causal=True)
        got = pk.attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
