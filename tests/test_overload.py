"""Overload-control suite (docs/OVERLOAD.md): deadline propagation, admission
shedding, retry budgets + circuit breakers, and gray-failure ejection.

Two kinds of test live here:

- deterministic sim-fabric tests: SimRpcNetwork's virtual clock + scriptable
  per-link latency make timeout/breaker/gray behavior replay exactly (the
  fabric satellite this PR added), so the state machines are pinned without
  a single real sleep;
- a seeded real-thread soak: 10x more concurrent requests than the worker
  admits, one slow "gray" service — the acceptance bar is that every
  rejected request fast-fails typed (< 1 s, never the old 60 s hang), no
  admitted request overruns its propagated deadline by more than the grace
  interval, and the member sheds instead of queueing.

CI runs this file inside the chaos seed matrix (tools/ci_check.sh): the
DMLC_CHAOS_SEED base offsets every parametrized seed range.
"""

from __future__ import annotations

import os
import random
import threading
import time

import pytest

from dmlc_tpu.cluster import deadline as deadline_lib
from dmlc_tpu.cluster.admission import AdmissionGate
from dmlc_tpu.cluster.retrypolicy import RetryPolicy
from dmlc_tpu.cluster.rpc import (
    DeadlineExceeded,
    Overloaded,
    RpcError,
    RpcUnreachable,
    SimRpcNetwork,
    TcpRpc,
    TcpRpcServer,
    serve_with_deadline,
)
from dmlc_tpu.scheduler.jobs import JobScheduler
from dmlc_tpu.scheduler.worker import DynamicBatcher, PredictWorker
from dmlc_tpu.utils.metrics import Counters

SEED_BASE = int(os.environ.get("DMLC_CHAOS_SEED", "0"))


def seeds(n: int) -> range:
    return range(SEED_BASE, SEED_BASE + n)


# ---------------------------------------------------------------------------
# Deadline propagation on the deterministic fabric
# ---------------------------------------------------------------------------


class TestSimDeadlines:
    def test_timeout_honored_against_link_latency(self):
        net = SimRpcNetwork()
        net.serve("b", {"echo": lambda p: {"ok": True}})
        net.set_latency("a", "b", 5.0)
        c = net.client("a")
        t0 = net.now
        with pytest.raises(RpcUnreachable, match="no reply within"):
            c.call("b", "echo", {}, timeout=1.0)
        # The caller really waited out its budget — and ONLY its budget.
        assert net.now - t0 == pytest.approx(1.0)
        # Under the latency, calls succeed and the clock advances by transit.
        net.set_latency("a", "b", 0.25)
        t0 = net.now
        assert c.call("b", "echo", {}, timeout=1.0) == {"ok": True}
        assert net.now - t0 == pytest.approx(0.25)

    def test_server_sheds_work_that_arrives_expired(self):
        net = SimRpcNetwork()
        ran = []
        net.serve("b", {"m": lambda p: ran.append(1) or {}})
        c = net.client("a")
        with pytest.raises(DeadlineExceeded):
            c.call("b", "m", {}, timeout=1.0, deadline=0.0)
        assert not ran  # never executed: no wasted work for a dead caller

    def test_deadline_checked_after_execution(self):
        """A method that burns past its budget raises DeadlineExceeded to
        the caller instead of returning a result the caller gave up on."""
        net = SimRpcNetwork()

        def slow(p):
            net.advance(3.0)  # service time, in virtual seconds
            return {"ok": True}

        net.serve("b", {"slow": slow})
        c = net.client("a")
        with pytest.raises(DeadlineExceeded, match="past its"):
            c.call("b", "slow", {}, timeout=1.0)
        # With budget to spare the same method answers fine.
        assert c.call("b", "slow", {}, timeout=10.0) == {"ok": True}

    def test_nested_calls_inherit_remaining_budget(self):
        """leader -> member -> SDFS-pull shape: the inner hop's budget is
        the OUTER caller's remainder, not a fresh 60 s default."""
        net = SimRpcNetwork()
        seen: list[float] = []

        def inner(p):
            dl = deadline_lib.current()
            seen.append(dl.remaining())
            return {}

        def outer(p):
            net.advance(0.4)  # the member works a while first
            # Note: inner timeout says 60, but the ambient deadline caps it.
            return net.client("b").call("c", "inner", {}, timeout=60.0)

        net.serve("b", {"outer": outer})
        net.serve("c", {"inner": inner})
        net.client("a").call("b", "outer", {}, timeout=1.0)
        assert len(seen) == 1
        assert seen[0] <= 0.6 + 1e-9  # inherited: 1.0 budget - 0.4 spent

    def test_nested_call_fast_fails_when_budget_is_gone(self):
        net = SimRpcNetwork()
        inner_ran = []

        def outer(p):
            net.advance(2.0)  # overruns the caller's 1.0 budget
            return net.client("b").call("c", "inner", {}, timeout=60.0)

        net.serve("b", {"outer": outer})
        net.serve("c", {"inner": lambda p: inner_ran.append(1) or {}})
        with pytest.raises(DeadlineExceeded):
            net.client("a").call("b", "outer", {}, timeout=1.0)
        assert not inner_ran  # the dead branch was pruned at the first hop


# ---------------------------------------------------------------------------
# TCP fabric: single-spend timeout + wire-typed errors (satellite #1)
# ---------------------------------------------------------------------------


class TestTcpDeadlines:
    def test_timeout_spent_once_across_phases(self):
        """The old fabric gave connect and recv a FULL timeout each (~2x
        the stated bound); now one monotonic budget covers all phases."""
        import socket as socketlib

        # A listener that accepts and then never replies: the call must
        # fail in ~timeout, not ~2x timeout.
        srv = socketlib.socket(socketlib.AF_INET, socketlib.SOCK_STREAM)
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        addr = f"127.0.0.1:{srv.getsockname()[1]}"
        try:
            t0 = time.monotonic()
            with pytest.raises(RpcUnreachable):
                TcpRpc().call(addr, "m", {}, timeout=0.6)
            elapsed = time.monotonic() - t0
            assert elapsed < 1.1, f"timeout double-spent: {elapsed:.2f}s for a 0.6s budget"
        finally:
            srv.close()

    def test_deadline_exceeded_and_overloaded_survive_the_wire(self):
        """Typed verdicts cross the TCP fabric intact: a DeadlineExceeded a
        method reached (e.g. on a nested hop) arrives typed, and an
        Overloaded shed arrives typed WITH its retry-after hint."""
        gate = AdmissionGate(1, 0, name="predict", retry_after_s=0.125)

        def nested_verdict(p):
            raise DeadlineExceeded("nested hop ran out of budget")

        def gated(p):
            with gate.admit():
                time.sleep(float(p.get("sleep", 0)))
                return {"ok": True}

        server = TcpRpcServer(
            "127.0.0.1", 0, {"nested": nested_verdict, "gated": gated}
        )
        try:
            rpc = TcpRpc()
            with pytest.raises(DeadlineExceeded):
                rpc.call(server.address, "nested", {}, timeout=2.0)
            # Overloaded verdict: saturate the single admission slot, then
            # call again.
            holder = threading.Thread(
                target=lambda: rpc.call(
                    server.address, "gated", {"sleep": 0.8}, timeout=5.0
                ),
            )
            holder.start()
            time.sleep(0.15)  # let the holder occupy the slot
            try:
                with pytest.raises(Overloaded) as exc:
                    rpc.call(server.address, "gated", {}, timeout=5.0)
                assert exc.value.retry_after_s == pytest.approx(0.125)
            finally:
                holder.join(timeout=5)
        finally:
            server.close()

    def test_client_side_timeout_is_overload_class(self):
        """When the server overruns and the CLIENT's clock trips first, the
        verdict is RpcUnreachable — still overload-class for the breaker."""
        server = TcpRpcServer(
            "127.0.0.1", 0, {"slow": lambda p: time.sleep(0.5) or {}}
        )
        try:
            from dmlc_tpu.cluster.retrypolicy import is_overload_error

            with pytest.raises(RpcUnreachable) as exc:
                TcpRpc().call(server.address, "slow", {}, timeout=0.1)
            assert is_overload_error(exc.value)
        finally:
            server.close()


# ---------------------------------------------------------------------------
# Admission control: gates shed, batcher brownouts (tentpole part 2)
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_gate_sheds_past_capacity_and_counts(self):
        metrics = Counters()
        gate = AdmissionGate(2, 1, name="predict", metrics=metrics)
        holders = [gate.admit() for _ in range(3)]
        for h in holders:
            h.__enter__()
        with pytest.raises(Overloaded, match="queue full"):
            with gate.admit():
                pass
        for h in holders:
            h.__exit__(None, None, None)
        # Released capacity admits again.
        with gate.admit():
            pass
        s = gate.summary()
        assert s["sheds"] == 1 and s["admitted"] == 4
        assert s["queue_hw"] == 1  # one request sat beyond max_inflight
        snap = metrics.snapshot()
        assert snap["shed"] == 1 and snap["shed_predict"] == 1
        assert snap["queue_hw_predict_high"] == 1

    def test_disabled_gate_admits_everything(self):
        gate = AdmissionGate(0, 0)
        ctxs = [gate.admit() for _ in range(100)]
        for c in ctxs:
            c.__enter__()
        for c in ctxs:
            c.__exit__(None, None, None)
        assert gate.summary()["sheds"] == 0

    def test_predict_worker_sheds_through_gate(self):
        gate = AdmissionGate(1, 0, name="predict")
        worker = PredictWorker({"m": lambda synsets: [0] * len(synsets)}, gate=gate)
        # Occupy the only slot, then the RPC surface must shed typed.
        hold = gate.admit()
        hold.__enter__()
        try:
            with pytest.raises(Overloaded):
                worker._predict({"model": "m", "synsets": ["x"]})
        finally:
            hold.__exit__(None, None, None)
        assert worker._predict({"model": "m", "synsets": ["x"]})["predictions"] == [0]

    def test_batcher_bounded_queue_sheds_typed(self):
        release = threading.Event()

        def blocked(synsets):
            release.wait(5.0)
            return [int(s) for s in synsets]

        metrics = Counters()
        batcher = DynamicBatcher(
            blocked, batch_size=2, max_wait_s=0.01, max_queue=4, metrics=metrics
        )
        try:
            futs = [batcher.submit(str(i)) for i in range(2)]  # in the backend
            time.sleep(0.1)  # worker picks them up, blocks in `blocked`
            futs += [batcher.submit(str(i)) for i in range(2, 6)]  # fills queue
            with pytest.raises(Overloaded) as exc:
                batcher.submit("nope")
            assert exc.value.retry_after_s == pytest.approx(0.01)
            release.set()
            assert sorted(f.result(timeout=5) for f in futs) == list(range(6))
            s = batcher.summary()
            assert s["sheds"] == 1 and s["queue_hw"] == 4
            assert metrics.snapshot()["shed_microbatch"] == 1
        finally:
            release.set()
            batcher.stop()

    def test_batcher_brownout_skips_wait_when_queue_deep(self):
        """With the queue at its bound, the coalescing wait must collapse
        toward zero — the batcher dispatches as fast as the device drains
        instead of adding latency it no longer has."""
        calls: list[float] = []

        def backend(synsets):
            calls.append(time.monotonic())
            return [int(s) for s in synsets]

        # max_wait_s is LONG (0.5 s); queue bound floors at 2*batch = 8.
        batcher = DynamicBatcher(backend, batch_size=4, max_wait_s=0.5, max_queue=4)
        try:
            t0 = time.monotonic()
            futs = [batcher.submit(str(i)) for i in range(8)]
            for f in futs:
                f.result(timeout=5)
            elapsed = time.monotonic() - t0
            # Un-brownouted, two partial waits would cost ~1.0 s; full
            # batches + pressure-shrunk waits finish far faster.
            assert elapsed < 0.45, f"brownout failed to shrink the wait: {elapsed:.2f}s"
        finally:
            batcher.stop()


# ---------------------------------------------------------------------------
# Retry budgets + circuit breakers (tentpole part 3)
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_breaker_opens_half_opens_and_recovers(self):
        net = SimRpcNetwork()
        policy = RetryPolicy(clock=net.clock, breaker_threshold=3, breaker_cooldown_s=5.0)
        net.serve("b", {"m": lambda p: {}})
        net.crash("b")
        c = net.client("a")
        for _ in range(3):
            assert policy.allow("b")
            with pytest.raises(RpcUnreachable) as exc:
                c.call("b", "m", {}, timeout=1.0)
            policy.record("b", exc.value)
        assert policy.breaker_state("b") == "open"
        # While open, nothing is allowed — and no RPC leaves the node.
        before = len(net.calls)
        assert not policy.allow("b")
        assert len(net.calls) == before
        # Cooldown elapses -> half-open admits exactly ONE probe.
        net.advance(5.0)
        assert policy.allow("b")
        assert not policy.allow("b"), "half-open must admit a single probe"
        # The probe fails (member still down): snaps back open.
        with pytest.raises(RpcUnreachable) as exc:
            c.call("b", "m", {}, timeout=1.0)
        policy.record("b", exc.value)
        assert policy.breaker_state("b") == "open"
        # Member restarts; next window's probe succeeds -> closed.
        net.restart("b")
        net.advance(5.0)
        assert policy.allow("b")
        c.call("b", "m", {}, timeout=1.0)
        policy.record("b")
        assert policy.breaker_state("b") == "closed"
        assert policy.open_count("b") == 2

    def test_method_errors_do_not_trip_the_breaker(self):
        policy = RetryPolicy(clock=lambda: 0.0, breaker_threshold=2)
        for _ in range(10):
            policy.record("b", RpcError("semantic refusal"))
        assert policy.breaker_state("b") == "closed"

    def test_retry_budget_token_bucket(self):
        now = [0.0]
        policy = RetryPolicy(clock=lambda: now[0], retry_rate_per_s=1.0, retry_burst=3.0)
        assert [policy.allow_retry("b") for _ in range(5)] == [
            True, True, True, False, False,
        ]
        now[0] += 2.0  # refill 2 tokens
        assert policy.allow_retry("b") and policy.allow_retry("b")
        assert not policy.allow_retry("b")
        # Budgets are per destination: "c" is untouched.
        assert policy.allow_retry("c")

    def test_denials_are_counted(self):
        metrics = Counters()
        policy = RetryPolicy(
            clock=lambda: 0.0, breaker_threshold=1, retry_burst=1.0, metrics=metrics
        )
        policy.record("b", RpcUnreachable("down"))  # opens at threshold 1
        assert not policy.allow("b")
        assert policy.allow_retry("c") and not policy.allow_retry("c")
        snap = metrics.snapshot()
        assert snap["breaker_open"] == 1
        assert snap["breaker_denied"] >= 1 and snap["retries_denied"] >= 1


# ---------------------------------------------------------------------------
# Scheduler integration: budgeted retries + gray ejection (tentpole parts 3+4)
# ---------------------------------------------------------------------------


def make_workload(n):
    return [(f"n{i:05d}", i) for i in range(n)]


class GrayFixture:
    """N echo members on the sim fabric; per-link latency models health.
    The scheduler's timer IS the fabric's virtual clock, so member EWMAs
    observe exactly the scripted latencies."""

    def __init__(
        self,
        n_members=6,
        n_queries=96,
        shard=8,
        predict_deadline=1.0,
        gray_factor=3.0,
        gray_probe_interval=0.5,
        policy_kw=None,
    ):
        self.net = SimRpcNetwork()
        self.members = [f"m{i}" for i in range(n_members)]
        self.served: dict[str, int] = {m: 0 for m in self.members}
        for m in self.members:
            def backend(synsets, member=m):
                self.served[member] += len(synsets)
                return [int(s[1:]) for s in synsets]

            self.net.serve(m, PredictWorker({"resnet18": backend}).methods())
            self.net.set_latency("L", m, 0.01)
        self.metrics = Counters()
        self.policy = RetryPolicy(
            clock=self.net.clock, metrics=self.metrics, **(policy_kw or {})
        )
        self.scheduler = JobScheduler(
            self.net.client("L"),
            lambda: list(self.members),
            jobs={"resnet18": make_workload(n_queries)},
            shard_size=shard,
            shard_timeout_s=predict_deadline,
            timer=self.net.clock,
            hedge_tail=False,  # isolate retry/gray behavior from hedging
            retry_policy=self.policy,
            gray_factor=gray_factor,
            gray_min_latency_s=0.05,
            gray_probe_interval_s=gray_probe_interval,
            metrics=self.metrics,
        )
        self.scheduler.is_leading = True

    def calls_to(self, member: str) -> int:
        return sum(1 for addr, m in self.net.calls if addr == member and m == "job.predict")


class TestGrayEjection:
    def test_slow_member_demoted_then_restored(self):
        # The workload must OUTLIVE the recovery: canaries are real shards,
        # so restoration (several probe intervals of good samples) needs
        # work still flowing when the member heals.
        f = GrayFixture(n_queries=4000, gray_probe_interval=0.2)
        slow = "m3"
        f.net.set_latency("L", slow, 0.5)  # slow but ALIVE (under the deadline)
        f.scheduler._start({})

        demoted_seen = False
        for _ in range(2000):
            f.scheduler.assign_once()
            f.scheduler.dispatch_all_once()
            if slow in f.scheduler.demoted:
                demoted_seen = True
                break
        assert demoted_seen, "slow-but-alive member never demoted"
        assert f.metrics.get("gray_demotions") == 1
        # Quarantined: no NEW assignment...
        f.scheduler.assign_once()
        for job in f.scheduler.jobs.values():
            if job.running:
                assert slow not in job.assigned
        # ...but canary probes keep flowing, and recovery restores it.
        f.net.set_latency("L", slow, 0.01)
        before = f.calls_to(slow)
        for _ in range(4000):
            f.scheduler.assign_once()
            if f.scheduler.dispatch_all_once() == 0:
                f.net.advance(0.05)  # idle tick: virtual time still passes
            if slow not in f.scheduler.demoted:
                break
            if all(j.done for j in f.scheduler.jobs.values()):
                break
        assert slow not in f.scheduler.demoted, "recovered member never restored"
        assert f.calls_to(slow) > before, "no canary probes reached the demoted member"
        assert f.metrics.get("gray_restored") == 1

    def test_breaker_reopening_demotes_member(self):
        f = GrayFixture(n_queries=400, policy_kw={"breaker_threshold": 2,
                                                  "breaker_cooldown_s": 0.2})
        flaky = "m1"
        f.net.crash(flaky)  # unreachable: breaker food, not latency food
        f.scheduler._start({})
        for _ in range(3000):
            f.scheduler.assign_once()
            if f.scheduler.dispatch_all_once() == 0:
                f.net.advance(0.1)
            if flaky in f.scheduler.demoted:
                break
            if all(j.done for j in f.scheduler.jobs.values()):
                break
        assert flaky in f.scheduler.demoted, "reopening breaker never demoted the member"


@pytest.mark.parametrize("seed", seeds(3))
def test_overload_soak_gray_member_bounded_retries(seed):
    """The sim-side acceptance soak: a full workload against a fleet with
    one gray (slow-but-alive) member. Asserts, per the issue:

    - the gray member is demoted and — after its latency recovers — restored;
    - total dispatches to it stay within the retry budget's order of
      magnitude (no storm: bounded by first-attempts + tokens + canaries);
    - every admitted shard's recorded latency stays under the propagated
      deadline + grace;
    - the workload completes exactly once despite the turbulence.
    """
    rng = random.Random(seed)
    n_queries = 2400
    f = GrayFixture(
        n_queries=n_queries,
        predict_deadline=1.0,
        gray_probe_interval=0.2,
        policy_kw={"retry_rate_per_s": 2.0, "retry_burst": 4.0},
    )
    slow = rng.choice(f.members)
    f.net.set_latency("L", slow, 0.6)
    f.scheduler._start({})

    was_demoted = False
    healed = False
    for step in range(20_000):
        if all(j.done for j in f.scheduler.jobs.values()):
            break
        if step % 5 == 0:
            f.scheduler.assign_once()
        if f.scheduler.dispatch_all_once() == 0:
            f.net.advance(0.05)
        if not was_demoted and slow in f.scheduler.demoted:
            was_demoted = True
        if was_demoted and not healed and rng.random() < 0.2:
            f.net.set_latency("L", slow, 0.01)  # the thermal event passes
            healed = True
    job = f.scheduler.jobs["resnet18"]
    assert job.finished == n_queries and job.correct == n_queries, (
        f"lost/duplicated work (seed {seed})"
    )
    assert was_demoted, f"gray member {slow} never demoted (seed {seed})"
    assert slow not in f.scheduler.demoted, f"{slow} never restored (seed {seed})"
    # No storm: while gray, the member saw only its pre-demotion shards,
    # budgeted retries, and interval-spaced canaries — far below the shard
    # count a naive requeue loop would have thrown at it.
    assert f.calls_to(slow) < n_queries // 2, (
        f"{f.calls_to(slow)} dispatches to the gray member looks like a "
        f"retry storm (seed {seed})"
    )
    # Admitted work never overran deadline + grace (0.25 s).
    worst = max(f.scheduler.jobs["resnet18"].shard_stats.reservoir)
    assert worst <= 1.0 + 0.25, f"admitted shard took {worst:.2f}s (seed {seed})"


# ---------------------------------------------------------------------------
# Real-thread acceptance soak: 10x burst, typed fast-fails, bounded p99
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", seeds(1))
def test_overload_soak_threads_fast_fail_and_bounded_p99(seed):
    """10x the worker's admission capacity arrives at once (real threads,
    real clock). Every request must resolve FAST and TYPED: rejected ones
    in well under a second via Overloaded/DeadlineExceeded, admitted ones
    inside deadline + grace. Nothing hangs toward the old 60 s default."""
    rng = random.Random(seed)
    service_s = 0.02
    gate = AdmissionGate(2, 2, name="predict", metrics=Counters(), retry_after_s=0.05)

    def backend(synsets):
        time.sleep(service_s)
        return [int(s) for s in synsets]

    worker = PredictWorker({"m": backend}, gate=gate)
    methods = worker.methods()
    deadline_s = 1.0
    n = 40  # 10x the gate's capacity of 4
    results: dict[int, tuple[str, float]] = {}

    def one(i: int, jitter: float) -> None:
        time.sleep(jitter)
        t0 = time.monotonic()
        try:
            serve_with_deadline(
                methods, "job.predict",
                {"model": "m", "synsets": [str(i)]},
                deadline_s, time.monotonic,
            )
            verdict = "ok"
        except Overloaded:
            verdict = "shed"
        except DeadlineExceeded:
            verdict = "deadline"
        results[i] = (verdict, time.monotonic() - t0)

    threads = [
        threading.Thread(target=one, args=(i, rng.uniform(0, 0.01))) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(results) == n, "some requests never resolved"

    verdicts = [v for v, _ in results.values()]
    shed = verdicts.count("shed")
    ok = verdicts.count("ok")
    assert shed > 0, "a 10x burst against a capacity-4 gate must shed"
    assert ok > 0, "admitted work must still complete under overload"
    # Typed rejections are FAST: well under the 1 s bar (and nowhere near
    # the old 60 s hang).
    for i, (verdict, elapsed) in results.items():
        if verdict in ("shed", "deadline"):
            assert elapsed < 1.0, f"request {i} {verdict} after {elapsed:.2f}s"
        else:
            assert elapsed <= deadline_s + 0.25, (
                f"admitted request {i} overran deadline+grace: {elapsed:.2f}s"
            )
    assert gate.summary()["sheds"] == shed
