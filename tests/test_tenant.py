"""Tenant identity + quota-edge suite (docs/OVERLOAD.md §Priority classes).

Pins the tentpole's admission contracts at their edges:

- the ambient tenant context (cluster/tenant.py) mirrors tracectx: bind /
  clear semantics, wire form omitted for the default tenant;
- a mixed-version fleet keeps working: legacy frames carry no ``n`` field
  and read as the default tenant at full share;
- AT quota admits, one past quota sheds *typed* (``quota="over_quota"``,
  tenant named) while the gate still has room; a full gate sheds
  ``gate_full``; and the microbatch displacement ordering is
  low-priority-and-over-quota first, never within-quota work.

CI runs this file inside the chaos seed matrix (tools/ci_check.sh).
"""

from __future__ import annotations

import threading
import time

import pytest

from dmlc_tpu.cluster import tenant as tenant_mod
from dmlc_tpu.cluster.admission import AdmissionGate
from dmlc_tpu.cluster.rpc import Overloaded, SimRpcNetwork
from dmlc_tpu.scheduler.worker import DynamicBatcher
from dmlc_tpu.utils.metrics import Counters


def specs(**kw):
    """{'acme': ('low', 0.2)} -> parsed TenantSpec table."""
    return tenant_mod.parse_tenants(
        {name: {"priority": p, "share": s} for name, (p, s) in kw.items()}
    )


# ---------------------------------------------------------------------------
# Ambient context + wire form
# ---------------------------------------------------------------------------


class TestAmbientTenant:
    def test_default_when_unbound(self):
        assert tenant_mod.current() == tenant_mod.DEFAULT_TENANT
        assert tenant_mod.wire_context() is None

    def test_bind_nests_and_restores(self):
        with tenant_mod.bind("acme") as t:
            assert t == "acme"
            assert tenant_mod.current() == "acme"
            assert tenant_mod.wire_context() == "acme"
            with tenant_mod.bind("beta"):
                assert tenant_mod.current() == "beta"
            assert tenant_mod.current() == "acme"
        assert tenant_mod.current() == tenant_mod.DEFAULT_TENANT

    def test_bind_none_clears_inherited_tenant(self):
        # The server binds None for frames without an `n` field; that must
        # CLEAR any tenant inherited on the dispatching stack (the sim
        # fabric dispatches on the caller's stack).
        with tenant_mod.bind("acme"), tenant_mod.bind(None):
            assert tenant_mod.current() == tenant_mod.DEFAULT_TENANT
            assert tenant_mod.wire_context() is None

    def test_default_tenant_rides_wireless(self):
        with tenant_mod.bind(tenant_mod.DEFAULT_TENANT):
            assert tenant_mod.wire_context() is None

    def test_from_wire_tolerates_garbage(self):
        assert tenant_mod.from_wire(None) is None
        assert tenant_mod.from_wire("") is None
        assert tenant_mod.from_wire(42) is None
        assert tenant_mod.from_wire(["acme"]) is None
        assert tenant_mod.from_wire("acme") == "acme"

    def test_parse_tenants_validates(self):
        with pytest.raises(ValueError):
            tenant_mod.parse_tenants({"a": {"priority": "urgent"}})
        with pytest.raises(ValueError):
            tenant_mod.parse_tenants({"a": {"share": 0.0}})
        with pytest.raises(ValueError):
            tenant_mod.parse_tenants({"a": "half"})
        table = tenant_mod.parse_tenants({"a": {}})
        assert table["a"].high_priority and table["a"].share == 1.0

    def test_spec_for_standing(self):
        table = specs(acme=("low", 0.25))
        assert tenant_mod.spec_for("acme", table).share == 0.25
        default = tenant_mod.spec_for(tenant_mod.DEFAULT_TENANT, table)
        assert default.high_priority and default.share == 1.0
        unknown = tenant_mod.spec_for("never-declared", table)
        assert not unknown.high_priority
        assert unknown.share == tenant_mod.UNKNOWN_SHARE

    def test_quota_floors_at_one_and_caps_at_capacity(self):
        tiny = tenant_mod.TenantSpec(name="t", share=0.01)
        assert tenant_mod.quota_of(tiny, 8) == 1
        full = tenant_mod.TenantSpec(name="t", share=1.0)
        assert tenant_mod.quota_of(full, 8) == 8
        assert tenant_mod.quota_of(full, 0) == 0


# ---------------------------------------------------------------------------
# Wire threading: the `n` field across the fabric, and legacy frames
# ---------------------------------------------------------------------------


class TestTenantOnTheWire:
    def _serve_echo(self, net: SimRpcNetwork) -> None:
        net.serve("srv:1", {"job.echo": lambda p: {"tenant": tenant_mod.current()}})

    def test_frame_carries_n_and_server_rebinds(self):
        net = SimRpcNetwork()
        self._serve_echo(net)
        client = net.client("cli:0")
        with tenant_mod.bind("acme"):
            reply = client.call("srv:1", "job.echo", {})
        assert reply["tenant"] == "acme"
        assert net.frames[-1]["n"] == "acme"

    def test_default_tenant_frames_are_byte_identical_legacy(self):
        # No tenant bound -> no `n` field at all: tenancy disabled costs
        # zero frame bytes and old peers never see a new field.
        net = SimRpcNetwork()
        self._serve_echo(net)
        reply = net.client("cli:0").call("srv:1", "job.echo", {})
        assert reply["tenant"] == tenant_mod.DEFAULT_TENANT
        assert "n" not in net.frames[-1]

    def test_legacy_frame_without_n_on_mixed_version_fleet(self):
        # A pre-tenancy peer's frame never carries `n`; the new server must
        # read it as the default tenant at full share, not refuse it.
        from dmlc_tpu.cluster.rpc import serve_with_deadline

        seen = {}

        def method(p):
            seen["tenant"] = tenant_mod.current()
            return {"ok": True}

        serve_with_deadline({"job.x": method}, "job.x", {}, 5.0,
                            clock=time.monotonic)
        assert seen["tenant"] == tenant_mod.DEFAULT_TENANT

        gate = AdmissionGate(2, 0, "legacy", tenants=specs(acme=("low", 0.5)))
        with gate.admit():
            pass  # the default tenant admits at full share on a quota fleet
        assert gate.sheds == 0

    def test_overloaded_reply_carries_tenant_and_verdict(self):
        net = SimRpcNetwork()
        gate = AdmissionGate(4, 0, "door", tenants=specs(acme=("low", 0.25)))

        def congested(p):
            with gate.admit():
                return {}

        net.serve("srv:1", {"job.x": congested})
        client = net.client("cli:0")
        with tenant_mod.bind("acme"):
            with gate.admit():  # acme holds its whole quota (1 of 4 slots)
                with pytest.raises(Overloaded) as e:
                    client.call("srv:1", "job.x", {})
        # The typed verdict survives the remote-error round trip.
        assert e.value.tenant == "acme"
        assert e.value.quota == "over_quota"


# ---------------------------------------------------------------------------
# AdmissionGate quota edges
# ---------------------------------------------------------------------------


class TestGateQuotaEdges:
    def test_at_quota_admits_one_past_sheds_typed(self):
        # capacity 4, share 0.5 -> quota 2: both quota tokens must admit,
        # the third shed must be typed over_quota with the gate NOT full.
        metrics = Counters()
        gate = AdmissionGate(
            4, 0, "predict", metrics=metrics, tenants=specs(acme=("low", 0.5))
        )
        with tenant_mod.bind("acme"):
            with gate.admit(), gate.admit():
                assert gate.ledger.active("acme") == gate.ledger.quota("acme") == 2
                with pytest.raises(Overloaded) as e:
                    with gate.admit():
                        pass
        assert e.value.quota == "over_quota"
        assert e.value.tenant == "acme"
        assert e.value.retry_after_s is not None
        assert gate.active == 0  # releases balanced
        assert metrics.get("shed_over_quota_predict") == 1

    def test_surge_exhausts_own_quota_not_the_door(self):
        # acme at quota must not stop the default tenant: the door still
        # has tokens and the default tenant's share is the full capacity.
        gate = AdmissionGate(4, 0, "predict", tenants=specs(acme=("low", 0.25)))
        with tenant_mod.bind("acme"):
            ctx = gate.admit()
            ctx.__enter__()
            with pytest.raises(Overloaded):
                with gate.admit():
                    pass
        try:
            with gate.admit():  # default tenant sails through
                pass
        finally:
            with tenant_mod.bind("acme"):
                ctx.__exit__(None, None, None)

    def test_gate_full_verdict_when_capacity_exhausted(self):
        gate = AdmissionGate(1, 0, "predict", tenants=specs(acme=("high", 1.0)))
        with gate.admit():
            with tenant_mod.bind("acme"):
                with pytest.raises(Overloaded) as e:
                    with gate.admit():
                        pass
        assert e.value.quota == "gate_full"
        assert e.value.tenant == "acme"

    def test_unknown_tenant_rides_the_residual_share(self):
        # An undeclared name gets UNKNOWN_SHARE, not a blackhole: with
        # capacity 10 that is one token — admitted — and the second sheds.
        gate = AdmissionGate(10, 0, "predict", tenants=specs(acme=("low", 0.5)))
        with tenant_mod.bind("who-is-this"):
            with gate.admit():
                with pytest.raises(Overloaded) as e:
                    with gate.admit():
                        pass
        assert e.value.quota == "over_quota"
        assert gate.ledger.summary()["who-is-this"]["over_quota_sheds"] == 1

    def test_no_tenants_configured_is_legacy(self):
        gate = AdmissionGate(2, 0, "predict")
        assert not gate.ledger.enforcing
        with tenant_mod.bind("acme"):
            with gate.admit():
                pass  # accounting only, no quota refusals possible
        assert gate.sheds == 0
        assert gate.ledger.quota("acme") == gate.capacity


# ---------------------------------------------------------------------------
# DynamicBatcher quota edges + displacement ordering
# ---------------------------------------------------------------------------


class TestBatcherQuotaEdges:
    def _blocked_batcher(self, release: threading.Event, **kw) -> DynamicBatcher:
        def predict(synsets):
            release.wait(timeout=10.0)
            return [0] * len(synsets)

        return DynamicBatcher(predict, batch_size=4, max_wait_s=0.005,
                              max_queue=8, **kw)

    @staticmethod
    def _drain_first_batch(b: DynamicBatcher) -> None:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with b._cv:
                if not b._queue:
                    return
            time.sleep(0.001)
        raise AssertionError("worker never picked up the priming batch")

    def test_quota_edge_and_displacement_ordering(self):
        release = threading.Event()
        b = self._blocked_batcher(
            release, tenants=specs(acme=("low", 0.2), beta=("high", 1.0))
        )
        try:
            # Prime: one full batch rides into the blocked backend, so the
            # queue state below is frozen and deterministic.
            primed = [b.submit(f"p{i}") for i in range(4)]
            self._drain_first_batch(b)

            # quota(acme) = max(1, int(0.2 * 8)) = 1: AT quota admits...
            with tenant_mod.bind("acme"):
                acme_fut = b.submit("acme0")
                # ... one past quota sheds typed, queue NOT full (1/8).
                with pytest.raises(Overloaded) as e:
                    b.submit("acme1")
            assert e.value.quota == "over_quota"
            assert e.value.tenant == "acme"

            # Fill the bounded queue with default work: 7 more -> 8/8.
            filler = [b.submit(f"f{i}") for i in range(7)]
            # Full queue + every resident within quota: a high-priority
            # submit must NOT displace within-quota work — typed gate_full.
            with tenant_mod.bind("beta"):
                with pytest.raises(Overloaded) as e:
                    b.submit("beta0")
            assert e.value.quota == "gate_full"

            # Push acme over quota (a shrunken share mid-flight), then the
            # same high-priority submit displaces acme's NEWEST queued item
            # — low-priority-and-over-quota first, never the default work.
            b.ledger.acquire("acme")
            with tenant_mod.bind("beta"):
                beta_fut = b.submit("beta1")
            with pytest.raises(Overloaded) as displaced:
                acme_fut.result(timeout=5.0)
            assert displaced.value.quota == "over_quota"
            assert displaced.value.tenant == "acme"

            release.set()
            assert [f.result(timeout=10.0) for f in primed] == [0] * 4
            assert [f.result(timeout=10.0) for f in filler] == [0] * 7
            assert beta_fut.result(timeout=10.0) == 0
            tenants = b.summary()["tenants"]
            assert tenants["acme"]["over_quota_sheds"] == 2
        finally:
            release.set()
            b.stop()

    def test_batcher_without_bound_never_enforces(self):
        release = threading.Event()
        release.set()
        b = DynamicBatcher(lambda s: [0] * len(s), batch_size=2)
        try:
            with tenant_mod.bind("acme"):
                assert b.submit("x").result(timeout=5.0) == 0
            assert not b.ledger.enforcing
        finally:
            b.stop()
