"""Test harness: force an 8-device virtual CPU platform BEFORE jax import.

Multi-chip hardware is not available in CI; all sharding/collective tests run
on a virtual 8-device CPU mesh (jax's xla_force_host_platform_device_count),
which exercises the same pjit/shard_map partitioning logic the TPU pod path
uses. Real-TPU execution is covered by bench.py on the driver side.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Make the repo root importable regardless of how pytest was invoked.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# The environment's sitecustomize registers a remote-TPU PJRT plugin and
# force-selects it via jax.config.update("jax_platforms", "axon,cpu") at
# interpreter startup, which overrides the JAX_PLATFORMS env var and makes the
# first backend touch block on the TPU tunnel. Tests must run hermetically on
# the virtual CPU mesh, so explicitly select cpu at the config level too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache (keyed by platform+HLO, shared with bench.py's
# TPU entries without collision): the suite's dominant cost is XLA compiles,
# and a warm cache cuts reruns from minutes to seconds.
from dmlc_tpu.utils import compile_cache  # noqa: E402

compile_cache.enable()

# Build the native data-plane library once (best effort) so its tests run
# against the real .so; the library is a gitignored build artifact.
try:
    from dmlc_tpu import native as _native  # noqa: E402

    _native.ensure_built()
except Exception:  # dmlc-lint: disable=E1 -- best-effort: tests that need the .so skip on native.available(), everything else must still collect
    pass
