"""Seeded randomized fault-injection ("chaos") soaks on the sim fabrics.

The targeted tests script ONE fault each (a crash, a partition, a failover);
these soaks search the space the reference validated with 6 manual VM-kill
trials (SURVEY.md §4): a seeded RNG drives dozens of interleaved crashes,
restarts, and partitions, and the invariants that must hold are checked at
quiescence. Deterministic per seed — a failing seed replays exactly.

Invariants under chaos:
- membership: once faults stop, every live node converges to the SAME view,
  every live node is ACTIVE in it, every dead node non-ACTIVE.
- scheduler: every job finishes every query EXACTLY once (no loss on member
  crash, no double-count on retry), with correctness still judged per query.
"""

from __future__ import annotations

import os
import random

import pytest

from test_membership import SimCluster
from test_scheduler import Fixture

# CI runs this suite as a seed MATRIX (tools/ci_check.sh): the base offsets
# every parametrized seed range, so each matrix leg searches a disjoint
# region of the fault space while any single failing seed still replays
# exactly (env DMLC_CHAOS_SEED=<base> pytest tests/test_chaos.py).
SEED_BASE = int(os.environ.get("DMLC_CHAOS_SEED", "0"))


def seeds(n: int) -> range:
    return range(SEED_BASE, SEED_BASE + n)


@pytest.mark.parametrize("seed", seeds(6))
def test_membership_chaos_converges(seed):
    rng = random.Random(seed)
    c = SimCluster(12, ring_k=3)
    c.rounds(3)  # settle the bootstrap
    introducer = "node0:8850"
    crashed: set = set()

    for _ in range(40):
        roll = rng.random()
        alive = [a for a in c.nodes if a not in crashed]
        if roll < 0.15 and len(alive) > 7:
            victim = rng.choice([a for a in alive if a != introducer])
            c.net.crash(victim)
            crashed.add(victim)
        elif roll < 0.25 and crashed:
            back = rng.choice(sorted(crashed))
            crashed.discard(back)
            c.net.restart(back)
            c.nodes[back].join(introducer)
        elif roll < 0.35:
            a, b = rng.sample(sorted(c.nodes), 2)
            c.net.partition(a, b)
        elif roll < 0.45:
            for a, b in list(c.net.cut):
                c.net.heal(a, b)
        c.round()

    # Quiesce: heal everything, let anti-entropy finish.
    for a, b in list(c.net.cut):
        c.net.heal(a, b)
    c.rounds(20)

    alive = sorted(a for a in c.nodes if a not in crashed)
    views = {a: c.statuses_seen_by(a) for a in alive}
    for viewer, view in views.items():
        for a in alive:
            assert view[a] == "active", f"{viewer} sees live {a} as {view[a]} (seed {seed})"
        for a in crashed:
            assert view.get(a, "failed") != "active", (
                f"{viewer} sees dead {a} as active (seed {seed})"
            )
    # Full agreement: anti-entropy must drive every live view identical.
    first = views[alive[0]]
    for viewer, view in views.items():
        assert view == first, f"{viewer} diverges from {alive[0]} (seed {seed})"


class TestIndirectProbes:
    """SWIM ping-req/ack2: link loss is not node death."""

    def test_partitioned_pair_stays_active_via_helpers(self):
        """Cut ONLY the a<->b link (every other path intact): with indirect
        probes, liveness evidence relays through helpers and neither node
        ever falsely FAILS the other."""
        c = SimCluster(6, ring_k=2)
        c.rounds(3)
        a, b = "node1:8850", "node2:8850"  # ring-adjacent (sorted ids)
        events: list = []
        c.nodes[a].on_change = lambda nid, m: events.append((nid[0], m.status.value))
        c.nodes[b].on_change = lambda nid, m: events.append((nid[0], m.status.value))
        c.net.partition(a, b)
        c.rounds(10)
        # Not even a TRANSIENT false verdict in either direction.
        assert (b, "failed") not in events and (a, "failed") not in events
        assert c.statuses_seen_by(a)[b] == "active"
        assert c.statuses_seen_by(b)[a] == "active"

    def test_without_probes_link_loss_is_misread_as_death(self):
        """The same scenario with indirect_probes=0 (the reference's
        direct-only detector) false-positives — the behavior the probes
        exist to fix."""
        c = SimCluster(6, ring_k=2, indirect_probes=0)
        c.rounds(3)
        a, b = "node1:8850", "node2:8850"
        events: list = []
        c.nodes[a].on_change = lambda nid, m: events.append((nid[0], m.status.value))
        c.net.partition(a, b)
        c.rounds(10)
        # The direct-only detector repeatedly (falsely) fails the peer; the
        # verdict flaps because helpers' gossip resurrects it each round.
        assert (b, "failed") in events

    def test_crashed_node_still_detected_with_probes_on(self):
        """Indirect probing must not mask real death: helpers get no acks
        from a crashed node, so the timeout verdict stands."""
        c = SimCluster(6, ring_k=2)
        c.rounds(3)
        victim = "node3:8850"
        c.net.crash(victim)
        c.rounds(8)
        for viewer in c.nodes:
            if viewer != victim:
                assert c.statuses_seen_by(viewer)[victim] == "failed"


@pytest.mark.parametrize("seed", seeds(3))
def test_leader_churn_chaos_exactly_once(seed):
    """Repeated leader kill -> standby promote -> resume cycles, with random
    progress between each: however many times leadership churns, every query
    is counted exactly once and the final leader finishes the workload (the
    reference's failover scenario, iterated instead of tried once)."""
    from dmlc_tpu.scheduler.jobs import JobScheduler
    from dmlc_tpu.cluster.failover import StandbyLeader

    rng = random.Random(seed)
    n_queries = 160
    f = Fixture(n_members=6, n_queries=n_queries, shard=16)
    candidates = [f"L{i}" for i in range(4)]  # distinct from the Fixture's "L"

    # Build a chain of candidate schedulers, all serving on the same fabric.
    def make_candidate(addr):
        sched = JobScheduler(
            f.net.client(addr),
            lambda: list(f.live),
            jobs={
                "resnet18": [(f"n{i:05d}", i) for i in range(n_queries)],
                "alexnet": [(f"n{i:05d}", i) for i in range(n_queries)],
            },
            shard_size=16,
            timer=f._fake_timer(),
        )
        f.net.serve(addr, sched.methods())
        monitor = StandbyLeader(f.net.client(addr), addr, candidates, sched)
        return sched, monitor

    chain = {addr: make_candidate(addr) for addr in candidates}
    # First candidate claims leadership and starts the jobs.
    chain[candidates[0]][1].step()
    assert chain[candidates[0]][1].is_leader
    chain[candidates[0]][0]._start({})

    alive = list(candidates)
    leader = candidates[0]
    for _ in range(len(candidates) - 1):
        sched = chain[leader][0]
        sched.assign_once()
        # Random amount of progress under the current leader.
        for _ in range(rng.randrange(1, 6)):
            sched.dispatch_all_once()
        # Standbys sync from the live leader, then the leader dies.
        for addr in alive:
            if addr != leader:
                chain[addr][1].step()
        f.net.crash(leader)
        alive.remove(leader)
        # The next live candidate notices and promotes (auto-resume).
        for addr in alive:
            chain[addr][1].step()
        new_leader = next(a for a in alive if chain[a][1].is_leader)
        assert new_leader != leader
        leader = new_leader

    final = chain[leader][0]
    final.assign_once()
    final.run_to_completion()
    for name, job in final.jobs.items():
        assert job.finished == n_queries, f"{name}: {job.finished} (seed {seed})"
        assert job.correct == n_queries, f"{name} lost/duplicated (seed {seed})"


def test_split_brain_puts_fenced_by_epochs(tmp_path):
    """THE double-lead scenario (VERDICT r2 weak #5): partition the two
    leader candidates, drive puts at BOTH claimants, heal. Epoch fencing
    must guarantee: the stale claimant's put is REFUSED (never acked), the
    newer term's put lands, on heal exactly one leader remains, and every
    acked version's bytes are intact — no acked write silently replaced."""
    from dmlc_tpu.cluster.failover import StandbyLeader
    from dmlc_tpu.cluster.rpc import RpcError, SimRpcNetwork
    from dmlc_tpu.cluster.sdfs import MemberStore, SdfsClient, SdfsLeader, SdfsMember
    from dmlc_tpu.scheduler.jobs import JobScheduler

    net = SimRpcNetwork()
    live = ["m0", "m1", "m2"]
    stores = {}
    for m in live:
        stores[m] = MemberStore(tmp_path / m)
        net.serve(m, SdfsMember(stores[m], net.client(m)).methods())

    def make_candidate(addr):
        sdfs = SdfsLeader(
            net.client(addr), lambda: list(live), replication_factor=2, is_leading=False
        )
        sched = JobScheduler(net.client(addr), lambda: list(live), jobs={})
        net.serve(addr, {**sdfs.methods(), **sched.methods()})
        monitor = StandbyLeader(net.client(addr), addr, ["L0", "L1"], sched, sdfs_leader=sdfs)
        return sdfs, sched, monitor

    sdfs0, _, mon0 = make_candidate("L0")
    sdfs1, _, mon1 = make_candidate("L1")
    mon0.step()
    mon1.step()
    assert mon0.is_leader and not mon1.is_leader

    client = lambda leader: SdfsClient(net.client("m0"), leader, stores["m0"], "m0")
    assert client("L0").put_bytes(b"term1-bytes", "f")["version"] == 1

    # --- partition the candidates; the standby promotes a NEWER term -----
    net.partition("L0", "L1")
    mon1.step()
    assert mon1.is_leader, "standby must promote when the leader is unreachable"
    assert mon0.is_leader, "old leader cannot see the new term yet"

    # Stale claimant's put: every member is fenced at L1's term, so the
    # write is refused — the client gets an ERROR, not a doomed ack.
    with pytest.raises(RpcError):
        client("L0").put_bytes(b"stale-claimant-bytes", "f")
    # The winning term's put is acked.
    reply = client("L1").put_bytes(b"term2-bytes", "f")
    v2 = reply["version"]
    assert v2 > 1 and len(reply["replicas"]) == 2

    # --- heal: the older term observes the newer one and abdicates -------
    net.heal("L0", "L1")
    mon0.step()
    assert not mon0.is_leader and mon1.is_leader, "exactly one leader after heal"
    assert sdfs0.state.to_wire() == sdfs1.state.to_wire(), "directories converged"

    # Every acked version is intact and serves its own bytes.
    assert client("L1").get_bytes("f", version=1)[1] == b"term1-bytes"
    assert client("L1").get_bytes("f", version=v2)[1] == b"term2-bytes"
    # The refused put left nothing behind: no member store holds bytes the
    # directory doesn't know about.
    for m, store in stores.items():
        for name, versions in store.listing().items():
            for v in versions:
                assert m in sdfs1.state.replicas_of(name, v), (m, name, v)
                assert store.read(name, v) in (b"term1-bytes", b"term2-bytes")


@pytest.mark.parametrize("seed", seeds(4))
def test_scheduler_chaos_exactly_once(seed):
    rng = random.Random(seed)
    n_queries = 200
    fx = Fixture(n_members=8, n_queries=n_queries, shard=16, accuracy=1.0)
    fx.scheduler._start({})
    crashed: list = []

    for step in range(10_000):
        if all(j.done for j in fx.scheduler.jobs.values()):
            break
        roll = rng.random()
        if roll < 0.03 and len(fx.live) > 2:
            victim = rng.choice(fx.live)
            fx.crash(victim)
            crashed.append(victim)
        elif roll < 0.06 and crashed:
            back = crashed.pop(rng.randrange(len(crashed)))
            fx.net.restart(back)
            fx.live.append(back)
        if step % 5 == 0:  # periodic reassignment, as the node's loop does
            fx.scheduler.assign_once()
        fx.scheduler.dispatch_all_once()
    else:
        pytest.fail(f"jobs never completed under chaos (seed {seed})")

    for name, job in fx.scheduler.jobs.items():
        assert job.finished == n_queries, f"{name}: {job.finished}/{n_queries} (seed {seed})"
        assert job.correct == n_queries, f"{name} lost/duplicated work (seed {seed})"
        assert not job.running and not job.outstanding and not job.retry_q
