"""Seeded randomized fault-injection ("chaos") soaks on the sim fabrics.

The targeted tests script ONE fault each (a crash, a partition, a failover);
these soaks search the space the reference validated with 6 manual VM-kill
trials (SURVEY.md §4): a seeded RNG drives dozens of interleaved crashes,
restarts, and partitions, and the invariants that must hold are checked at
quiescence. Deterministic per seed — a failing seed replays exactly.

Invariants under chaos:
- membership: once faults stop, every live node converges to the SAME view,
  every live node is ACTIVE in it, every dead node non-ACTIVE.
- scheduler: every job finishes every query EXACTLY once (no loss on member
  crash, no double-count on retry), with correctness still judged per query.
"""

from __future__ import annotations

import random

import pytest

from test_membership import SimCluster
from test_scheduler import Fixture


@pytest.mark.parametrize("seed", range(6))
def test_membership_chaos_converges(seed):
    rng = random.Random(seed)
    c = SimCluster(12, ring_k=3)
    c.rounds(3)  # settle the bootstrap
    introducer = "node0:8850"
    crashed: set = set()

    for _ in range(40):
        roll = rng.random()
        alive = [a for a in c.nodes if a not in crashed]
        if roll < 0.15 and len(alive) > 7:
            victim = rng.choice([a for a in alive if a != introducer])
            c.net.crash(victim)
            crashed.add(victim)
        elif roll < 0.25 and crashed:
            back = rng.choice(sorted(crashed))
            crashed.discard(back)
            c.net.restart(back)
            c.nodes[back].join(introducer)
        elif roll < 0.35:
            a, b = rng.sample(sorted(c.nodes), 2)
            c.net.partition(a, b)
        elif roll < 0.45:
            for a, b in list(c.net.cut):
                c.net.heal(a, b)
        c.round()

    # Quiesce: heal everything, let anti-entropy finish.
    for a, b in list(c.net.cut):
        c.net.heal(a, b)
    c.rounds(20)

    alive = sorted(a for a in c.nodes if a not in crashed)
    views = {a: c.statuses_seen_by(a) for a in alive}
    for viewer, view in views.items():
        for a in alive:
            assert view[a] == "active", f"{viewer} sees live {a} as {view[a]} (seed {seed})"
        for a in crashed:
            assert view.get(a, "failed") != "active", (
                f"{viewer} sees dead {a} as active (seed {seed})"
            )
    # Full agreement: anti-entropy must drive every live view identical.
    first = views[alive[0]]
    for viewer, view in views.items():
        assert view == first, f"{viewer} diverges from {alive[0]} (seed {seed})"


@pytest.mark.parametrize("seed", range(4))
def test_scheduler_chaos_exactly_once(seed):
    rng = random.Random(seed)
    n_queries = 200
    fx = Fixture(n_members=8, n_queries=n_queries, shard=16, accuracy=1.0)
    fx.scheduler._start({})
    crashed: list = []

    for step in range(10_000):
        if all(j.done for j in fx.scheduler.jobs.values()):
            break
        roll = rng.random()
        if roll < 0.03 and len(fx.live) > 2:
            victim = rng.choice(fx.live)
            fx.crash(victim)
            crashed.append(victim)
        elif roll < 0.06 and crashed:
            back = crashed.pop(rng.randrange(len(crashed)))
            fx.net.restart(back)
            fx.live.append(back)
        if step % 5 == 0:  # periodic reassignment, as the node's loop does
            fx.scheduler.assign_once()
        fx.scheduler.dispatch_all_once()
    else:
        pytest.fail(f"jobs never completed under chaos (seed {seed})")

    for name, job in fx.scheduler.jobs.items():
        assert job.finished == n_queries, f"{name}: {job.finished}/{n_queries} (seed {seed})"
        assert job.correct == n_queries, f"{name} lost/duplicated work (seed {seed})"
        assert not job.running and not job.outstanding and not job.retry_q
