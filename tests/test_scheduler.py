"""Scheduler + failover on the deterministic sim fabric: fair assignment,
shard dispatch with exactly-once counting, member failure retry, leader
failover with cursor resume (the reference's report §2-3 scenarios, scripted).
"""

import pytest

from dmlc_tpu.cluster.failover import LeaderTracker, StandbyLeader
from dmlc_tpu.cluster.rpc import SimRpcNetwork
from dmlc_tpu.scheduler.jobs import JobScheduler
from dmlc_tpu.scheduler.worker import PredictWorker


def make_workload(n, prefix="n", offset=0):
    return [(f"{prefix}{i:05d}", offset + i) for i in range(n)]


class Fixture:
    """N members serving fake model backends + a leader scheduler."""

    def __init__(self, n_members=10, n_queries=100, shard=16, accuracy=1.0):
        self.net = SimRpcNetwork()
        self.live = [f"m{i}" for i in range(n_members)]
        self.calls = {m: 0 for m in self.live}  # shards served per member

        def backend_for(member, correct_frac):
            def fn(synsets):
                self.calls[member] += 1
                out = []
                for k, s in enumerate(synsets):
                    truth = int(s[1:])
                    # Deterministically wrong for a fraction of queries.
                    wrong = (truth % 100) >= correct_frac * 100
                    out.append(truth + 1 if wrong else truth)
                return out

            return fn

        for m in self.live:
            worker = PredictWorker(
                {
                    "resnet18": backend_for(m, accuracy),
                    "alexnet": backend_for(m, accuracy),
                }
            )
            self.net.serve(m, worker.methods())

        self.scheduler = JobScheduler(
            self.net.client("L"),
            lambda: list(self.live),
            jobs={
                "resnet18": make_workload(n_queries),
                "alexnet": make_workload(n_queries),
            },
            shard_size=shard,
            timer=self._fake_timer(),
        )
        self.scheduler.is_leading = True  # fixture models the active leader
        self.net.serve("L", self.scheduler.methods())

    def _fake_timer(self):
        t = [0.0]

        def timer():
            t[0] += 0.005
            return t[0]

        return timer

    def crash(self, m):
        self.live.remove(m)
        self.net.crash(m)


def test_assignment_splits_members_evenly():
    f = Fixture()
    f.net.client("cli").call("L", "job.start", {})
    assigned = f.net.client("cli").call("L", "job.assignments", {})["assigned"]
    assert len(assigned["resnet18"]) == 5
    assert len(assigned["alexnet"]) == 5
    assert not set(assigned["resnet18"]) & set(assigned["alexnet"])


def test_run_to_completion_and_report():
    f = Fixture(n_queries=100, shard=16, accuracy=1.0)
    f.scheduler._start({})
    f.scheduler.run_to_completion()
    rep = f.net.client("cli").call("L", "job.report", {})["jobs"]
    for name in ("resnet18", "alexnet"):
        r = rep[name]
        assert r["finished"] == r["total"] == 100
        assert r["accuracy"] == 1.0
        assert not r["running"]
        for k in ("mean", "median", "p90", "p95", "p99", "std"):
            assert k in r["query_latency"] and k in r["shard_latency"]
        # Completed work over the fake timer's dispatch window.
        assert r["throughput_qps"] > 0
    # Work spread across members: every member served at least one shard.
    assert all(c > 0 for c in f.calls.values())


def test_partial_accuracy_counted_exactly():
    f = Fixture(n_queries=100, shard=10, accuracy=0.7)
    f.scheduler._start({})
    f.scheduler.run_to_completion()
    job = f.scheduler.jobs["resnet18"]
    assert job.finished == 100
    assert job.correct == 70  # truths 0..99, wrong for (truth % 100) >= 70


def test_member_crash_mid_run_retries_without_double_count():
    f = Fixture(n_members=4, n_queries=64, shard=16)
    f.scheduler._start({})
    f.scheduler.assign_once()
    assert f.scheduler.dispatch_once("resnet18") == 16
    f.crash(f.scheduler.jobs["resnet18"].assigned[1 % len(f.scheduler.jobs["resnet18"].assigned)])
    f.scheduler.run_to_completion()
    job = f.scheduler.jobs["resnet18"]
    assert job.finished == 64  # exactly once, despite the failed dispatch
    assert job.correct == 64
    assert f.scheduler.jobs["alexnet"].finished == 64


def test_idle_scheduler_dispatches_nothing():
    f = Fixture()
    assert f.scheduler.dispatch_all_once() == 0  # predict never issued
    assert f.scheduler.jobs["resnet18"].finished == 0


def test_leader_tracker_advances_and_wraps():
    net = SimRpcNetwork()
    leading = {"L0": True, "L1": True, "L2": True}
    for addr in ("L0", "L1", "L2"):
        net.serve(addr, {"leader.status": (lambda a: lambda p: {"leading": leading[a]})(addr)})
    t = LeaderTracker(net.client("m"), ["L0", "L1", "L2"])
    assert t.probe() and t.current == "L0"
    net.crash("L0")
    assert not t.probe()  # advance to L1
    assert t.probe() and t.current == "L1"
    net.crash("L1")
    net.crash("L2")
    assert not t.probe()  # -> L2
    assert not t.probe()  # -> L0 (wrap)
    assert t.current == "L0"
    net.restart("L0")
    assert t.probe()
    # Alive-but-deferring candidates are skipped too, not just dead ones.
    leading["L0"] = False
    assert not t.probe()
    assert t.current == "L1"


def test_failover_resumes_from_cursor():
    f = Fixture(n_members=6, n_queries=80, shard=16)
    f.scheduler.is_leading = True  # primary actively leads
    f.scheduler._start({})
    f.scheduler.assign_once()
    # Primary completes 2 shards of each job, then standby syncs.
    for _ in range(2):
        f.scheduler.dispatch_once("resnet18")
        f.scheduler.dispatch_once("alexnet")
    standby = JobScheduler(
        f.net.client("L1"),
        lambda: list(f.live),
        jobs={"resnet18": make_workload(80), "alexnet": make_workload(80)},
        shard_size=16,
        timer=f._fake_timer(),
    )
    monitor = StandbyLeader(f.net.client("L1"), "L1", ["L", "L1"], standby)
    monitor.step()  # mirrors primary state
    assert standby.jobs["resnet18"].finished == 32
    assert not monitor.is_leader

    shards_before = dict(f.calls)
    f.net.crash("L")
    monitor.step()  # primary dead -> promote + auto-resume
    assert monitor.is_leader
    assert standby.jobs["resnet18"].running
    standby.run_to_completion()
    for name in ("resnet18", "alexnet"):
        assert standby.jobs[name].finished == 80
        assert standby.jobs[name].correct == 80
    # Resume really started at the cursor: exactly (80-32)/16 = 3 more shards
    # per job were served cluster-wide.
    extra = sum(f.calls.values()) - sum(shards_before.values())
    assert extra == 6


def test_adopt_state_never_rewinds():
    f = Fixture(n_queries=64, shard=16)
    f.scheduler._start({})
    f.scheduler.assign_once()
    f.scheduler.dispatch_once("resnet18")
    f.scheduler.dispatch_once("resnet18")
    stale = {
        "jobs": {
            "resnet18": {
                "model": "resnet18",
                "finished": 16,
                "correct": 16,
                "running": True,
                "query_samples": [],
                "shard_samples": [],
            }
        }
    }
    f.scheduler.adopt_state(stale)
    assert f.scheduler.jobs["resnet18"].finished == 32  # stale snapshot ignored


def test_rebooted_ex_leader_defers_to_active_leader():
    """A restarted first-candidate must NOT reclaim leadership while another
    candidate actively leads (the dual-leader bug)."""
    net = SimRpcNetwork()
    live = ["m0", "m1"]
    active = JobScheduler(net.client("L1"), lambda: list(live), jobs={"j": make_workload(8)})
    active.is_leading = True
    net.serve("L1", active.methods())
    rebooted = JobScheduler(net.client("L0"), lambda: list(live), jobs={"j": make_workload(8)})
    net.serve("L0", rebooted.methods())
    monitor = StandbyLeader(net.client("L0"), "L0", ["L0", "L1"], rebooted)
    monitor.step()
    assert not monitor.is_leader  # defers despite being first in the list
    # Only once the active leader dies does the rebooted one take over.
    net.crash("L1")
    monitor.step()
    assert monitor.is_leader


def test_standby_mirrors_sdfs_directory(tmp_path):
    """Failover must not orphan the SDFS directory (files + versions)."""
    from dmlc_tpu.cluster.sdfs import MemberStore, SdfsClient, SdfsLeader, SdfsMember

    net = SimRpcNetwork()
    live = ["m0", "m1", "m2"]
    stores = {}
    for m in live:
        store = MemberStore(tmp_path / m)
        net.serve(m, SdfsMember(store, net.client(m)).methods())
        stores[m] = store
    primary_sdfs = SdfsLeader(net.client("L0"), lambda: list(live), replication_factor=2)
    primary_jobs = JobScheduler(net.client("L0"), lambda: list(live), jobs={})
    primary_jobs.is_leading = True
    net.serve("L0", {**primary_sdfs.methods(), **primary_jobs.methods()})

    client = SdfsClient(net.client("m0"), "L0", stores["m0"], "m0")
    client.put_bytes(b"v1", "w")
    client.put_bytes(b"v2", "w")

    standby_sdfs = SdfsLeader(net.client("L1"), lambda: list(live), replication_factor=2)
    standby_jobs = JobScheduler(net.client("L1"), lambda: list(live), jobs={})
    net.serve("L1", {**standby_sdfs.methods(), **standby_jobs.methods()})
    monitor = StandbyLeader(net.client("L1"), "L1", ["L0", "L1"], standby_jobs, sdfs_leader=standby_sdfs)
    monitor.step()  # mirrors directory
    assert standby_sdfs.state.latest_version("w") == 2

    net.crash("L0")
    monitor.step()
    assert monitor.is_leader
    # Post-failover: get resolves, and a new put gets v3, never recycles v1.
    client.leader_addr = "L1"
    v, data = client.get_bytes("w")
    assert (v, data) == (2, b"v2")
    assert client.put_bytes(b"v3", "w")["version"] == 3


# ---------------------------------------------------------------------------
# Concurrent dispatch (round-2: up to W shards in flight per job)
# ---------------------------------------------------------------------------

import threading
import time as _time


def _sim_members(net, live, backend):
    for m in live:
        net.serve(m, PredictWorker({"j": backend}).methods())


def echo_backend(synsets):
    return [int(s[1:]) for s in synsets]


def test_concurrent_dispatch_k_shards_in_flight():
    """4 dispatcher threads drive 4 members SIMULTANEOUSLY: every backend
    blocks on a barrier that only releases once all 4 have a shard in
    flight — completion is proof of 4-way concurrency, no timing needed."""
    net = SimRpcNetwork()
    live = [f"m{i}" for i in range(4)]
    barrier = threading.Barrier(4, timeout=10)

    def backend(synsets):
        barrier.wait()
        return echo_backend(synsets)

    _sim_members(net, live, backend)
    sched = JobScheduler(
        net.client("L"), lambda: list(live), jobs={"j": make_workload(64)}, shard_size=16
    )
    sched.is_leading = True
    sched._start({})
    threads = [threading.Thread(target=sched.dispatch_all_once) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    job = sched.jobs["j"]
    assert job.finished == 64 and job.correct == 64 and job.done
    assert not job.outstanding and not job.buffered and not job.retry_q


def test_concurrent_dispatch_completion_rate_scales():
    """K members x W workers with per-shard latency: wall time ~ serial/K."""
    net = SimRpcNetwork()
    live = [f"m{i}" for i in range(4)]
    delay = 0.03

    def backend(synsets):
        _time.sleep(delay)
        return echo_backend(synsets)

    _sim_members(net, live, backend)
    n_shards, shard = 16, 8
    sched = JobScheduler(
        net.client("L"),
        lambda: list(live),
        jobs={"j": make_workload(n_shards * shard)},
        shard_size=shard,
    )
    sched.is_leading = True
    sched._start({})

    def worker():
        while sched.has_dispatchable() or sched.jobs["j"].running:
            if sched.dispatch_all_once() == 0 and not sched.jobs["j"].running:
                return

    t0 = _time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    wall = _time.perf_counter() - t0
    serial = n_shards * delay
    job = sched.jobs["j"]
    assert job.finished == n_shards * shard and job.correct == job.finished
    assert wall < serial * 0.6, f"no speedup: wall={wall:.3f}s vs serial={serial:.3f}s"


def test_out_of_order_results_flush_as_contiguous_prefix():
    """Shard 0 completes AFTER shard 1: shard 1 buffers (finished stays 0,
    the durable cursor never skips a gap), then shard 0 flushes both."""
    net = SimRpcNetwork()
    gate = threading.Event()

    def slow(synsets):
        assert gate.wait(10)
        return echo_backend(synsets)

    net.serve("m0", PredictWorker({"j": slow}).methods())
    net.serve("m1", PredictWorker({"j": echo_backend}).methods())
    sched = JobScheduler(
        net.client("L"), lambda: ["m0", "m1"], jobs={"j": make_workload(16)}, shard_size=8
    )
    sched.is_leading = True
    sched._start({})
    job = sched.jobs["j"]
    assert job.assigned == ["m0", "m1"]

    t = threading.Thread(target=sched.dispatch_once, args=("j",))
    t.start()  # reserves offset 0 -> m0 (round-robin), blocks on the gate
    deadline = _time.monotonic() + 10
    while 0 not in job.outstanding and _time.monotonic() < deadline:
        _time.sleep(0.005)
    assert job.outstanding.get(0) == {"m0"}

    completed = sched.dispatch_once("j")  # offset 8 -> m1, completes first
    assert completed == 8  # completed work, but buffered behind the gap:
    assert job.finished == 0 and 8 in job.buffered  # cursor never skips

    gate.set()
    t.join(timeout=10)
    assert job.finished == 16 and job.correct == 16 and job.done


def test_failed_shard_retries_excluding_failed_member():
    net = SimRpcNetwork()

    def broken(synsets):
        raise RuntimeError("wedged accelerator")

    net.serve("m0", PredictWorker({"j": broken}).methods())
    net.serve("m1", PredictWorker({"j": echo_backend}).methods())
    sched = JobScheduler(
        net.client("L"), lambda: ["m0", "m1"], jobs={"j": make_workload(8)}, shard_size=8
    )
    sched.is_leading = True
    sched._start({})
    assert sched.dispatch_once("j") == 0  # m0 fails the shard
    job = sched.jobs["j"]
    assert job.retry_q and job.retry_q[0][0] == 0 and "m0" in job.retry_q[0][1]
    assert sched.dispatch_once("j") == 8  # retried on m1, not m0
    assert job.finished == 8 and job.correct == 8


def test_concurrent_crash_mid_run_keeps_exactly_once():
    """Members crash while 4 dispatcher threads are in flight: every query
    still counts exactly once."""
    net = SimRpcNetwork()
    live = [f"m{i}" for i in range(4)]

    def backend(synsets):
        _time.sleep(0.002)
        return echo_backend(synsets)

    _sim_members(net, live, backend)
    total = 64 * 8
    sched = JobScheduler(
        net.client("L"), lambda: list(live), jobs={"j": make_workload(total)}, shard_size=8
    )
    sched.is_leading = True
    sched._start({})

    def worker():
        while True:
            sched.assign_once()
            if sched.dispatch_all_once() == 0 and not sched.jobs["j"].running:
                return

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    _time.sleep(0.05)
    net.crash("m2")
    live.remove("m2")
    _time.sleep(0.05)
    net.crash("m0")
    live.remove("m0")
    for t in threads:
        t.join(timeout=30)
    job = sched.jobs["j"]
    assert job.finished == total
    assert job.correct == total  # exactly once: no double counts, no losses


def test_tail_hedging_backs_up_stragglers():
    """Once fresh shards run out, idle dispatchers re-send the oldest
    outstanding shard to a DIFFERENT member; whichever answer lands first
    counts, the other is a dedup'd no-op — exactly once either way."""
    f = Fixture(n_members=4, n_queries=32, shard=16)
    f.scheduler._start({})
    job = f.scheduler.jobs["resnet18"]
    # Latency evidence: hedging is gated on 2x the observed median shard
    # latency (no evidence -> no hedge). The fake timer advances 5 ms per
    # call, so anything beyond a 2 ms threshold is "slow".
    for _ in range(5):
        job.shard_stats.record(0.001)

    # Reserve both fresh shards without completing them (in flight).
    first = f.scheduler.next_shard("resnet18")
    second = f.scheduler.next_shard("resnet18")
    assert first is not None and second is not None
    assert job.next_offset >= len(job.queries)

    # Next reservation is a HEDGE of the oldest outstanding offset, on a
    # member other than the original assignee.
    hedge = f.scheduler.next_shard("resnet18")
    assert hedge is not None
    h_member, h_offset, h_shard, h_excluded = hedge
    assert h_offset == first[1]
    assert h_member != first[0] and first[0] in h_excluded
    # Two copies in flight max: the next idle reservation hedges the OTHER
    # shard, and after that there is nothing left to hand out.
    hedge2 = f.scheduler.next_shard("resnet18")
    assert hedge2 is not None and hedge2[1] == second[1]
    assert f.scheduler.next_shard("resnet18") is None

    # Hedge answer lands first and counts; the straggler's late answer is a
    # duplicate no-op.
    preds = [int(s[1:]) for s, _ in h_shard]
    assert f.scheduler._record_result(job, h_offset, h_shard, preds, 0.1, h_member) == len(h_shard)
    assert f.scheduler._record_result(job, first[1], h_shard, preds, 9.9, first[0]) == 0
    assert job.finished == len(h_shard) and job.correct == len(h_shard)


def test_hedge_failure_bookkeeping_keeps_other_copy_alive():
    """One copy failing must not forget the other in-flight copy, must not
    requeue while it lives, and a later requeue excludes every member that
    failed the shard."""
    f = Fixture(n_members=8, n_queries=16, shard=16)  # 4 assigned per job
    f.scheduler._start({})
    job = f.scheduler.jobs["resnet18"]
    for _ in range(5):
        job.shard_stats.record(0.001)  # latency evidence enabling hedges
    original = f.scheduler.next_shard("resnet18")
    hedge = f.scheduler.next_shard("resnet18")
    offset = original[1]
    assert hedge[1] == offset and job.outstanding[offset] == {original[0], hedge[0]}

    # The ORIGINAL fails: the hedge stays tracked, nothing is requeued yet.
    f.scheduler._record_failure(job, offset, original[0], original[3])
    assert job.outstanding[offset] == {hedge[0]}
    assert not job.retry_q
    # Idle dispatchers may now back up the surviving copy again — but never
    # on the member that already failed it.
    rehedge = f.scheduler.next_shard("resnet18")
    assert rehedge is not None and rehedge[1] == offset
    assert rehedge[0] not in {original[0], hedge[0]}

    # Everything in flight fails -> ONE requeue excluding all failed members.
    f.scheduler._record_failure(job, offset, hedge[0], hedge[3])
    assert not job.retry_q
    f.scheduler._record_failure(job, offset, rehedge[0], rehedge[3])
    assert len(job.retry_q) == 1
    requeued_offset, excluded = job.retry_q[0]
    assert requeued_offset == offset
    assert {original[0], hedge[0], rehedge[0]} <= excluded


def test_hedging_disabled_reserves_nothing_extra():
    f = Fixture(n_members=4, n_queries=16, shard=16)
    f.scheduler.hedge_tail = False
    f.scheduler._start({})
    f.scheduler.jobs["resnet18"].shard_stats.record(0.001)
    assert f.scheduler.next_shard("resnet18") is not None
    assert f.scheduler.next_shard("resnet18") is None  # no hedge branch


def test_hedging_waits_for_latency_evidence():
    """Without any observed shard latency — or before the in-flight copy is
    actually slow — idle dispatchers must NOT duplicate work."""
    f = Fixture(n_members=4, n_queries=16, shard=16)
    f.scheduler._start({})
    job = f.scheduler.jobs["resnet18"]
    assert f.scheduler.next_shard("resnet18") is not None
    # No latency evidence at all: no hedge.
    assert f.scheduler.next_shard("resnet18") is None
    assert f.scheduler.has_dispatchable() in (True, False)  # must not crash
    # Evidence of a LONG median: the in-flight copy is not yet slow.
    for _ in range(5):
        job.shard_stats.record(100.0)
    assert f.scheduler.next_shard("resnet18") is None


def test_chip_weighted_placement():
    """A 4-chip host draws ~4x the shards of 1-chip hosts (north star:
    ICI-local placement proportional to per-host chip topology)."""
    net = SimRpcNetwork()
    live = ["big", "small0", "small1"]
    served = {m: 0 for m in live}

    def backend_for(m):
        def fn(synsets):
            served[m] += 1
            return echo_backend(synsets)

        return fn

    for m in live:
        net.serve(m, PredictWorker({"j": backend_for(m)}).methods())
    weights = {"big": 4, "small0": 1, "small1": 1}
    sched = JobScheduler(
        net.client("L"),
        lambda: list(live),
        jobs={"j": make_workload(24 * 8)},
        shard_size=8,
        member_weight=lambda addr: weights[addr],
    )
    sched.is_leading = True
    sched._start({})
    sched.run_to_completion()
    job = sched.jobs["j"]
    assert job.finished == 24 * 8
    assert served["big"] == 16 and served["small0"] == 4 and served["small1"] == 4
    # Per-member latency appears in the report.
    rep = job.report()
    assert set(rep["member_latency"]) == set(live)
    assert rep["member_latency"]["big"]["count"] == 16


# ---------------------------------------------------------------------------
# gang scheduling over a registered mesh group
# ---------------------------------------------------------------------------


class GangEcho:
    """Fake gang-capable backend: answers its rank's slice with the class
    encoded in the synset id, and records every (rank, world, n) call."""

    def __init__(self, log):
        self.log = log

    def __call__(self, synsets):
        raise AssertionError("gang job must never take the per-member path")

    def predict_gang(self, synsets, rank, world):
        from dmlc_tpu.scheduler.worker import gang_slice

        self.log.append((rank, world, len(synsets)))
        start, stop = gang_slice(len(synsets), rank, world)
        return [int(s[1:]) for s in synsets[start:stop]]


def _gang_fixture(n_queries=40, shard=8):
    net = SimRpcNetwork()
    live = ["m0", "m1"]
    calls = {m: [] for m in live}
    for m in live:
        net.serve(m, PredictWorker({"resnet18": GangEcho(calls[m])}).methods())
    sched = JobScheduler(
        net.client("L"),
        lambda: list(live),
        jobs={"resnet18": make_workload(n_queries)},
        shard_size=shard,
        mesh_group=lambda: {"m0": 0, "m1": 1},
    )
    sched.is_leading = True
    net.serve("L", sched.methods())
    return net, sched, calls


def test_gang_stale_assignment_not_dispatchable():
    """ADVICE r3: while a mesh group is registered but the job's assignment
    does not match it yet (stale, pre-assign), dispatch_once is a no-op —
    has_dispatchable must say False so dispatcher threads sleep instead of
    busy-spinning; once the assignment matches, work counts again."""
    net, sched, calls = _gang_fixture(n_queries=40, shard=8)
    sched._start({})
    # Pre-assign: job started, mesh registered, no assignment yet.
    assert sched.jobs["resnet18"].running
    sched.jobs["resnet18"].assigned = ["m0"]  # stale: not the mesh group
    assert not sched.has_dispatchable()
    assert sched.dispatch_once("resnet18") == 0
    sched.assign_once()  # reconciles assignment to the mesh group
    assert sched.has_dispatchable()
    sched.run_to_completion()
    assert sched.jobs["resnet18"].finished == 40
    assert not sched.has_dispatchable()


def test_gang_dispatch_collective_shards_exactly_once():
    """A job whose assigned members are exactly the registered mesh group
    dispatches every shard to ALL of them (one collective execution per
    shard), reassembles rank-ordered slices, counts each query once, and
    reports the gang in the jobs report."""
    net, sched, calls = _gang_fixture(n_queries=40, shard=8)
    sched._start({})
    sched.assign_once()
    sched.run_to_completion()
    job = sched.jobs["resnet18"]
    assert job.finished == 40 and job.correct == 40  # slices reassembled in order
    rep = job.report()
    assert rep["gang_shards"] == 5  # every shard served collectively
    # Every shard reached BOTH processes with the full synset list.
    assert len(calls["m0"]) == 5 and len(calls["m1"]) == 5
    assert all(c == (0, 2, 8) for c in calls["m0"])
    assert all(c == (1, 2, 8) for c in calls["m1"])


def test_gang_member_failure_requeues_whole_shard():
    """All-or-nothing: one process failing fails the collective shard; it
    requeues whole and completes once the fleet is healthy again — no
    partial credit, no double count."""
    net, sched, calls = _gang_fixture(n_queries=16, shard=8)
    sched._start({})
    sched.assign_once()
    net.crash("m1")
    assert sched.dispatch_once("resnet18") == 0  # gang fails, shard requeued
    assert sched.jobs["resnet18"].retry_q
    net.restart("m1")
    sched.run_to_completion()
    job = sched.jobs["resnet18"]
    assert job.finished == 16 and job.correct == 16
    assert job.report()["gang_shards"] == 2  # the retried shard counted once


def test_gang_falls_back_to_member_dispatch_while_mesh_unregistered():
    """mesh_group -> None (mesh not fully registered / not configured):
    ordinary per-member dispatch through __call__ backends."""
    net = SimRpcNetwork()
    live = ["m0", "m1", "m2"]
    for m in live:
        net.serve(
            m,
            PredictWorker(
                {"resnet18": lambda synsets: [int(s[1:]) for s in synsets]}
            ).methods(),
        )
    sched = JobScheduler(
        net.client("L"),
        lambda: list(live),
        jobs={"resnet18": make_workload(24)},
        shard_size=8,
        mesh_group=lambda: None,  # registration incomplete
    )
    sched.is_leading = True
    sched._start({})
    sched.assign_once()
    sched.run_to_completion()
    job = sched.jobs["resnet18"]
    assert job.finished == 24 and job.correct == 24
    assert job.report()["gang_shards"] == 0


def test_registered_mesh_group_owns_assignment_and_never_solo_dispatches():
    """While a mesh group is registered, jobs are assigned the WHOLE group
    (even with extra non-mesh members active) and shards only ever go out
    as collectives — a per-member job.predict against a global-mesh backend
    would fail on every member forever (the round-3 review's livelock)."""
    net = SimRpcNetwork()
    live = ["m0", "m1", "m2"]  # m2 active but outside the mesh
    calls = {m: [] for m in live}
    for m in live:
        net.serve(m, PredictWorker({"resnet18": GangEcho(calls[m])}).methods())
    sched = JobScheduler(
        net.client("L"),
        lambda: list(live),
        jobs={"resnet18": make_workload(24)},
        shard_size=8,
        mesh_group=lambda: {"m0": 0, "m1": 1},
    )
    sched.is_leading = True
    sched._start({})
    # Force a stale assignment (as if assigned before mesh registration):
    # dispatch must WAIT for the next assign pass, not solo-dispatch
    # (GangEcho.__call__ raises if the per-member path is ever taken).
    sched.jobs["resnet18"].assigned = ["m0", "m2"]
    assert sched.dispatch_once("resnet18") == 0
    sched.assign_once()
    assert sched.jobs["resnet18"].assigned == ["m0", "m1"]  # the group, not m2
    sched.run_to_completion()
    job = sched.jobs["resnet18"]
    assert job.finished == 24 and job.correct == 24
    assert job.report()["gang_shards"] == 3
    assert calls["m2"] == []


def test_gang_config_error_trips_breaker_and_surfaces():
    """A method-level refusal (config incompatibility) fails identically on
    every retry: after the cap the job STOPS with the error in the report
    instead of hot-spinning; `predict` re-arms it. Unreachability (tested
    in test_gang_member_failure_requeues_whole_shard) never trips it."""

    class Refuses:
        def __call__(self, synsets):
            raise AssertionError("per-member path must not be used")

        def predict_gang(self, synsets, rank, world):
            raise ValueError("batch 64 not divisible by 5 processes")

    net = SimRpcNetwork()
    live = ["m0", "m1"]
    for m in live:
        net.serve(m, PredictWorker({"resnet18": Refuses()}).methods())
    sched = JobScheduler(
        net.client("L"),
        lambda: list(live),
        jobs={"resnet18": make_workload(16)},
        shard_size=8,
        mesh_group=lambda: {"m0": 0, "m1": 1},
    )
    sched.is_leading = True
    sched._start({})
    for _ in range(sched.gang_max_consec_failures + 2):
        sched.dispatch_once("resnet18")
    job = sched.jobs["resnet18"]
    assert not job.running
    assert "not divisible" in job.report()["last_error"]
    assert job.finished == 0
    # Operator fixes the config and retries: predict re-arms the job.
    sched._start({})
    assert job.running and job.report()["last_error"] == ""


class GangStagingEcho(GangEcho):
    """GangEcho + decode staging: records prefetch decodes and answers
    predict from them, like EngineBackend's staging contract."""

    def __init__(self, log):
        super().__init__(log)
        self.decodes = []

    def decode_gang(self, synsets, rank, world):
        self.decodes.append((rank, world, len(synsets)))
        return True


def test_gang_decode_prefetch_counted_per_rank():
    """Every gang shard gets a decode-prefetch phase on every rank before
    its collective; the leader counts staged ranks in the job report."""
    net, sched, calls = _gang_fixture(n_queries=40, shard=8)
    # Re-wire with staging-capable backends so decodes are observable.
    workers = {}
    for m in ("m0", "m1"):
        w = GangStagingEcho([])
        workers[m] = w
        net.serve(m, PredictWorker({"resnet18": w}).methods())
    sched._start({})
    sched.assign_once()
    sched.run_to_completion()
    job = sched.jobs["resnet18"]
    assert job.finished == 40 and job.gang_shards == 5
    assert job.report()["gang_staged_ranks"] == 10  # 5 shards x 2 ranks
    assert len(workers["m0"].decodes) == 5 and len(workers["m1"].decodes) == 5


def test_gang_decode_overlaps_collective_execution():
    """VERDICT r3 weak #5: decode of shard N+1 must run WHILE shard N's
    collective executes. Rank 0's collective blocks until it observes a
    prefetch decode for a DIFFERENT shard — it can only be released if the
    decode phase runs outside the gang serialization. A fully serialized
    implementation (decode inside the gang lock, or no prefetch at all)
    times out here."""
    import threading
    import time as _time

    net, sched, _ = _gang_fixture(n_queries=16, shard=8)
    state_lock = threading.Lock()
    decodes: set = set()
    overlap_proven = []

    class OverlapWitness(GangEcho):
        def __init__(self, blocking):
            super().__init__([])
            self.blocking = blocking

        def decode_gang(self, synsets, rank, world):
            with state_lock:
                decodes.add(tuple(synsets))
            return True

        def predict_gang(self, synsets, rank, world):
            if self.blocking:
                deadline = _time.time() + 5
                while _time.time() < deadline:
                    with state_lock:
                        if any(d != tuple(synsets) for d in decodes):
                            overlap_proven.append(True)
                            break
                    _time.sleep(0.005)
            return super().predict_gang(synsets, rank, world)

    net.serve("m0", PredictWorker({"resnet18": OverlapWitness(blocking=True)}).methods())
    net.serve("m1", PredictWorker({"resnet18": OverlapWitness(blocking=False)}).methods())
    sched._start({})
    sched.assign_once()
    threads = [
        threading.Thread(target=sched.dispatch_once, args=("resnet18",))
        for _ in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert overlap_proven, "no decode for another shard arrived during execution"
    sched.run_to_completion()
    assert sched.jobs["resnet18"].finished == 16
