"""Head-based adaptive trace sampling (docs/OBSERVABILITY.md §7).

- The sampled bit is the third element of the ``t`` frame field; legacy
  2-element frames read as sampled (old peers keep tracing).
- An unsampled root costs ZERO raw span storage while aggregates — the
  profiler's food — stay exact for every request.
- Spans that raise are force-recorded regardless of the bit: error and
  deadline-exceeded requests always survive into the merged timeline.
- The adaptive controller shrinks the effective rate toward a spans/s
  budget and regrows it when load falls.
- ``obs.trace_ctl`` pushes rate/budget/force knobs fleet-wide.
"""

from __future__ import annotations

import pytest

from dmlc_tpu.cluster import tracectx
from dmlc_tpu.cluster.rpc import RpcError, SimRpcNetwork
from dmlc_tpu.utils.tracing import Tracer


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class TestSampledBitWire:
    def test_wire_carries_sampled_bit(self):
        assert tracectx.to_wire(tracectx.child(sampled=True))[2] == 1
        assert tracectx.to_wire(tracectx.child(sampled=False))[2] == 0

    def test_from_wire_round_trip(self):
        ctx = tracectx.child(sampled=False)
        back = tracectx.from_wire(tracectx.to_wire(ctx))
        assert back.trace_id == ctx.trace_id
        assert back.sampled is False

    def test_legacy_two_element_frame_reads_sampled(self):
        # Old peers send [trace, span]: absent bit means "keep tracing",
        # so a mixed-version fleet degrades toward more data, not less.
        back = tracectx.from_wire(["t1", "s1"])
        assert back.sampled is True

    def test_children_inherit_the_root_decision(self):
        root = tracectx.child(sampled=False)
        with tracectx.bind(root):
            child = tracectx.child()
        assert child.sampled is False
        assert child.trace_id == root.trace_id

    def test_bit_rides_the_sim_fabric(self):
        net = SimRpcNetwork()
        net.serve("peer:1", {"ping": lambda p: {}})
        root = tracectx.child(sampled=False)
        with tracectx.bind(root):
            net.client("me:1").call("peer:1", "ping", {})
        assert net.frames[-1]["t"][2] == 0


class TestHeadSampling:
    def _tracer(self, rate: float, **kw) -> Tracer:
        t = Tracer()
        t.enabled = True
        t.set_sampling(rate=rate, **kw)
        return t

    def test_unsampled_roots_store_nothing_but_aggregate_exactly(self):
        t = self._tracer(0.0)
        for _ in range(10):
            with t.span("scheduler/dispatch"):
                pass
        assert t.events_wire() == []
        assert t.summary()["scheduler/dispatch"]["count"] == 10.0
        s = t.sampling_summary()
        assert s["unsampled"] == 10 and s["sampled"] == 0

    def test_rate_one_keeps_everything(self):
        t = self._tracer(1.0)
        for _ in range(5):
            with t.span("root"):
                pass
        assert len(t.events_wire()) == 5
        assert t.sampling_summary()["sampled"] == 5

    def test_error_spans_force_recorded_at_rate_zero(self):
        t = self._tracer(0.0)
        with pytest.raises(RpcError):
            with t.span("loadgen/request"):
                with t.span("rpc/job.predict"):
                    raise RpcError("deadline: too slow")
        # The WHOLE local chain of the failing request survives: every
        # enclosing span saw the same exception on unwind.
        events = t.events_wire()
        assert {e["name"] for e in events} == {"loadgen/request", "rpc/job.predict"}
        assert all(e["attrs"]["error"] == "RpcError" for e in events)
        assert all(e["attrs"]["forced"] == "error" for e in events)
        assert t.sampling_summary()["forced_records"] == 2

    def test_ok_spans_of_unsampled_trace_stay_dropped(self):
        t = self._tracer(0.0)
        with pytest.raises(ValueError):
            with t.span("root"):
                with t.span("ok_child"):
                    pass  # exits cleanly before the failure
                raise ValueError("later failure")
        names = {e["name"] for e in t.events_wire()}
        assert names == {"root"}  # the clean child was already dropped

    def test_forced_window_samples_everything(self):
        clock = FakeClock()
        t = self._tracer(0.0, clock=clock)
        t.force_sampling(10.0)
        with t.span("root"):
            pass
        assert len(t.events_wire()) == 1
        clock.t = 11.0  # window expired
        with t.span("root"):
            pass
        assert len(t.events_wire()) == 1

    def test_record_honors_the_ambient_bit(self):
        t = self._tracer(1.0)
        with tracectx.bind(tracectx.child(sampled=False)):
            t.record("device/forward", 0.005)
        assert t.events_wire() == []
        assert t.summary()["device/forward"]["count"] == 1.0


class TestAdaptiveController:
    def test_rate_shrinks_proportionally_over_budget(self):
        clock = FakeClock()
        t = Tracer()
        t.enabled = True
        t.set_sampling(rate=1.0, spans_per_s=10.0, clock=clock)
        t.adapt_window_s = 1.0
        # 100 spans/s against a 10/s budget for two windows.
        for _ in range(3):
            for _ in range(100):
                with t.span("root"):
                    pass
            clock.t += 1.0
        s = t.sampling_summary()
        assert s["effective_rate"] < 0.5  # cut hard, not by baby steps
        assert s["effective_rate"] >= Tracer.MIN_SAMPLE_RATE

    def test_rate_regrows_when_load_falls(self):
        clock = FakeClock()
        t = Tracer()
        t.enabled = True
        t.set_sampling(rate=1.0, spans_per_s=10.0, clock=clock)
        t.adapt_window_s = 1.0
        for _ in range(3):
            for _ in range(100):
                with t.span("root"):
                    pass
            clock.t += 1.0
        squeezed = t.sampling_summary()["effective_rate"]
        for _ in range(20):  # near-idle windows
            with t.span("root"):
                pass
            clock.t += 1.0
        regrown = t.sampling_summary()["effective_rate"]
        assert regrown > squeezed
        assert regrown <= 1.0

    def test_budget_zero_disables_adaptation(self):
        clock = FakeClock()
        t = Tracer()
        t.enabled = True
        t.set_sampling(rate=0.5, spans_per_s=0.0, clock=clock)
        for _ in range(50):
            with t.span("root"):
                pass
            clock.t += 0.1
        assert t.sampling_summary()["effective_rate"] == 0.5


class TestTraceCtlKnobs:
    def _serve_obs(self):
        from dmlc_tpu.cluster.observe import ObsService
        from dmlc_tpu.utils.metrics import Registry
        from dmlc_tpu.utils.tracing import tracer

        net = SimRpcNetwork()
        net.serve("n1:1", ObsService(Registry(), lane="n1:1").methods())
        return net, tracer

    def test_sampling_knobs_pushed_over_the_wire(self):
        net, tracer = self._serve_obs()
        prev = tracer.enabled
        try:
            reply = net.client("cli:0").call(
                "n1:1", "obs.trace_ctl",
                {"enable": True, "sample_rate": 0.25, "spans_per_s": 50.0},
                timeout=2.0,
            )
            assert reply["enabled"] is True
            assert reply["sampling"]["base_rate"] == 0.25
            assert reply["sampling"]["spans_per_s_budget"] == 50.0
            forced = net.client("cli:0").call(
                "n1:1", "obs.trace_ctl", {"force_sample_s": 5.0}, timeout=2.0
            )
            assert forced["sampling"]["base_rate"] == 0.25
        finally:
            tracer.enabled = prev
            tracer.set_sampling(rate=1.0, spans_per_s=0.0)
            tracer.reset()

    def test_metrics_reply_surfaces_sampling_state(self):
        net, tracer = self._serve_obs()
        try:
            reply = net.client("cli:0").call(
                "n1:1", "obs.metrics", {}, timeout=2.0
            )
            assert {"sampled", "unsampled", "effective_rate",
                    "observed_rate"} <= set(reply["sampling"])
        finally:
            tracer.set_sampling(rate=1.0, spans_per_s=0.0)
            tracer.reset()
