"""Checkpointed training in anger: dp x tp steps with replicated SDFS
checkpoints, leader killed mid-training, training resumed from the
checkpoint served by the promoted standby (VERDICT r1 item 9)."""

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_tpu.cluster.failover import StandbyLeader
from dmlc_tpu.cluster.rpc import SimRpcNetwork
from dmlc_tpu.cluster.sdfs import MemberStore, SdfsClient, SdfsLeader, SdfsMember
from dmlc_tpu.models.vit import ViT
from dmlc_tpu.parallel import mesh as mesh_lib
from dmlc_tpu.parallel import train as train_lib
from dmlc_tpu.parallel.trainer import TrainingDriver
from dmlc_tpu.scheduler.jobs import JobScheduler
from dmlc_tpu.utils.checkpoint import SdfsCheckpointer


def fresh_state():
    model = ViT(
        num_classes=8, patch_size=8, hidden_size=32, num_layers=1,
        num_heads=2, mlp_dim=64, dtype=jnp.float32,
    )
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3), jnp.float32), train=False
    )
    return train_lib.create_train_state(model, variables, train_lib.default_optimizer(1e-3))


def data_fn(step: int):
    rng = np.random.RandomState(step)
    images = rng.randn(8, 16, 16, 3).astype(np.float32)
    labels = rng.randint(0, 8, size=(8,))
    return images, labels


def host_tree(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)


def test_driver_checkpoints_and_survives_leader_kill(tmp_path):
    net = SimRpcNetwork()
    live = ["m0", "m1", "m2"]
    stores = {}
    for m in live:
        stores[m] = MemberStore(tmp_path / m)
        net.serve(m, SdfsMember(stores[m], net.client(m)).methods())

    # Primary (L0, actively leading) + standby (L1) with directory sync.
    primary_sdfs = SdfsLeader(net.client("L0"), lambda: list(live), replication_factor=2)
    primary_jobs = JobScheduler(net.client("L0"), lambda: list(live), jobs={})
    primary_jobs.is_leading = True
    net.serve("L0", {**primary_sdfs.methods(), **primary_jobs.methods()})
    standby_sdfs = SdfsLeader(
        net.client("L1"), lambda: list(live), replication_factor=2, is_leading=False
    )
    standby_jobs = JobScheduler(net.client("L1"), lambda: list(live), jobs={})
    net.serve("L1", {**standby_sdfs.methods(), **standby_jobs.methods()})
    monitor = StandbyLeader(
        net.client("L1"), "L1", ["L0", "L1"], standby_jobs, sdfs_leader=standby_sdfs
    )

    mesh = mesh_lib.make_mesh({"dp": 4, "tp": 2})

    # --- phase 1: train with periodic replicated checkpoints -------------
    client0 = SdfsClient(net.client("m0"), "L0", stores["m0"], "m0")
    driver1 = TrainingDriver(
        mesh,
        fresh_state(),
        data_fn,
        checkpointer=SdfsCheckpointer(client0),
        checkpoint_every=2,
    )
    assert driver1.start_step == 0  # nothing to restore yet
    driver1.run(3)  # checkpoints at step 2 and (final) step 3
    assert [h["step"] for h in driver1.history] == [1, 2, 3]
    params_after_3 = host_tree(driver1.state.params)

    monitor.step()  # standby mirrors the directory (checkpoint versions)
    assert standby_sdfs.state.latest_version("checkpoints/train_state") == 2

    # --- leader dies mid-training ---------------------------------------
    net.crash("L0")
    monitor.step()
    assert monitor.is_leader  # promoted; SDFS writes now accepted at L1

    # --- phase 2: a NEW driver on the new leader restores + continues ----
    client1 = SdfsClient(net.client("m1"), "L1", stores["m1"], "m1")
    driver2 = TrainingDriver(
        mesh,
        fresh_state(),
        data_fn,
        checkpointer=SdfsCheckpointer(client1),
        checkpoint_every=2,
    )
    assert driver2.start_step == 3  # restored from the replicated checkpoint
    restored_params = host_tree(driver2.state.params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
        restored_params,
        params_after_3,
    )

    last = driver2.run(2)  # steps 4, 5 — checkpointed through the NEW leader
    assert [h["step"] for h in driver2.history] == [4, 5]
    assert int(driver2.state.step) == 5
    assert np.isfinite(last["loss"])
    # The post-failover checkpoint is a fresh version in the same file.
    assert standby_sdfs.state.latest_version("checkpoints/train_state") >= 3


def test_driver_fresh_run_without_checkpointer():
    mesh = mesh_lib.make_mesh({"dp": 8})
    driver = TrainingDriver(mesh, fresh_state(), data_fn, checkpointer=None)
    first = driver.run(2)
    assert int(driver.state.step) == 2
    assert np.isfinite(first["loss"]) and 0.0 <= first["accuracy"] <= 1.0
