"""Checkpointed training in anger: dp x tp steps with replicated SDFS
checkpoints, leader killed mid-training, training resumed from the
checkpoint served by the promoted standby (VERDICT r1 item 9).

Runs under a subprocess isolation wrapper (same pattern as the pjrt probe
and multihost tests): the XLA CPU client occasionally aborts the whole
interpreter when this module's 4x2 mesh work lands in a process that
already ran other backend-touching suites, and an abort in-process takes
the entire tier-1 collector down with it. Each wrapper re-runs its test in
a FRESH interpreter (clean backend state — which is also what makes the
abort stop reproducing) and retries once if the child dies on a signal.
Tracking note: docs/OPERATIONS.md §Known test isolation quirks.
"""

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The wrapper sets this before re-invoking pytest on this file in a child
# process; the child defines the real tests, the parent defines wrappers
# under the SAME names so node ids select the right layer in each mode.
_INNER = os.environ.get("DMLC_TRAIN_DRIVER_INNER") == "1"


if _INNER:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dmlc_tpu.cluster.failover import StandbyLeader
    from dmlc_tpu.cluster.rpc import SimRpcNetwork
    from dmlc_tpu.cluster.sdfs import MemberStore, SdfsClient, SdfsLeader, SdfsMember
    from dmlc_tpu.models.vit import ViT
    from dmlc_tpu.parallel import mesh as mesh_lib
    from dmlc_tpu.parallel import train as train_lib
    from dmlc_tpu.parallel.trainer import TrainingDriver
    from dmlc_tpu.scheduler.jobs import JobScheduler
    from dmlc_tpu.utils.checkpoint import SdfsCheckpointer

    def fresh_state():
        model = ViT(
            num_classes=8, patch_size=8, hidden_size=32, num_layers=1,
            num_heads=2, mlp_dim=64, dtype=jnp.float32,
        )
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3), jnp.float32), train=False
        )
        return train_lib.create_train_state(
            model, variables, train_lib.default_optimizer(1e-3)
        )

    def data_fn(step: int):
        rng = np.random.RandomState(step)
        images = rng.randn(8, 16, 16, 3).astype(np.float32)
        labels = rng.randint(0, 8, size=(8,))
        return images, labels

    def host_tree(tree):
        return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)

    def test_driver_checkpoints_and_survives_leader_kill(tmp_path):
        net = SimRpcNetwork()
        live = ["m0", "m1", "m2"]
        stores = {}
        for m in live:
            stores[m] = MemberStore(tmp_path / m)
            net.serve(m, SdfsMember(stores[m], net.client(m)).methods())

        # Primary (L0, actively leading) + standby (L1) with directory sync.
        primary_sdfs = SdfsLeader(
            net.client("L0"), lambda: list(live), replication_factor=2
        )
        primary_jobs = JobScheduler(net.client("L0"), lambda: list(live), jobs={})
        primary_jobs.is_leading = True
        net.serve("L0", {**primary_sdfs.methods(), **primary_jobs.methods()})
        standby_sdfs = SdfsLeader(
            net.client("L1"), lambda: list(live), replication_factor=2,
            is_leading=False,
        )
        standby_jobs = JobScheduler(net.client("L1"), lambda: list(live), jobs={})
        net.serve("L1", {**standby_sdfs.methods(), **standby_jobs.methods()})
        monitor = StandbyLeader(
            net.client("L1"), "L1", ["L0", "L1"], standby_jobs,
            sdfs_leader=standby_sdfs,
        )

        mesh = mesh_lib.make_mesh({"dp": 4, "tp": 2})

        # --- phase 1: train with periodic replicated checkpoints ---------
        client0 = SdfsClient(net.client("m0"), "L0", stores["m0"], "m0")
        driver1 = TrainingDriver(
            mesh,
            fresh_state(),
            data_fn,
            checkpointer=SdfsCheckpointer(client0),
            checkpoint_every=2,
        )
        assert driver1.start_step == 0  # nothing to restore yet
        driver1.run(3)  # checkpoints at step 2 and (final) step 3
        assert [h["step"] for h in driver1.history] == [1, 2, 3]
        params_after_3 = host_tree(driver1.state.params)

        monitor.step()  # standby mirrors the directory (checkpoint versions)
        assert standby_sdfs.state.latest_version("checkpoints/train_state") == 2

        # --- leader dies mid-training ------------------------------------
        net.crash("L0")
        monitor.step()
        assert monitor.is_leader  # promoted; SDFS writes now accepted at L1

        # --- phase 2: a NEW driver on the new leader restores + continues
        client1 = SdfsClient(net.client("m1"), "L1", stores["m1"], "m1")
        driver2 = TrainingDriver(
            mesh,
            fresh_state(),
            data_fn,
            checkpointer=SdfsCheckpointer(client1),
            checkpoint_every=2,
        )
        assert driver2.start_step == 3  # restored from the replicated checkpoint
        restored_params = host_tree(driver2.state.params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6),
            restored_params,
            params_after_3,
        )

        last = driver2.run(2)  # steps 4, 5 — checkpointed through the NEW leader
        assert [h["step"] for h in driver2.history] == [4, 5]
        assert int(driver2.state.step) == 5
        assert np.isfinite(last["loss"])
        # The post-failover checkpoint is a fresh version in the same file.
        assert standby_sdfs.state.latest_version("checkpoints/train_state") >= 3

    def test_driver_fresh_run_without_checkpointer():
        mesh = mesh_lib.make_mesh({"dp": 8})
        driver = TrainingDriver(mesh, fresh_state(), data_fn, checkpointer=None)
        first = driver.run(2)
        assert int(driver.state.step) == 2
        assert np.isfinite(first["loss"]) and 0.0 <= first["accuracy"] <= 1.0


else:

    def _run_isolated(test_name: str) -> None:
        env = dict(os.environ)
        env["DMLC_TRAIN_DRIVER_INNER"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [
            sys.executable, "-m", "pytest",
            f"{os.path.abspath(__file__)}::{test_name}",
            "-q", "-p", "no:cacheprovider",
        ]
        for attempt in (1, 2):
            proc = subprocess.run(
                cmd, capture_output=True, text=True, env=env,
                cwd=REPO_ROOT, timeout=600,
            )
            if proc.returncode == 0:
                return
            if proc.returncode < 0 and attempt == 1:
                continue  # child died on a signal: one fresh-interpreter retry
            raise AssertionError(
                f"{test_name} failed in isolation (rc={proc.returncode}):\n"
                f"{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}"
            )

    def test_driver_checkpoints_and_survives_leader_kill():
        _run_isolated("test_driver_checkpoints_and_survives_leader_kill")

    def test_driver_fresh_run_without_checkpointer():
        _run_isolated("test_driver_fresh_run_without_checkpointer")
