"""Continuous-batching slot scheduler + RPC streaming (ISSUE 7 pins).

- the continuous-batching pin: N >= 8 concurrent generations with mixed
  prompt/output lengths complete CORRECTLY (token-identical to isolated
  runs) while sharing one running batch, slots observed joining/leaving
  between steps (flight-recorder step stamps), and measured tok/s >= 2x
  the sequential one-request-at-a-time baseline on the same model;
- typed Overloaded sheds at a full slot table and an exhausted page pool;
- deadline-carrying: expired budgets exit slots with a ``deadline:`` error;
- mid-decode page exhaustion evicts with a typed Overloaded error and a
  ``slot_evict`` flight event;
- seeded join/leave soak over the sim fabric with EXACTLY-ONCE token
  delivery through the chunk-poll protocol (replayed polls are idempotent,
  ack truncation is permanent). DMLC_CHAOS_SEED offsets the soak's seeds
  (the CI chaos matrix runs this file across its seed legs).
"""

import os
import threading
import time

import numpy as np
import pytest

pytest.importorskip("jax")
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dmlc_tpu.cluster.deadline import Deadline  # noqa: E402
from dmlc_tpu.cluster.flight import FlightRecorder  # noqa: E402
from dmlc_tpu.cluster.rpc import (  # noqa: E402
    DeadlineExceeded,
    Overloaded,
    SimRpcNetwork,
)
from dmlc_tpu.generate.engine import GenerationEngine  # noqa: E402
from dmlc_tpu.generate.slots import SlotScheduler  # noqa: E402
from dmlc_tpu.generate.worker import (  # noqa: E402
    GenerateWorker,
    GenerationBackend,
    generate,
)
from dmlc_tpu.models.registry import get_model  # noqa: E402
from dmlc_tpu.utils.metrics import Counters  # noqa: E402

SEED_BASE = int(os.environ.get("DMLC_CHAOS_SEED", "0"))
SPEC = get_model("lm_small")
VOCAB = SPEC.num_outputs


@pytest.fixture(scope="module")
def variables():
    _, v = SPEC.init_params(jax.random.PRNGKey(0), dtype=jnp.float32)
    return v


def make_engine(variables, **kw):
    kw.setdefault("max_slots", 8)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 128)
    kw.setdefault("max_prefill", 16)
    return GenerationEngine("lm_small", variables=variables, **kw)


def reference_tokens(variables, prompt, n_new):
    """Isolated greedy reference for one request."""
    eng = make_engine(variables, max_slots=1)
    toks = [eng.join(0, np.asarray(prompt, np.int32))]
    for _ in range(n_new - 1):
        eng.ensure_capacity(0)
        toks.append(int(eng.step()[0]))
    return toks


class TestContinuousBatchingPin:
    def test_concurrent_correct_and_2x_over_sequential(self, variables):
        rng = np.random.default_rng(100 + SEED_BASE)
        n_req = 10  # > max_slots so late joins enter a mid-decode batch
        reqs = [
            (
                rng.integers(0, VOCAB, size=int(rng.integers(3, 12))).tolist(),
                int(rng.integers(6, 14)),
            )
            for _ in range(n_req)
        ]
        refs = [reference_tokens(variables, p, n) for p, n in reqs]

        def run_phase(concurrent: bool):
            flight = FlightRecorder()
            eng = make_engine(variables)
            sched = SlotScheduler(eng, max_waiting=n_req, flight=flight)
            # Warm the compile caches outside the timed window.
            sched.submit([1, 2, 3], max_new_tokens=2).result(timeout=30)
            t0 = time.perf_counter()
            outs = []
            if concurrent:
                streams = [
                    sched.submit(p, max_new_tokens=n) for p, n in reqs
                ]
                outs = [s.result(timeout=60) for s in streams]
            else:
                for p, n in reqs:
                    outs.append(
                        sched.submit(p, max_new_tokens=n).result(timeout=60)
                    )
            dt = time.perf_counter() - t0
            steps = eng.steps
            tok_total = sum(len(o) for o in outs)
            sched.stop()
            return outs, dt, steps, tok_total, flight

        outs_c, dt_c, steps_c, toks_c, flight = run_phase(concurrent=True)
        outs_s, dt_s, steps_s, toks_s, _ = run_phase(concurrent=False)

        # Correctness: every request's tokens match its isolated reference
        # despite sharing the batch with strangers — in BOTH phases.
        assert outs_c == refs
        assert outs_s == refs

        # Slots join AND leave between steps of one running batch: admits
        # stamped at step > 0 (joined mid-decode) and exits at distinct
        # steps while the batch kept running.
        events = flight.events()
        admits = [e for e in events if e["kind"] == "slot_admit"]
        exits = [e for e in events if e["kind"] == "slot_exit"]
        assert any(e["step"] > 0 for e in admits), "no slot joined mid-batch"
        exit_steps = {e["step"] for e in exits}
        assert len(exit_steps) > 1, "all slots exited at the same step"

        # Step-count economics: sequential pays ~sum(tokens) steps, the
        # shared batch ~max(tokens) per generation wave.
        assert steps_s >= 2 * steps_c, (steps_s, steps_c)
        # The measured pin: continuous batching >= 2x sequential tok/s.
        tok_s_c = toks_c / dt_c
        tok_s_s = toks_s / dt_s
        assert tok_s_c >= 2.0 * tok_s_s, (
            f"continuous {tok_s_c:.1f} tok/s vs sequential {tok_s_s:.1f}"
        )


class TestOverloadContract:
    def test_slot_table_full_sheds_typed(self, variables):
        eng = make_engine(variables, max_slots=2)
        metrics = Counters()
        flight = FlightRecorder()
        sched = SlotScheduler(
            eng, max_waiting=0, metrics=metrics, flight=flight
        )
        try:
            streams = [
                sched.submit([1, 2, 3], max_new_tokens=64) for _ in range(2)
            ]
            with pytest.raises(Overloaded) as e:
                sched.submit([1, 2, 3], max_new_tokens=4)
            assert e.value.retry_after_s is not None
            assert metrics.get("shed") == 1
            assert any(ev["kind"] == "shed" for ev in flight.events())
            for s in streams:
                s.result(timeout=60)
        finally:
            sched.stop()

    def test_page_pool_exhaustion_sheds_typed(self, variables):
        # 8-token pages, 3 usable pages: a 14-token prompt reserves 2, the
        # next one cannot reserve its 2 and must shed with retry-after.
        eng = make_engine(variables, num_pages=4, page_size=8)
        sched = SlotScheduler(eng, max_waiting=8)
        try:
            first = sched.submit(list(range(14)), max_new_tokens=2)
            with pytest.raises(Overloaded, match="page pool"):
                sched.submit(list(range(14)), max_new_tokens=2)
            first.result(timeout=60)
        finally:
            sched.stop()

    def test_mid_decode_exhaustion_evicts_typed(self, variables):
        # 3 usable pages. Slot A: 14-token prompt (2 pages), 10 new tokens
        # (crosses into a 3rd page at length 16). Slot B: 7-token prompt
        # (the 3rd page), crosses its boundary at length 8 — FIRST, with
        # the pool empty: B is evicted with a typed Overloaded while A
        # rides B's recycled page to completion. The deferred-start
        # scheduler makes the admission order deterministic.
        flight = FlightRecorder()
        metrics = Counters()
        eng = make_engine(variables, num_pages=4, page_size=8)
        sched = SlotScheduler(
            eng, max_waiting=8, metrics=metrics, flight=flight, autostart=False
        )
        try:
            a = sched.submit(list(range(14)), max_new_tokens=10)
            b = sched.submit(list(range(7)), max_new_tokens=8)
            sched.start()
            assert len(a.result(timeout=60)) == 10
            with pytest.raises(Overloaded, match="evicted"):
                b.result(timeout=60)
            assert any(e["kind"] == "slot_evict" for e in flight.events())
            assert metrics.get("gen_evictions") == 1
            # Eviction + completion recycled everything: the pool is whole.
            assert eng.pages_free == eng.cache.allocator.pages_total
        finally:
            sched.stop()

    def test_deadline_carried_and_enforced(self, variables):
        eng = make_engine(variables)
        sched = SlotScheduler(eng, max_waiting=8)
        try:
            stream = sched.submit(
                [1, 2, 3], max_new_tokens=200, deadline=Deadline(0.05),
            )
            with pytest.raises(DeadlineExceeded):
                stream.result(timeout=60)
        finally:
            sched.stop()

    def test_submit_validates_against_engine_limits(self, variables):
        eng = make_engine(variables, max_prefill=8)
        sched = SlotScheduler(eng)
        try:
            with pytest.raises(ValueError, match="max_prefill"):
                sched.submit(list(range(9)), max_new_tokens=2)
            with pytest.raises(ValueError, match="max_tokens"):
                sched.submit([1], max_new_tokens=10_000)
            with pytest.raises(ValueError):
                sched.submit([], max_new_tokens=2)
        finally:
            sched.stop()


class TestExactlyOnceStreaming:
    """The chunk-poll protocol over the sim fabric."""

    def _worker(self, variables, **backend_kw):
        backend_kw.setdefault("max_slots", 4)
        backend_kw.setdefault("page_size", 8)
        backend_kw.setdefault("num_pages", 128)
        backend_kw.setdefault("max_prefill", 16)
        backend_kw.setdefault("max_waiting", 64)
        backend = GenerationBackend("lm_small", **backend_kw)
        # Inject the prebuilt engine path: warm by building via _ensure
        # and swapping seed-matched variables for determinism.
        backend.warmup()
        backend.load_variables(variables)
        worker = GenerateWorker({"lm_small": backend})
        net = SimRpcNetwork()
        net.serve("member", worker.methods())
        return backend, worker, net

    def test_poll_replay_is_idempotent(self, variables):
        backend, worker, net = self._worker(variables)
        try:
            cli = net.client("cli")
            reply = cli.call(
                "member", "job.generate",
                {"model": "lm_small", "prompt": [1, 2, 3], "max_new_tokens": 5},
            )
            gid = reply["gen_id"]
            # Wait for completion, then poll twice WITHOUT acking: the
            # replay must return identical chunks.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                r1 = cli.call("member", "job.generate_poll",
                              {"gen_id": gid, "ack": 0})
                if r1["done"]:
                    break
                time.sleep(0.01)
            r2 = cli.call("member", "job.generate_poll", {"gen_id": gid, "ack": 0})
            assert r1["chunks"] == r2["chunks"] and r1["done"]
            # Cumulative ack truncates for good.
            last_seq = r1["chunks"][-1][0]
            r3 = cli.call("member", "job.generate_poll",
                          {"gen_id": gid, "ack": last_seq})
            assert r3["chunks"] == [] and r3["done"] and not r3.get("error")
        finally:
            backend.stop()

    def test_seeded_join_leave_soak_exactly_once(self, variables):
        """Concurrent clients churning through the worker: every request's
        reassembled stream equals its isolated greedy reference, token for
        token — no duplicates, no gaps, no cross-slot bleed."""
        backend, worker, net = self._worker(variables)
        try:
            rng = np.random.default_rng(200 + SEED_BASE)
            reqs = [
                (
                    rng.integers(0, VOCAB, size=int(rng.integers(2, 15))).tolist(),
                    int(rng.integers(1, 10)),
                )
                for _ in range(16)
            ]
            refs = [reference_tokens(variables, p, n) for p, n in reqs]
            results: dict[int, list[int]] = {}
            errors: dict[int, Exception] = {}

            def run(i):
                p, n = reqs[i]
                try:
                    results[i] = generate(
                        net.client(f"cli{i}"), "member", "lm_small", p,
                        max_new_tokens=n, poll_interval_s=0.002,
                    )
                except Exception as e:  # collected and asserted below
                    errors[i] = e

            threads = [
                threading.Thread(target=run, args=(i,)) for i in range(len(reqs))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert results == {i: refs[i] for i in range(len(reqs))}
            # All pages recycled once the fleet of requests drained.
            eng = backend._scheduler.engine
            assert eng.pages_free == eng.cache.allocator.pages_total
            assert eng.jit_cache_sizes() == {"step": 1, "prefill": 1}
        finally:
            backend.stop()

    def test_unknown_model_and_session_are_rpc_errors(self, variables):
        from dmlc_tpu.cluster.rpc import RpcError

        backend, worker, net = self._worker(variables)
        try:
            cli = net.client("cli")
            with pytest.raises(RpcError, match="not served here"):
                cli.call("member", "job.generate",
                         {"model": "nope", "prompt": [1], "max_new_tokens": 1})
            with pytest.raises(RpcError, match="unknown generation"):
                cli.call("member", "job.generate_poll",
                         {"gen_id": "missing", "ack": 0})
        finally:
            backend.stop()


class TestNodeIntegration:
    def test_node_serves_generate_end_to_end(self, tmp_path):
        """A real ClusterNode with generate_models wired: the CLI verb
        streams a generation through the member RPC server, and the
        metric gauges/status surface the new plane."""
        from dmlc_tpu.cli import Cli
        from dmlc_tpu.cluster.localcluster import (
            start_local_cluster,
            stop_local_cluster,
            wait_until,
        )

        nodes = start_local_cluster(
            tmp_path, 1,
            n_leader_candidates=1,
            generate_models=["lm_small"],
            gen_page_size=8,
            gen_num_pages=64,
            gen_max_prefill=16,
            eager_load=False,
        )
        try:
            node = nodes[0]
            wait_until(lambda: node.standby.is_leader, msg="leader promotion")
            reply = node.generate("lm_small", [1, 2, 3], max_new_tokens=5)
            assert len(reply["tokens"]) == 5
            assert all(0 <= t < VOCAB for t in reply["tokens"])
            snap = node.registry.snapshot()
            assert "generate-lm_small_slots_active" in snap["gauges"]
            assert "generate-lm_small_tok_s" in snap["gauges"]
            status = node.status(remote=False)
            assert status["generate"]["models"]["lm_small"]["completions"] == 1
            out = Cli(node).run_command("generate lm_small 1 2 3 --max-new 3")
            assert "3 token(s)" in out
        finally:
            stop_local_cluster(nodes)
